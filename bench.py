"""Benchmark: flagship train-step MFU on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline per BASELINE.md north star: 40% MFU for an @op train step
(the reference publishes no numbers of its own; 0.40 MFU is the target the
TPU build must reach, so vs_baseline = achieved_mfu / 0.40).

Built for a hostile backend (the relayed TPU plugin can hang at init or die
with UNAVAILABLE): the benchmark body runs in a supervised child process
under a hard deadline, gets one retry, and on unrecoverable failure the
supervisor still emits a single parseable JSON line carrying an "error" key
(exit code 0) instead of a stack trace. Progress is staged on stderr so a
hang is attributable to a phase.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ATTEMPT_DEADLINE_S = 560  # per child attempt; first TPU compile alone can take 90 s
ATTEMPTS = 2
PROBE_DEADLINE_S = 125  # child self-terminates at 120 s; small margin on top
PROBE_ATTEMPTS = 4
METRIC = "llama_train_step_mfu"


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _scan_metric(out: str):
    """Last metric line from child stdout → (good_line, diagnosed_error).
    An error-bearing line is a self-diagnosis (e.g. backend-init timeout),
    never a result — both supervisor paths must treat it as retryable."""
    for line in reversed(out.splitlines()):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("metric") == METRIC:
            if obj.get("error"):
                return None, obj["error"]
            return line, None
    return None, None


def tcp_preflight() -> str | None:
    """~1 ms relay-liveness check before any 120 s jax probe.

    Round 4 pinned the init hang: the PJRT plugin blocks retrying
    `GET http://127.0.0.1:8083/init` against ECONNREFUSED when the
    relay/tunnel isn't running (tpu_evidence/DIAGNOSIS.md). A refused
    loopback connect is definitive — same netns, nothing to time out —
    so report it precisely instead of burning 4x120 s to say "hang".
    Returns None when the preflight passes (port open, or this isn't
    the relayed-axon environment), else the diagnosis string.
    """
    if os.environ.get("JAX_PLATFORMS") != "axon" or not os.environ.get(
            "PALLAS_AXON_POOL_IPS"):
        return None  # not the relayed environment; nothing to preflight
    tools_dir = os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools")
    sys.path.insert(0, tools_dir)
    try:
        from tpu_diag import RELAY_HOST, RELAY_PORTS, tcp_probe
    except Exception:  # noqa: BLE001 — a tooling import must never kill bench
        return None
    finally:
        # don't leave tools/ shadowing stdlib names for the whole process
        try:
            sys.path.remove(tools_dir)
        except ValueError:
            pass
    port = RELAY_PORTS[0]
    last = "unknown"
    deadline = time.monotonic() + 60  # relay may be mid-restart; give it 60 s
    while time.monotonic() < deadline:
        status = tcp_probe(RELAY_HOST, port)["status"]
        if status == "open":
            return None
        if status != "refused":
            return None  # timeout/filtered: a listener may exist — probe on
        last = "connection refused"
        time.sleep(5)
    return (f"relay not listening on {RELAY_HOST}:{port} ({last}) — the "
            f"relay/tunnel process is not running on this host, so "
            f"PJRT_Client_Create's GET /init can never succeed "
            f"(see tpu_evidence/DIAGNOSIS.md)")


def probe_backend(preflight_err: str | None = None) -> str | None:
    """Cheap relay probes before committing to a full measurement attempt.

    The relay either answers `jax.devices()` in seconds or hangs; burning a
    full 560 s attempt on a hung init wastes the driver window (BENCH_r02
    died this way, twice). Four 120 s probes give a flaky relay more bites
    at a fraction of the cost. Returns None when a probe succeeds, else the
    joined error string. ``preflight_err`` is the caller's TCP-preflight
    diagnosis: the common failure (relay process absent) is already
    precisely diagnosed, so one jax probe runs as insurance against the
    preflight's port assumption going stale instead of four.
    """
    errors = []
    attempts = PROBE_ATTEMPTS
    if preflight_err is not None:
        _log(f"preflight: {preflight_err}")
        errors.append(preflight_err)
        attempts = 1  # one ground-truth probe; don't burn the window
    for attempt in range(1, attempts + 1):
        _log(f"probe {attempt}/{attempts} (deadline {PROBE_DEADLINE_S}s)")
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--probe"],
                stdout=subprocess.PIPE,
                timeout=PROBE_DEADLINE_S,
            )
        except subprocess.TimeoutExpired:
            errors.append(f"probe {attempt}: hung, killed after {PROBE_DEADLINE_S}s")
            _log(errors[-1])
            continue
        out = proc.stdout.decode("utf-8", "replace").strip().splitlines()
        last = out[-1] if out else ""
        if proc.returncode == 0 and last.startswith("ok"):
            _log(f"probe {attempt}: backend up in "
                 f"{time.monotonic() - t0:.0f}s ({last})")
            return None
        errors.append(f"probe {attempt}: {last or f'rc={proc.returncode}'}")
        _log(errors[-1])
    return "; ".join(errors)


def cpu_fallback_attempt(probe_err: str) -> str | None:
    """The relay is definitively absent: measure what CAN be measured.

    Every BENCH round so far in the relay-down environment recorded
    ``value: 0.0, error: backend never initialized`` — no perf trajectory
    at all, even though the whole serving path (decode, paged decode,
    speculative decode, fleet, disagg) runs fine on the CPU backend. One
    child attempt with ``JAX_PLATFORMS=cpu`` runs the tiny-config bench
    end to end; its JSON line is emitted with ``cpu_fallback: true`` and
    the relay diagnosis attached so the numbers are never mistaken for
    TPU measurements. Returns the line to print, or None if even the CPU
    run failed (caller falls back to the error-only JSON)."""
    _log("relay absent — falling back to JAX_PLATFORMS=cpu for the "
         "serving-path probes")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--run"],
            stdout=subprocess.PIPE, timeout=ATTEMPT_DEADLINE_S, env=env)
    except subprocess.TimeoutExpired:
        _log("cpu fallback hung; giving up on it")
        return None
    good, diagnosed = _scan_metric(proc.stdout.decode("utf-8", "replace"))
    if good is None:
        _log(f"cpu fallback failed: {diagnosed or 'no metric line'}")
        return None
    obj = json.loads(good)
    obj["cpu_fallback"] = True
    obj["relay_error"] = probe_err
    return json.dumps(obj)


def supervise() -> None:
    preflight_err = tcp_preflight()
    probe_err = probe_backend(preflight_err)
    if probe_err is not None:
        if preflight_err is not None:
            # the relay process is NOT RUNNING (refused loopback connect)
            # — no amount of retrying reaches a TPU. Record a real perf
            # trajectory on the CPU backend instead of an error-only row.
            line = cpu_fallback_attempt(probe_err)
            if line is not None:
                print(line, flush=True)
                return
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": "mfu_fraction",
                    "vs_baseline": 0.0,
                    "error": f"backend never initialized: {probe_err}",
                }
            ),
            flush=True,
        )
        return
    errors = []
    deadline = ATTEMPT_DEADLINE_S
    for attempt in range(1, ATTEMPTS + 1):
        _log(f"attempt {attempt}/{ATTEMPTS} (deadline {deadline}s)")
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--run"],
                stdout=subprocess.PIPE,  # stderr passes through for live progress
                timeout=deadline,
            )
        except subprocess.TimeoutExpired as e:
            # the child may have printed the headline metric before hanging
            # (e.g. in the optional breakdown pass) — salvage it; an
            # error-bearing line is NOT a result (a child can self-diagnose
            # and then hang in backend teardown) and must still retry
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode("utf-8", "replace")
            good, diagnosed = _scan_metric(partial)
            if good is not None:
                _log(f"attempt {attempt}: hung after printing the metric; "
                     f"using it")
                print(good, flush=True)
                return
            errors.append(
                f"attempt {attempt}: "
                + (diagnosed or f"hung, killed after {deadline}s")
            )
            _log(errors[-1])
            # a full-deadline hang already burned ~9 min; cap the retry so
            # the TOTAL stays inside any plausible driver timeout and the
            # error JSON always gets printed
            deadline = 300
            continue
        out = proc.stdout.decode("utf-8", "replace")
        good, diagnosed = _scan_metric(out)
        if good is not None:
            print(good, flush=True)
            return
        errors.append(
            f"attempt {attempt}: "
            + (diagnosed or f"rc={proc.returncode} after "
                            f"{time.monotonic() - t0:.0f}s, no metric line")
        )
        _log(errors[-1])
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "mfu_fraction",
                "vs_baseline": 0.0,
                "error": "; ".join(errors) or "no attempts ran",
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# child: the actual benchmark
# --------------------------------------------------------------------------


def pick_config(platform: str):
    """Model + batch sized for the target: ~350M-param Llama on one v5e chip.

    The PRIMARY config is the fused-CE + full-recompute-remat b16 variant:
    the only headline candidate whose AOT row actually fits 16 GB HBM
    (8.55 GB, mfu bound 0.79 — tpu_evidence/AOT_ANALYSIS.md; the dense b8
    config needs 17.1 GB and would RESOURCE_EXHAUST the chip). The dense
    no-remat config survives as the ``dense_b8`` secondary probe in run().
    """
    from lzy_tpu.models.llama import LlamaConfig

    if platform in ("tpu", "axon"):
        cfg = LlamaConfig(
            vocab_size=32_768, d_model=1024, n_layers=20, n_heads=8,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048,
            remat=True, remat_policy="nothing", fused_ce=True,
            tie_embeddings=True, use_flash_kernel=True,
        )
        batch_size, seq_len = 16, 2048
        steps, warmup = 20, 3
    else:
        cfg = LlamaConfig.tiny(vocab_size=2048)
        batch_size, seq_len = 4, 128
        steps, warmup = 3, 1
    return cfg, batch_size, seq_len, steps, warmup


def init_devices(timeout_s: float = 240.0):
    """Backend init under a watchdog: jax.devices() on this relayed platform
    has been observed to hang for >580 s; surface that as an error promptly
    instead of eating the whole attempt deadline."""
    import threading

    result: list = []

    def probe():
        import jax

        result.append(jax.devices())

    t = threading.Thread(target=probe, daemon=True, name="jax-init")
    t.start()
    t.join(timeout_s)
    if not result:
        raise RuntimeError(f"jax backend init did not complete in {timeout_s:.0f}s")
    return result[0]


def run() -> None:
    _apply_platform_contract()
    _log("initializing jax backend...")
    try:
        devices = init_devices()
    except Exception as e:
        # self-diagnose on stdout so the supervisor's final JSON carries the
        # actual cause, not just "no metric line"
        print(json.dumps({"metric": METRIC, "value": 0.0,
                          "unit": "mfu_fraction", "vs_baseline": 0.0,
                          "error": f"{e}"}), flush=True)
        raise
    import jax

    platform = devices[0].platform
    chip = "v5e" if platform in ("tpu", "axon") else "cpu"
    _log(f"backend up: {len(devices)}x {platform}")

    import optax

    from lzy_tpu.models import count_params, llama, unbox
    from lzy_tpu.parallel import TrainState, make_train_step, mesh_for, mfu

    cfg, batch_size, seq_len, steps, warmup = pick_config(platform)

    mesh = mesh_for(fsdp=-1)
    _log("initializing params...")
    boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = unbox(boxed)
    n_params = count_params(params)
    _log(f"model ready: {n_params/1e6:.0f}M params, batch {batch_size} x seq {seq_len}")

    tx = optax.adamw(3e-4)
    loss_fn = llama.make_loss_fn(cfg, mesh)
    step, shard_state, _ = make_train_step(
        loss_fn, tx, mesh=mesh, param_logical_axes=axes,
        batch_logical_axes=("batch", "seq"),
    )
    state = shard_state(TrainState.create(params, tx))
    batch = {
        "tokens": jax.random.randint(
            jax.random.PRNGKey(1), (batch_size, seq_len), 0, cfg.vocab_size
        )
    }

    # hard sync via host transfer: each step consumes the previous state, so
    # materializing the last loss proves the whole chain executed
    # (block_until_ready alone does not flush on relayed TPU platforms)
    _log("compiling + warmup...")
    for i in range(warmup):
        state, metrics = step(state, batch)
        float(metrics["loss"])
        _log(f"warmup step {i + 1}/{warmup} done")

    _log(f"timing {steps} steps...")
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    step_ms = 1000 * dt / steps
    _log(f"timed: {step_ms:.1f} ms/step, loss {final_loss:.3f}")

    tokens_per_s = batch_size * seq_len * steps / dt
    achieved_mfu = mfu(tokens_per_s, n_params, len(devices), chip=chip)

    detail = {
        "platform": platform,
        "chips": len(devices),
        "params": n_params,
        "tokens_per_s": round(tokens_per_s, 1),
        "step_time_ms": round(step_ms, 2),
        "batch": batch_size,
        "seq_len": seq_len,
    }

    def emit():
        print(json.dumps({
            "metric": METRIC,
            "value": round(achieved_mfu, 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(achieved_mfu / 0.40, 4),
            "detail": detail,
        }), flush=True)

    # headline FIRST: the breakdown costs two extra compiles, and on this
    # backend a compile can hang — the supervisor salvages the last metric
    # line, so a measured MFU must already be on stdout before we risk it
    emit()
    # the adam moments (~2x params) are dead weight from here on; freeing
    # them is what lets the extra passes fit in HBM next to the live params
    params = state.params
    _free_buffers(state.opt_state)
    state = None
    extra = step_breakdown(jax, loss_fn, params, batch, step_ms)
    if extra:
        detail.update(extra)
        emit()
    is_tpu = platform in ("tpu", "axon")
    extra = decode_measurement(
        jax, cfg, params,
        batch_size=8 if is_tpu else 4,
        prompt_len=128 if is_tpu else 32,
        new_tokens=64)
    if extra:
        detail.update(extra)
        emit()
    extra = paged_decode_measurement(
        jax, cfg, params,
        batch_size=8 if is_tpu else 4,
        prompt_len=128 if is_tpu else 32,
        new_tokens=64,
        page_size=64 if is_tpu else 16)
    if extra:
        detail.update(extra)
        emit()
    extra = spec_decode_measurement(
        jax, cfg, params,
        slots=8 if is_tpu else 4,
        page_size=64 if is_tpu else 16,
        prompt_len=24 if is_tpu else 12,
        new_tokens=64 if is_tpu else 48,
        spec_tokens=6)
    if extra:
        detail.update(extra)
        emit()
    extra = fleet_decode_measurement(
        jax, cfg, params,
        replicas=2,
        slots=4 if is_tpu else 2,
        prompt_len=64 if is_tpu else 16,
        new_tokens=32 if is_tpu else 8,
        n_requests=8 if is_tpu else 4)
    if extra:
        detail.update(extra)
        emit()
    extra = disagg_measurement(
        jax, cfg, params,
        decode_replicas=2,
        slots=4 if is_tpu else 2,
        page_size=64 if is_tpu else 16,
        long_prompt_len=256 if is_tpu else 48,
        short_prompt_len=16 if is_tpu else 8,
        new_tokens=32 if is_tpu else 8,
        n_requests=8 if is_tpu else 4)
    if extra:
        detail.update(extra)
        emit()
    extra = kvtier_measurement(
        jax, cfg, params,
        slots=4 if is_tpu else 2,
        page_size=64 if is_tpu else 16,
        prompt_len=512 if is_tpu else 192,
        new_tokens=16 if is_tpu else 6)
    if extra:
        detail.update(extra)
        emit()
    extra = slo_measurement(
        jax, cfg, params,
        slots=4 if is_tpu else 2,
        page_size=64 if is_tpu else 16,
        long_prompt_len=512 if is_tpu else 96,
        new_tokens=16 if is_tpu else 6,
        n_victim=32 if is_tpu else 20,
        prefill_budget=256 if is_tpu else 32)
    if extra:
        detail.update(extra)
        emit()
    extra = llm_op_pipeline_measurement(
        jax, cfg, params,
        replicas=2,
        slots=4 if is_tpu else 2,
        page_size=64 if is_tpu else 16,
        prompt_len=128 if is_tpu else 32,
        new_tokens=32 if is_tpu else 8,
        n_conversations=6 if is_tpu else 3,
        steps=3)
    if extra:
        detail.update(extra)
        emit()
    extra = agent_pipeline_measurement(
        jax, cfg, params,
        replicas=2,
        slots=4 if is_tpu else 2,
        # page <= reply so the speculative prefill covers whole reply
        # pages — the thing the fused TTFT number is measuring
        page_size=32 if is_tpu else 8,
        prompt_len=128 if is_tpu else 32,
        new_tokens=32 if is_tpu else 8,
        n_conversations=6 if is_tpu else 3,
        steps=3)
    if extra:
        detail.update(extra)
        emit()
    extra = stream_measurement(
        jax, cfg, params,
        slots=4 if is_tpu else 2,
        prompt_len=64 if is_tpu else 16,
        new_tokens=64 if is_tpu else 32)
    if extra:
        detail.update(extra)
        emit()
    extra = gateway_restart_measurement(
        jax, cfg, params,
        replicas=2,
        slots=2,
        prompt_len=32 if is_tpu else 12,
        new_tokens=24 if is_tpu else 16)
    if extra:
        detail.update(extra)
        emit()
    extra = capacity_curve_measurement()
    if extra:
        detail.update(extra)
        emit()
    extra = sharded_decode_measurement()
    if extra:
        detail.update(extra)
        emit()
    if platform in ("tpu", "axon"):
        # each extra pass builds a whole second model+optimizer: evict the
        # previous one (buffers AND compiled executables) first or OOM
        _free_buffers(params, batch, metrics)
        params = batch = metrics = None
        jax.clear_caches()
        # secondary probe: the pre-promotion dense no-remat config. Its
        # AOT row says 17.1 GB / fits: NO, so an OOM here is EXPECTED
        # evidence, not a regression — the fused-b16 headline above is
        # what the chip actually serves (VERDICT top-next #1)
        extra = variant_measurement(
            jax, cfg, mesh, n_params, "dense_b8",
            {"fused_ce": False, "remat": False},
            batch_size=8, seq_len=2048)
        if extra:
            detail.update(extra)
            emit()
        jax.clear_caches()
        extra = seq4k_measurement(jax, cfg, mesh, n_params)
        if extra:
            detail.update(extra)
            emit()


def _free_buffers(*trees) -> None:
    """Eagerly release device buffers (GC alone is too late on a 16 GB chip)."""
    import jax

    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "delete"):
                try:
                    leaf.delete()
                except Exception:  # noqa: BLE001 — already deleted/donated
                    pass


def variant_measurement(jax, cfg, mesh, n_params, tag: str, overrides: dict,
                        *, batch_size: int, seq_len: int, steps: int = 10,
                        _raise: bool = False):
    """Best-effort MFU for a config variant (e.g. the logits-free fused CE
    loss, or the seq-4k point) — the evidence for flipping defaults. MFU is
    computed against the HEADLINE model's param count so variants are
    comparable. With ``_raise`` failures propagate (for callers with their
    own retry policy); otherwise they are logged and swallowed."""
    try:
        import dataclasses

        import optax

        from lzy_tpu.models import llama, unbox
        from lzy_tpu.parallel import TrainState, make_train_step, mfu

        _log(f"{tag}: building model...")
        vcfg = dataclasses.replace(cfg, **overrides)
        boxed, axes = llama.init_params(vcfg, jax.random.PRNGKey(0))
        tx = optax.adamw(3e-4)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(vcfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(unbox(boxed), tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (batch_size, seq_len), 0, vcfg.vocab_size
        )}
        try:
            _log(f"{tag}: compiling + warmup...")
            for _ in range(2):
                state, metrics = step(state, batch)
            float(metrics["loss"])
            _log(f"{tag}: timing {steps} steps...")
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step(state, batch)
            float(metrics["loss"])
            dt = time.perf_counter() - t0
        finally:
            _free_buffers(state, batch)
        tokens_per_s = batch_size * seq_len * steps / dt
        value = mfu(tokens_per_s, n_params, len(jax.devices()), chip="v5e")
        _log(f"{tag}: {1000 * dt / steps:.1f} ms/step, mfu {value:.4f}")
        return {f"{tag}_mfu": round(value, 4),
                f"{tag}_step_time_ms": round(1000 * dt / steps, 2)}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        if _raise:
            raise
        _log(f"{tag} skipped: {type(e).__name__}: {e}")
        return {}


def seq4k_measurement(jax, cfg, mesh, n_params, steps: int = 10):
    """Best-effort long-context point (VERDICT r1 #9): MFU at seq 4096,
    batch halved to keep HBM flat. Never risks the headline metric."""
    # fastest first, then progressively trade FLOPs for memory: dots keeps
    # the MXU outputs (the standard transformer remat point on TPU);
    # nothing_saveable is the max-savings last resort
    attempts = [(False, None), (True, "dots"), (True, "nothing")]
    for remat, policy in attempts:
        try:
            overrides = {"max_seq_len": 4096, "remat": remat}
            if policy is not None:
                overrides["remat_policy"] = policy
            out = variant_measurement(
                jax, cfg, mesh, n_params, "seq4k", overrides,
                batch_size=4, seq_len=4096, steps=steps, _raise=True)
            out["seq4k_batch"] = 4
            if remat:
                out["seq4k_remat"] = policy
            return out
        except Exception as e:  # noqa: BLE001 — diagnostics only
            _log(f"seq4k (remat={remat},{policy}) skipped: "
                 f"{type(e).__name__}: {e}")
            if "RESOURCE_EXHAUSTED" not in str(e):
                return {}
            jax.clear_caches()  # next attempt saves more memory
    return {}


def decode_measurement(jax, cfg, params, *, batch_size: int,
                       prompt_len: int, new_tokens: int):
    """Best-effort serving-path point: KV-cache decode throughput of the
    headline model (batched prefill + one jitted per-token decode step —
    the exact hot loop the continuous-batching engine in lzy_tpu/serving
    drives). The step is jitted ONCE and timed directly, so the metric is
    pure decode — no prefill share, no per-call recompiles; two extra
    compiles total (prefill chunk + step), wrapped so a hiccup never
    loses the headline metric."""
    try:
        import functools

        import jax.numpy as jnp

        from lzy_tpu.models.generate import (
            batched_prefill, decode_config, init_cache, make_prefill_step)
        from lzy_tpu.models.llama import Llama

        dcfg = decode_config(cfg)
        model = Llama(dcfg)
        prompt = jax.random.randint(
            jax.random.PRNGKey(2), (batch_size, prompt_len), 0,
            dcfg.vocab_size)
        _log("decode: compiling + prefill...")
        cache = init_cache(lambda: model.init(
            jax.random.PRNGKey(0), jnp.zeros((batch_size, 1), jnp.int32)))
        cache, last = batched_prefill(
            model, cache, params, prompt, max_seq_len=dcfg.max_seq_len,
            prefill_step=make_prefill_step(model))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(cache, params, tok):
            logits, updated = model.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"])
            return (updated["cache"],
                    jnp.argmax(logits[:, -1], -1).astype(jnp.int32))

        cur = jnp.argmax(last, -1).astype(jnp.int32)
        # two warm steps: the first compiles against host-fresh inputs,
        # the second against the jit's own (committed) outputs — with
        # sharded bench params those are distinct compilations, and the
        # second would otherwise land inside the timed window
        cache, cur = step(cache, params, cur[:, None])   # compile + warmup
        cache, cur = step(cache, params, cur[:, None])
        cur.block_until_ready()
        _log(f"decode: timing {new_tokens} steps x batch {batch_size}...")
        t0 = time.perf_counter()
        for _ in range(new_tokens):
            cache, cur = step(cache, params, cur[:, None])
        cur.block_until_ready()
        dt = time.perf_counter() - t0
        tps = batch_size * new_tokens / dt
        _log(f"decode: {1000 * dt / new_tokens:.2f} ms/step, "
             f"{tps:.1f} tok/s")
        return {"decode_tokens_per_s": round(tps, 1),
                "decode_step_ms": round(1000 * dt / new_tokens, 3),
                "decode_batch": batch_size,
                "decode_prompt_len": prompt_len}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"decode skipped: {type(e).__name__}: {e}")
        return {}


def paged_decode_measurement(jax, cfg, params, *, batch_size: int,
                             prompt_len: int, new_tokens: int,
                             page_size: int):
    """Best-effort paged-serving point: decode throughput through the
    PAGED attention path (the hot loop of serving.PagedInferenceEngine),
    measured next to the dense ``decode_tokens_per_s`` so the per-step
    cost of paging is a number, not a guess. Three variants per round so
    the trajectory separates kernel wins from config drift:

    - the NATIVE path (ops/paged_attention: pallas on TPU, the lax
      oracle elsewhere) is the headline ``paged_decode_tokens_per_s``;
    - the LEGACY gather-back-to-dense path rides along as
      ``paged_decode_legacy_tokens_per_s`` (the pre-PR-9 number);
    - the native path over an int8-quantized pool
      (``paged_decode_quant_tokens_per_s``) shows what halved KV bytes
      cost/buy per step at identical shapes.

    ``kernel_path`` (pallas/lax/legacy) and ``kv_quant`` are recorded in
    the row. Pure-throughput shape: identity page tables, the cache
    index parked at ``prompt_len`` (step cost does not depend on what
    the K/V bytes contain). A few extra compiles, wrapped so a hiccup
    never loses the headline metric."""
    try:
        import dataclasses
        import functools

        import jax.numpy as jnp

        from lzy_tpu.models.generate import (
            _set_cache_index, decode_config, init_cache)
        from lzy_tpu.models.llama import Llama
        from lzy_tpu.ops.paged_attention import default_kernel

        pages_per_seq = cfg.max_seq_len // page_size
        n_pages = batch_size * pages_per_seq + 1
        pt = jnp.arange(
            1, batch_size * pages_per_seq + 1, dtype=jnp.int32
        ).reshape(batch_size, pages_per_seq)
        native_kernel = default_kernel()

        # Timing discipline (the BENCH_r06 "lax trails legacy by 14%"
        # postmortem): the two paths compile to BYTE-IDENTICAL optimized
        # HLO on CPU — a side-by-side `.lower().compile().as_text()`
        # dump diffs clean except for metadata — so the measured gap was
        # never a kernel gap. It was ordering noise: each variant timed
        # exactly once, back to back, so whichever ran first paid (or
        # dodged) allocator warmup and cache effects for the others.
        # Fix: build + warm EVERY variant first, then time them in
        # interleaved round-robin rounds and keep the best round per
        # variant. A real kernel regression still loses every round;
        # one-off scheduling hiccups no longer masquerade as one.
        def build_variant(tag, **over):
            dcfg = dataclasses.replace(
                decode_config(cfg), decode_paged=True,
                kv_page_size=page_size, kv_pages=n_pages, **over)
            model = Llama(dcfg)
            _log(f"paged decode[{tag}]: compiling...")
            cache = init_cache(lambda: model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((batch_size, 1), jnp.int32), page_table=pt))
            cache = _set_cache_index(cache, prompt_len)

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(cache, params, tok, pt):
                logits, updated = model.apply(
                    {"params": params, "cache": cache}, tok,
                    page_table=pt, mutable=["cache"])
                return (updated["cache"],
                        jnp.argmax(logits[:, -1], -1).astype(jnp.int32))

            cur = jnp.zeros((batch_size,), jnp.int32)
            # two warm steps — same second-layout reasoning as the dense
            # probe
            cache, cur = step(cache, params, cur[:, None], pt)
            cache, cur = step(cache, params, cur[:, None], pt)
            cur.block_until_ready()
            state = {"cache": cache, "cur": cur}

            def run():
                cache, cur = state["cache"], state["cur"]
                t0 = time.perf_counter()
                for _ in range(new_tokens):
                    cache, cur = step(cache, params, cur[:, None], pt)
                cur.block_until_ready()
                dt = time.perf_counter() - t0
                state["cache"], state["cur"] = cache, cur
                return batch_size * new_tokens / dt, 1000 * dt / new_tokens

            def free():
                _free_buffers(state["cache"])

            return run, free

        # legacy FIRST: the variant proven green on every pre-PR-9 round
        # is banked before the native path gets a chance to hiccup, so
        # the headline can fall back to it instead of vanishing
        out = {"paged_decode_page_size": page_size,
               "paged_decode_kv_quant": "off"}
        variants = []  # [tag, run, free] — mutable so a timing failure
        legacy_built = False  # can drop one variant without losing the rest
        try:
            run, free = build_variant("legacy")
            variants.append(["legacy", run, free])
            legacy_built = True
        except Exception as e:  # noqa: BLE001 — variant is optional
            _log(f"paged decode legacy variant skipped: "
                 f"{type(e).__name__}: {e}")
        native_built = False
        try:
            run, free = build_variant(
                native_kernel, paged_attention_native=True,
                paged_kernel=native_kernel)
            variants.append([native_kernel, run, free])
            native_built = True
        except Exception as e:  # noqa: BLE001 — fall back to legacy
            if not legacy_built:
                raise
            _log(f"paged decode native variant failed "
                 f"({type(e).__name__}: {e}); legacy headline")
        try:
            run, free = build_variant(
                f"{native_kernel}+int8", paged_attention_native=True,
                paged_kernel=native_kernel, kv_quant="int8")
            variants.append([f"{native_kernel}+int8", run, free])
        except Exception as e:  # noqa: BLE001 — variant is optional
            _log(f"paged decode quant variant skipped: "
                 f"{type(e).__name__}: {e}")

        best = {}  # tag -> (tps, step_ms), best round wins
        for rnd in range(3):
            for entry in list(variants):
                tag, run = entry[0], entry[1]
                try:
                    tps_r, ms_r = run()
                except Exception as e:  # noqa: BLE001 — drop variant
                    _log(f"paged decode[{tag}] round {rnd} failed "
                         f"({type(e).__name__}: {e}); dropping variant")
                    variants.remove(entry)
                    best.pop(tag, None)
                    if tag == native_kernel:
                        native_built = False
                    continue
                _log(f"paged decode[{tag}] r{rnd}: {ms_r:.2f} ms/step, "
                     f"{tps_r:.1f} tok/s (page {page_size})")
                if tag not in best or tps_r > best[tag][0]:
                    best[tag] = (tps_r, ms_r)
        for entry in variants:
            entry[2]()

        if "legacy" in best:
            out["paged_decode_legacy_tokens_per_s"] = round(
                best["legacy"][0], 1)
        if native_built and native_kernel in best:
            tps, step_ms = best[native_kernel]
            out["paged_decode_kernel_path"] = native_kernel
        elif "legacy" in best:
            tps, step_ms = best["legacy"]
            out["paged_decode_kernel_path"] = "legacy"
        else:
            raise RuntimeError("no paged decode variant survived timing")
        out["paged_decode_tokens_per_s"] = round(tps, 1)
        out["paged_decode_step_ms"] = round(step_ms, 3)
        quant_tag = f"{native_kernel}+int8"
        if quant_tag in best:
            out["paged_decode_quant_tokens_per_s"] = round(
                best[quant_tag][0], 1)
            out["paged_decode_quant_mode"] = "int8"
        if quant_tag in best:
            try:
                # observed quantizer error on a representative KV sample
                # (feeds the lzy_kernel_dequant_error_ewma gauge; the
                # timing loop's pool holds zeros, whose error would read
                # as 0.0)
                from lzy_tpu.ops.paged_attention import (
                    dequantize_kv, note_dequant_error, quantize_kv)

                sample = jax.random.normal(
                    jax.random.PRNGKey(0), (1024, cfg.head_dim),
                    jnp.float32)
                qs, ss, zs = quantize_kv(sample)
                err = float(jnp.mean(jnp.abs(
                    dequantize_kv(qs, ss, zs, jnp.float32) - sample)))
                out["paged_decode_dequant_err_mean"] = round(
                    note_dequant_error(err), 6)
            except Exception as e:  # noqa: BLE001 — metric is optional
                _log(f"paged decode dequant-error probe skipped: "
                     f"{type(e).__name__}: {e}")
        return out
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"paged decode skipped: {type(e).__name__}: {e}")
        return {}


def sharded_decode_measurement():
    """Best-effort gang-serving point: decode throughput of 1×2 and 1×4
    CPU-mesh ``ShardedPagedInferenceEngine`` gangs next to a single-device
    ``PagedInferenceEngine`` baseline, with mesh shape and per-shard KV
    occupancy in the row. Runs in a CHILD process because the meshes need
    ``--xla_force_host_platform_device_count`` set before backend init —
    the parent's device topology (and every other probe's numbers) stays
    untouched. The child pins ``JAX_PLATFORMS=cpu`` even on TPU rounds:
    this row is a partitioning/scheduling-overhead trajectory riding the
    CPU-fallback round, never a chip number."""
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
        _log("sharded decode: spawning 8-device cpu child...")
        proc = subprocess.run(
            [sys.executable, __file__, "--sharded-probe"],
            stdout=subprocess.PIPE, timeout=480, env=env)
        for line in reversed(
                proc.stdout.decode("utf-8", "replace").splitlines()):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "error" in obj:
                _log(f"sharded decode skipped: {obj['error']}")
                return {}
            return obj
        _log(f"sharded decode skipped: no result line "
             f"(rc={proc.returncode})")
        return {}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"sharded decode skipped: {type(e).__name__}: {e}")
        return {}


def sharded_probe_child() -> None:
    """Child half of ``sharded_decode_measurement`` (``--sharded-probe``):
    drives the SAME request set through a single-device paged engine and
    1×2 / 1×4 gangs, asserts the 1×2 stream is bit-identical to the
    baseline (the gang contract, re-proven every bench round), and prints
    one JSON row. The 1×4 gang needs ``n_kv_heads % 4 == 0``, which the
    tiny config fails — it runs on a widened config with fresh params
    against its OWN widened baseline, so its ratio is apples-to-apples
    even though its absolute number is not comparable to the 1×2 one.

    Compute dtype is pinned to float32: the gang's bit-identity is exact
    in f32 (no contraction dim ever shards), but under bf16 compute the
    partitioned program's different XLA fusion boundaries round
    intermediates at different points — 1-ULP logit noise that can flip
    argmax on near-tie prompts and would make this row's identity check
    flaky. f32 keeps the assertion a hard invariant round over round."""
    _apply_platform_contract()
    try:
        import dataclasses

        import jax
        import jax.numpy as jnp

        from lzy_tpu.models import llama, unbox
        from lzy_tpu.models.llama import LlamaConfig
        from lzy_tpu.serving import PagedInferenceEngine
        from lzy_tpu.serving.sharded import ShardedPagedInferenceEngine

        n_dev = len(jax.devices())
        if n_dev < 4:
            raise RuntimeError(
                f"need >= 4 devices for a 1x4 gang, have {n_dev}")
        slots, page_size, prompt_len, new_tokens = 4, 16, 32, 32
        prompts = [[3 + i, 5, 7, 11 + i] * (prompt_len // 4)
                   for i in range(slots)]

        def build_params(cfg):
            boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
            return unbox(boxed)

        def drive(make):
            eng = make()
            try:
                # two warm requests: fresh-input then committed-layout
                # compile, same reasoning as the spec probe
                for i in (7, 9):
                    warm = eng.submit([3, 5 + i] * (prompt_len // 2),
                                      max_new_tokens=2)
                    while not warm.done:
                        eng.step()
                reqs = [eng.submit(p, max_new_tokens=new_tokens)
                        for p in prompts]
                occ = None
                t0 = time.perf_counter()
                while not all(r.done for r in reqs):
                    eng.step()
                    if hasattr(eng, "shard_occupancy"):
                        cur = eng.shard_occupancy()
                        # keep the hottest mid-flight snapshot: occupancy
                        # at peak residency, not after frees
                        if occ is None or sum(cur) > sum(occ):
                            occ = cur
                dt = time.perf_counter() - t0
                total = sum(len(r.tokens) for r in reqs)
                toks = [list(r.tokens) for r in reqs]
            finally:
                eng.close()
            return total / dt, occ, toks

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=2048),
                                  dtype=jnp.float32)
        params = build_params(cfg)
        _log("sharded decode: single-device baseline...")
        base_tps, _, base_toks = drive(lambda: PagedInferenceEngine(
            cfg, params, slots=slots, page_size=page_size,
            max_queue=2 * slots + 2))
        _log(f"sharded decode: baseline {base_tps:.1f} tok/s; 1x2 gang...")
        tps2, occ2, toks2 = drive(lambda: ShardedPagedInferenceEngine(
            cfg, params, tp=2, slots=slots, page_size=page_size,
            max_queue=2 * slots + 2))
        if toks2 != base_toks:
            raise AssertionError(
                "1x2 gang stream diverged from the single-device engine "
                "(bit-identity contract broken)")
        _log(f"sharded decode: 1x2 {tps2:.1f} tok/s, per-shard KV {occ2}")
        out = {
            "sharded_decode_tokens_per_s": round(tps2, 1),
            "sharded_decode_mesh": "1x2",
            "sharded_decode_shard_kv_blocks": occ2,
            "sharded_decode_baseline_tokens_per_s": round(base_tps, 1),
            "sharded_decode_vs_single": round(tps2 / base_tps, 3),
            "sharded_decode_bit_identical": True,
            "sharded_decode_dtype": "float32",
        }
    except Exception as e:  # noqa: BLE001 — reported to the parent
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}), flush=True)
        os._exit(1)
    try:
        # 1x4 rider: widened config (tiny n_kv_heads=2 fails the tp=4
        # divisibility gate); a failure here must not lose the 1x2 row
        wcfg = dataclasses.replace(cfg, n_kv_heads=4)
        wparams = build_params(wcfg)
        _log("sharded decode: widened 1x4 pair...")
        wbase_tps, _, wbase_toks = drive(lambda: PagedInferenceEngine(
            wcfg, wparams, slots=slots, page_size=page_size,
            max_queue=2 * slots + 2))
        tps4, occ4, toks4 = drive(lambda: ShardedPagedInferenceEngine(
            wcfg, wparams, tp=4, slots=slots, page_size=page_size,
            max_queue=2 * slots + 2))
        if toks4 != wbase_toks:
            raise AssertionError(
                "1x4 gang stream diverged from its widened baseline")
        _log(f"sharded decode: 1x4 {tps4:.1f} tok/s, per-shard KV {occ4}")
        out.update({
            "sharded_decode_1x4_tokens_per_s": round(tps4, 1),
            "sharded_decode_1x4_mesh": "1x4",
            "sharded_decode_1x4_shard_kv_blocks": occ4,
            "sharded_decode_1x4_vs_single": round(tps4 / wbase_tps, 3),
            "sharded_decode_1x4_widened_kv_heads": wcfg.n_kv_heads,
        })
    except Exception as e:  # noqa: BLE001 — rider is optional
        _log(f"sharded decode 1x4 skipped: {type(e).__name__}: {e}")
        out["sharded_decode_1x4_skipped"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out), flush=True)
    # hard-exit like probe(): a hung backend teardown must not eat the
    # parent's window
    os._exit(0)


def _sim_spec_tokens_per_step(proposer, prompt, cont):
    """Host-side replay of the engine's acceptance rule over a KNOWN
    greedy continuation: how many tokens/step would prompt lookup have
    earned on this request? Pure python (no device work) — the workload
    selector below uses it to score candidates."""
    hist = list(prompt) + [int(cont[0])]
    i, rounds, emitted = 1, 0, 0
    while i < len(cont):
        p = proposer.propose(hist)
        rounds += 1
        take = 1
        if p:
            m = 0
            while m < len(p) and i + m < len(cont) \
                    and p[m] == int(cont[i + m]):
                m += 1
            take = min(m + 1, len(cont) - i)
        hist += [int(t) for t in cont[i:i + take]]
        i += take
        emitted += take
    return emitted / rounds if rounds else 1.0


def spec_decode_measurement(jax, cfg, params, *, slots: int,
                            page_size: int, prompt_len: int,
                            new_tokens: int, spec_tokens: int):
    """Best-effort speculative-decoding point (serving/spec.py).

    The headline ``spec_decode_tokens_per_s`` is measured EXACTLY like
    its baseline ``paged_decode_tokens_per_s``: a raw loop over the
    jitted paged forward — here the ``[B, gamma+1]`` verify step with
    host-side n-gram proposal, exact-match acceptance and index rewind
    (the speculative hot loop, minus engine scheduling) — so the two
    numbers differ only by what speculation changes. The engine-level
    pair (``spec_engine_*``, speculation on vs off through the full
    ``PagedInferenceEngine``) rides along as the end-to-end view.

    Speculation is a WORKLOAD-CLASS optimization: it pays on
    repetitive/structured continuations (code, extraction, summaries
    quoting their source) and is a wash on free-form text. Like the
    fleet probe (which must use a shared-prefix workload or affinity is
    structurally unmeasurable), this probe has to measure the class the
    feature targets: a selection pass generates candidate prompts,
    scores each by replaying the acceptance rule over its actual greedy
    continuation (host-side; one batched generate of device work), and
    benchmarks the most repetitive-continuation ones. The acceptance
    rate is reported so a reader can discount the number for less
    repetitive traffic. Wrapped so a hiccup never loses the headline
    metric."""
    try:
        import dataclasses
        import functools

        import jax.numpy as jnp
        import numpy as np

        from lzy_tpu.models.generate import (
            decode_config, generate, init_cache)
        from lzy_tpu.models.llama import Llama
        from lzy_tpu.serving import NgramProposer, PagedInferenceEngine

        _log(f"spec decode: scoring candidate workloads "
             f"(batch {slots}, gamma {spec_tokens})...")
        # constant-token seeds spread over the vocab: the cheapest
        # generator of genuinely repetitive continuations on an arbitrary
        # model; ONE batched generate covers the whole candidate set
        cands = [[t] * prompt_len
                 for t in range(7, cfg.vocab_size, max(cfg.vocab_size // 64,
                                                       1))]
        outs = np.asarray(generate(
            cfg, params, jnp.asarray(cands, jnp.int32),
            max_new_tokens=new_tokens))
        proposer = NgramProposer(max_ngram=3, gamma=spec_tokens)
        scored = sorted(
            ((_sim_spec_tokens_per_step(
                proposer, p, outs[i, prompt_len:].tolist()), p)
             for i, p in enumerate(cands)), key=lambda x: -x[0])
        prompts = [p for _, p in scored[:slots]]
        predicted = round(sum(s for s, _ in scored[:slots]) / slots, 2)

        # -- raw verify loop (methodology twin of paged_decode) ----------
        # runs the NATIVE paged-attention path (pallas on TPU, lax
        # elsewhere): the stream-equals-generate() assertion below then
        # re-proves the native verify's bit-identity on every bench round
        from lzy_tpu.ops.paged_attention import default_kernel

        native_kernel = default_kernel()
        B, gamma, width = slots, spec_tokens, spec_tokens + 1
        pages_per_seq = cfg.max_seq_len // page_size
        pt = jnp.arange(1, B * pages_per_seq + 1, dtype=jnp.int32).reshape(
            B, pages_per_seq)

        def set_index_rows(cache, pos):
            vals = np.asarray(pos, np.int32)
            # one COPIED device array per leaf: jnp.asarray is zero-copy
            # on CPU, so it would alias this numpy buffer straight into
            # a donated jit argument — the same jnp.array-not-asarray
            # rule the engine's _cache property and device mirrors
            # (_pos_dev/_pt_dev) follow
            return jax.tree_util.tree_map_with_path(
                lambda path, leaf: jnp.array(vals) if any(
                    getattr(p, "key", None) == "index" for p in path)
                else leaf, cache)

        def build_and_warm(native: bool):
            dcfg = dataclasses.replace(
                decode_config(cfg), decode_paged=True,
                kv_page_size=page_size, kv_pages=B * pages_per_seq + 1,
                paged_attention_native=native,
                paged_kernel=native_kernel if native else "lax")
            model = Llama(dcfg)

            @functools.partial(jax.jit, donate_argnums=(0,))
            def chunk_step(cache, params, toks, pt):
                logits, upd = model.apply(
                    {"params": params, "cache": cache}, toks,
                    page_table=pt, mutable=["cache"])
                return upd["cache"], jnp.argmax(logits, -1).astype(
                    jnp.int32)

            cache = init_cache(lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
                page_table=pt))
            # real prefill (acceptance depends on real logits, unlike
            # the content-independent paged probe): one [B, prompt_len]
            # chunk
            cache, am = chunk_step(cache, params,
                                   jnp.asarray(prompts, jnp.int32), pt)
            am = np.asarray(am)
            # two warm verify calls (fresh-input layout, then committed
            # jit-output layout — distinct compilations under sharded
            # params); any native-path compile failure surfaces HERE,
            # before the timing loop, where the fallback can catch it
            pos0 = np.full((B,), prompt_len, np.int64)
            toks0 = np.zeros((B, width), np.int32)
            cache, _ = chunk_step(set_index_rows(cache, pos0), params,
                                  jnp.asarray(toks0), pt)
            cache, warm = chunk_step(set_index_rows(cache, pos0), params,
                                     jnp.asarray(toks0), pt)
            warm.block_until_ready()
            return chunk_step, cache, am

        # native-first with the same legacy fallback as the paged probe:
        # a kernel hiccup must cost the kernel win, never the whole
        # spec trajectory
        _log("spec decode: compiling + prefill...")
        kernel_path = native_kernel
        try:
            chunk_step, cache, am = build_and_warm(True)
        except Exception as e:  # noqa: BLE001 — fall back to legacy
            _log(f"spec decode native path failed ({type(e).__name__}: "
                 f"{e}); legacy kernel")
            kernel_path = "legacy"
            chunk_step, cache, am = build_and_warm(False)
        # per-row incremental n-gram index (what the engine keeps per
        # slot); its .seq doubles as the row's emitted history
        rows = [proposer.index(list(p) + [int(am[r, -1])])
                for r, p in enumerate(prompts)]
        pos = np.full((B,), prompt_len, np.int64)
        emitted = np.ones((B,), np.int64)   # the prefill's argmax token
        rounds = proposed = accepted = 0
        _log(f"spec decode: predicted {predicted} tok/step; timing "
             f"{B} rows x {new_tokens} tokens...")
        t0 = time.perf_counter()
        while any(emitted < new_tokens):
            toks = np.zeros((B, width), np.int32)
            drafts = []
            for r in range(B):
                d = []
                if emitted[r] < new_tokens:
                    toks[r, 0] = rows[r].seq[-1]
                    d = rows[r].propose()[:gamma]
                    toks[r, 1:1 + len(d)] = d
                drafts.append(d)
            cache = set_index_rows(cache, pos)
            cache, am_dev = chunk_step(cache, params, jnp.asarray(toks), pt)
            am = np.asarray(am_dev)
            for r in range(B):
                if emitted[r] >= new_tokens:
                    continue
                d = drafts[r]
                m = 0
                while m < len(d) and d[m] == int(am[r, m]):
                    m += 1
                take = min(m + 1, int(new_tokens - emitted[r]))
                rows[r].extend((list(d[:m]) + [int(am[r, m])])[:take])
                pos[r] += take
                emitted[r] += take
                proposed += len(d)
                accepted += m
            rounds += 1
        # np.asarray on the argmax already forced every device step
        dt = time.perf_counter() - t0
        tps_raw = B * new_tokens / dt
        acc = round(accepted / proposed, 4) if proposed else 0.0
        tok_step = round(float(B * new_tokens) / (rounds * B), 4)
        # the raw loop reproduces the oracle stream exactly (exact-match
        # acceptance): diverging here would mean a verify-path bug
        sel = {tuple(p): i for i, p in enumerate(cands)}
        for r, p in enumerate(prompts):
            want = outs[sel[tuple(p)], prompt_len:].tolist()
            got = rows[r].seq[prompt_len:prompt_len + new_tokens]
            if got != want:
                raise AssertionError(
                    f"speculative stream diverged from generate() on "
                    f"row {r}")
        _log(f"spec decode: {tps_raw:.1f} tok/s raw verify loop "
             f"(acceptance {acc}, {tok_step} tok/step)")

        # -- engine-level end-to-end pair (speculation on vs off) --------
        def drive(g: int):
            eng = PagedInferenceEngine(
                cfg, params, slots=slots, page_size=page_size,
                max_queue=2 * slots + 2, spec_tokens=g,
                native_attention=kernel_path != "legacy")
            try:
                # two warm requests: layout reasoning as above
                for i in (7, 9):
                    warm = eng.submit([3, 5 + i] * (prompt_len // 2),
                                      max_new_tokens=2 * (g + 1) + 2)
                    while not warm.done:
                        eng.step()
                reqs = [eng.submit(p, max_new_tokens=new_tokens)
                        for p in prompts]
                t0 = time.perf_counter()
                while not all(r.done for r in reqs):
                    eng.step()
                dt = time.perf_counter() - t0
                total = sum(len(r.tokens) for r in reqs)
            finally:
                eng.close()
            return total / dt

        eng_off = drive(0)
        eng_on = drive(spec_tokens)
        _log(f"spec decode: engine {eng_on:.1f} tok/s with speculation "
             f"vs {eng_off:.1f} without")
        return {"spec_decode_tokens_per_s": round(tps_raw, 1),
                "spec_acceptance_rate": acc,
                "spec_tokens_per_step": tok_step,
                "spec_gamma": spec_tokens,
                "spec_decode_kernel_path": kernel_path,
                "spec_decode_kv_quant": "off",
                "spec_engine_decode_tokens_per_s": round(eng_on, 1),
                "spec_engine_off_decode_tokens_per_s": round(eng_off, 1),
                # permanent raw-vs-engine regression gate: how many x
                # the engine's scheduling leaves on the table relative
                # to its own raw verify loop (1.0 = scheduling is free;
                # BENCH_r06 read 3.8 before the one-fence round)
                "engine_overhead_ratio": round(tps_raw / eng_on, 2)}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"spec decode skipped: {type(e).__name__}: {e}")
        return {}


def fleet_decode_measurement(jax, cfg, params, *, replicas: int,
                             slots: int, prompt_len: int,
                             new_tokens: int, n_requests: int):
    """Best-effort serving-fleet point: aggregate decode throughput of a
    multi-replica gateway (lzy_tpu/gateway) over the SAME engines the
    single-engine ``decode_tokens_per_s`` probe models — the fleet number
    next to the single number is the scaling evidence. Drives a
    shared-prefix workload through the prefix-affinity router with one
    client thread per decode slot, and reports the per-replica token
    breakdown so imbalance is a number, not a guess. Wrapped so a hiccup
    never loses the headline metric."""
    try:
        from concurrent import futures as _futures

        from lzy_tpu.gateway import (
            GatewayService, PrefixAffinityRouter, ReplicaFleet)
        from lzy_tpu.serving import InferenceEngine

        _log(f"fleet decode: building {replicas} replicas x "
             f"{slots} slots...")
        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=slots,
                                    max_queue=2 * n_requests))
        # router chunk 8 so the shared prefixes below span FULL chunks
        # on every config — prompts must share whole chunks or affinity
        # is structurally unmeasurable
        router = PrefixAffinityRouter(8)
        gw = GatewayService(fleet, router=router, model_name="bench",
                            max_waiters=replicas * slots + 2)
        try:
            for _ in range(replicas):
                fleet.add_replica()
            # one shared-prefix FAMILY per replica. A single fleet-wide
            # prefix routes every request to one replica BY DESIGN
            # (prefix affinity doing its job) — but that makes the probe
            # a single-replica number wearing a fleet label: BENCH_r06
            # read fleet_per_replica_tokens {replica-1: 32, replica-2: 0}.
            # Distinct families keep the affinity story AND spread load.
            chunk = prompt_len - prompt_len % 8
            families = [list(range(1 + 64 * f, chunk + 1 + 64 * f))
                        for f in range(replicas)]
            prompts = [families[i % replicas] + [i % 50 + 2, i % 30 + 2]
                       for i in range(n_requests)]
            # seed each family's affinity onto its own replica BEFORE the
            # first route: on an idle fleet the load tie-break is
            # deterministic (lowest replica id), so routing the families
            # cold would pin them all to replica-1 anyway
            for rep, fam in zip(fleet.replicas(), families):
                router.observe(rep.id, fam)
            # warmup: compile prefill + decode once per replica — the jit
            # cache is process-shared but each engine still pays its own
            # first-dispatch costs, which must not land in the timed
            # window of whichever family hits that replica first
            for f in range(replicas):
                gw.generate(prompts[f], max_new_tokens=2, timeout_s=300)
            # engine counters are cumulative — snapshot after warmup so
            # the reported breakdown covers exactly the timed window
            base = {r.id: r.engine.stats().tokens_generated
                    for r in fleet.replicas()}
            _log(f"fleet decode: timing {n_requests} requests x "
                 f"{new_tokens} tokens...")
            t0 = time.perf_counter()
            with _futures.ThreadPoolExecutor(replicas * slots) as pool:
                results = list(pool.map(
                    lambda p: gw.generate(p, max_new_tokens=new_tokens,
                                          timeout_s=300),
                    prompts))
            dt = time.perf_counter() - t0
            total = sum(len(r["tokens"]) for r in results)
            per_replica = {
                r.id: r.engine.stats().tokens_generated - base.get(r.id, 0)
                for r in fleet.replicas()}
            stats = gw.stats()
        finally:
            gw.close()
        tps = total / dt
        _log(f"fleet decode: {tps:.1f} tok/s aggregate over "
             f"{replicas} replicas ({per_replica})")
        return {"fleet_decode_tokens_per_s": round(tps, 1),
                "fleet_replicas": replicas,
                "fleet_slots_per_replica": slots,
                "fleet_per_replica_tokens": per_replica,
                "fleet_prefix_route_rate": stats["prefix_route_rate"]}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"fleet decode skipped: {type(e).__name__}: {e}")
        return {}


def disagg_measurement(jax, cfg, params, *, decode_replicas: int,
                       slots: int, page_size: int, long_prompt_len: int,
                       short_prompt_len: int, new_tokens: int,
                       n_requests: int):
    """Best-effort disaggregated-serving point: TTFT and aggregate decode
    throughput of a prefill-pool + decode-pool gateway
    (lzy_tpu/gateway/disagg) under a MIXED long-prompt/short-prompt
    workload — the traffic shape disaggregation exists for (long prefills
    stall co-resident decodes on a monolithic replica). Reported next to
    the monolithic ``fleet_decode_tokens_per_s`` so the interference win
    is a number. Wrapped so a hiccup never loses the headline metric."""
    try:
        from concurrent import futures as _futures

        from lzy_tpu.gateway import (
            DisaggGatewayService, PrefixAffinityRouter, ReplicaFleet)
        from lzy_tpu.serving import DecodeEngine, PrefillEngine

        _log(f"disagg: building 1 prefill + {decode_replicas} decode "
             f"replicas x {slots} slots (page {page_size})...")
        kw = dict(slots=slots, page_size=page_size,
                  max_queue=2 * n_requests)
        decode_fleet = ReplicaFleet(
            lambda: DecodeEngine(cfg, params, **kw),
            replica_prefix="decode")
        prefill_fleet = ReplicaFleet(
            lambda: PrefillEngine(cfg, params, **kw),
            replica_prefix="prefill")
        gw = DisaggGatewayService(
            decode_fleet, prefill_fleet, page_size=page_size,
            router=PrefixAffinityRouter(page_size),
            prefill_router=PrefixAffinityRouter(page_size),
            prefill_replicas=1, model_name="bench",
            max_waiters=decode_replicas * slots + 2)
        try:
            for _ in range(decode_replicas):
                decode_fleet.add_replica()
            prefill_fleet.add_replica()
            # mixed workload: every other request drags a long prompt
            # through the prefill pool while short ones decode
            long_p = long_prompt_len - long_prompt_len % page_size
            prompts = []
            for i in range(n_requests):
                if i % 2 == 0:
                    prompts.append(list(range(1, long_p + 1)) + [i % 50 + 2])
                else:
                    prompts.append([i % 50 + 2, i % 30 + 3]
                                   + list(range(2, short_prompt_len + 2)))
            # warmup: compile prefill + decode on both pools
            gw.generate(prompts[0], max_new_tokens=2, timeout_s=300)
            gw.generate(prompts[1], max_new_tokens=2, timeout_s=300)
            _log(f"disagg: timing {n_requests} requests x "
                 f"{new_tokens} tokens...")
            t0 = time.perf_counter()
            with _futures.ThreadPoolExecutor(decode_replicas * slots) \
                    as pool:
                results = list(pool.map(
                    lambda p: gw.generate(p, max_new_tokens=new_tokens,
                                          timeout_s=300),
                    prompts))
            dt = time.perf_counter() - t0
            total = sum(len(r["tokens"]) for r in results)
            ttfts = [r["ttft_ms"] for r in results
                     if r.get("ttft_ms") is not None]
            stats = gw.stats()
        finally:
            gw.close()
        tps = total / dt
        ttft_ms = sum(ttfts) / len(ttfts) if ttfts else None
        _log(f"disagg: {tps:.1f} tok/s aggregate, mean TTFT "
             f"{ttft_ms and round(ttft_ms, 1)} ms "
             f"({stats['kv_transfers']} transfers, "
             f"{stats['kv_transfer_skipped_by_cache']} cache-skips, "
             f"{stats['reprefill_fallbacks']} fallbacks)")
        return {"disagg_decode_tokens_per_s": round(tps, 1),
                "disagg_ttft_ms": round(ttft_ms, 2) if ttft_ms else None,
                "disagg_decode_replicas": decode_replicas,
                "disagg_kv_transfers": stats["kv_transfers"],
                "disagg_kv_transfer_bytes": stats["kv_transfer_bytes"],
                "disagg_transfer_skipped_by_cache":
                    stats["kv_transfer_skipped_by_cache"],
                "disagg_reprefill_fallbacks":
                    stats["reprefill_fallbacks"]}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"disagg skipped: {type(e).__name__}: {e}")
        return {}


def kvtier_measurement(jax, cfg, params, *, slots: int, page_size: int,
                       prompt_len: int, new_tokens: int):
    """Best-effort tiered-KV point: TTFT of a shared-system-prompt
    request routed to a COLD replica, with the fleet-global prefix
    index importing the warm sibling's blocks vs the same fleet forced
    to re-prefill (index off). Round-robin routing makes the second
    request land on the cold replica deterministically — the exact
    traffic shape the cross-replica import exists for (autoscale /
    failover cache warm-up). Reports tier hit/miss counts so the win is
    attributable. Wrapped so a hiccup never loses the headline metric."""
    try:
        from lzy_tpu.gateway import (
            GatewayService, GlobalKVIndex, ReplicaFleet, RoundRobinRouter)
        from lzy_tpu.serving import PagedInferenceEngine

        shared_len = prompt_len - prompt_len % page_size
        shared = list(range(1, shared_len + 1))
        blocks = 4 * (shared_len // page_size) + 8

        def run_side(with_index: bool) -> dict:
            fleet = ReplicaFleet(lambda: PagedInferenceEngine(
                cfg, params, slots=slots, page_size=page_size,
                kv_blocks=blocks))
            gw = GatewayService(
                fleet, router=RoundRobinRouter(page_size),
                kv_index=GlobalKVIndex(page_size) if with_index else None,
                model_name="bench")
            try:
                for _ in range(2):
                    fleet.add_replica()
                # warm request: pays the full shared-prefix prefill on
                # replica 1 (and compiles the programs both sides share)
                r1 = gw.generate(shared + [3], max_new_tokens=2,
                                 timeout_s=300)
                gw.tick()    # replicas advertise into the global index
                # cold request: round-robin lands it on replica 2 —
                # with the index it imports r1's blocks, without it the
                # whole shared prompt re-prefills
                r2 = gw.generate(shared + [7], max_new_tokens=new_tokens,
                                 timeout_s=300)
                stats = gw.stats()
                cold = fleet.get(r2["replica"])
                saved = (cold.engine.kv.stats().prefill_tokens_saved
                         if cold is not None else 0)
                return {
                    "ttft_ms": r2["ttft_ms"],
                    "cold_replica": r2["replica"],
                    "warm_replica": r1["replica"],
                    "import_from": r2.get("kv_import_from"),
                    "imports": stats.get("kvtier_imports", 0),
                    "import_bytes": stats.get("kvtier_import_bytes", 0),
                    "fallbacks": stats.get(
                        "kvtier_reprefill_fallbacks", 0),
                    "prefill_tokens_saved": saved,
                }
            finally:
                gw.close()

        _log(f"kvtier: two-replica fleet, {shared_len}-token shared "
             f"prefix, cross-replica import vs forced re-prefill...")
        imp = run_side(True)
        base = run_side(False)
        _log(f"kvtier: import TTFT {imp['ttft_ms']} ms "
             f"({imp['imports']} imports, "
             f"{imp['prefill_tokens_saved']} tokens saved) vs re-prefill "
             f"TTFT {base['ttft_ms']} ms")
        return {
            # the headline: cold-replica TTFT with the sibling import
            "kvtier_prefix_import_ttft_ms": imp["ttft_ms"],
            # the counterfactual: same fleet, index off, full re-prefill
            "kvtier_reprefill_ttft_ms": base["ttft_ms"],
            "kvtier_imports": imp["imports"],
            "kvtier_import_bytes": imp["import_bytes"],
            "kvtier_import_from": imp["import_from"],
            # tier hit/miss per row: hits = staged imports that landed,
            # misses = fallbacks (failed stagings) + the index-off side's
            # structural miss (always re-prefills)
            "kvtier_tier_hits": imp["imports"],
            "kvtier_tier_misses": imp["fallbacks"] + 1,
            "kvtier_prefill_tokens_saved": imp["prefill_tokens_saved"],
            "kvtier_shared_prefix_tokens": shared_len,
        }
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"kvtier skipped: {type(e).__name__}: {e}")
        return {}


def _percentile(values, q: float):
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[idx]


def slo_measurement(jax, cfg, params, *, slots: int, page_size: int,
                    long_prompt_len: int, new_tokens: int,
                    n_victim: int, prefill_budget: int):
    """Multi-tenant SLO isolation point: victim TTFT p99 under a
    long-prompt aggressor, with the SLO layer (rate limits + KV quota +
    WFQ + chunked prefill) ON vs OFF on the same paged gateway shape.
    The bursty two-tenant workload is the ISSUE-7 scenario: aggressor
    threads hammer 100+-token prompts as fast as admission lets them
    while the victim issues short interactive prompts; the ON/OFF delta
    is the number the layer exists for. Wrapped so a hiccup never loses
    the headline metric."""
    try:
        import threading as _threading

        from lzy_tpu.gateway import (
            GatewayService, PrefixAffinityRouter, ReplicaFleet)
        from lzy_tpu.serving import (
            PagedInferenceEngine, QuotaExceeded, SloLimiter, TenantPolicy,
            TenantTable)

        long_p = max(page_size, long_prompt_len - long_prompt_len
                     % page_size)

        def run_side(slo_on: bool):
            table = None
            if slo_on:
                table = TenantTable(default=TenantPolicy())
                table.set_policy(TenantPolicy(
                    tenant="agg", priority=2, requests_per_s=20.0,
                    burst_s=0.5, max_queued=2,
                    kv_block_quota=3 * (long_p // page_size)))
                table.set_policy(TenantPolicy(tenant="vic", priority=0))
            fleet = ReplicaFleet(lambda: PagedInferenceEngine(
                cfg, params, slots=slots, page_size=page_size,
                max_queue=64, tenants=table,
                prefill_budget=prefill_budget if slo_on else None,
            ).start())
            gw = GatewayService(
                fleet, router=PrefixAffinityRouter(page_size),
                model_name="bench", max_waiters=2 * slots + 4,
                slo=SloLimiter(table) if table is not None else None)
            rejections = 0
            try:
                fleet.add_replica()
                # warm both shapes (prefill buckets + decode) off-clock
                gw.generate(list(range(1, long_p + 1)),
                            max_new_tokens=2, timeout_s=600)
                gw.generate([2, 3], max_new_tokens=2, timeout_s=600)
                stop = _threading.Event()

                def aggress(tid):
                    nonlocal rejections
                    i = 0
                    while not stop.is_set():
                        prompt = [(tid * 31 + 5 * i + j) % 50 + 1
                                  for j in range(long_p)]
                        try:
                            gw.generate(prompt, max_new_tokens=new_tokens,
                                        timeout_s=600, tenant="agg")
                        except QuotaExceeded as e:
                            rejections += 1
                            time.sleep(min(e.retry_after_s or 0.01, 0.05))
                        except Exception:  # noqa: BLE001 — keep hammering
                            time.sleep(0.01)
                        i += 1

                threads = [_threading.Thread(target=aggress, args=(t,),
                                             daemon=True)
                           for t in range(3)]
                for t in threads:
                    t.start()
                time.sleep(0.3)       # let the burst build
                ttfts = []
                for i in range(n_victim):
                    res = gw.generate([7, i % 40 + 2, 9],
                                      max_new_tokens=new_tokens,
                                      timeout_s=600, tenant="vic")
                    if res.get("ttft_ms") is not None:
                        ttfts.append(res["ttft_ms"])
                    time.sleep(0.01)  # bursty-interactive cadence
                stop.set()
                for t in threads:
                    t.join(timeout=60)
            finally:
                gw.close()
            return ttfts, rejections

        _log(f"slo: two-tenant burst, long prompt {long_p}, "
             f"{n_victim} victim probes, budget {prefill_budget}...")
        on_ttfts, on_rejections = run_side(slo_on=True)
        off_ttfts, _ = run_side(slo_on=False)
        p99_on = _percentile(on_ttfts, 0.99)
        p99_off = _percentile(off_ttfts, 0.99)
        _log(f"slo: victim TTFT p99 {p99_on} ms (SLO on) vs {p99_off} ms "
             f"(off); aggressor rejections {on_rejections}")
        return {"slo_ttft_p99_ms": p99_on,
                "slo_ttft_p99_ms_unprotected": p99_off,
                "slo_victim_ttft_p50_ms": _percentile(on_ttfts, 0.5),
                "slo_aggressor_rejections": on_rejections,
                "slo_prefill_budget": prefill_budget}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"slo skipped: {type(e).__name__}: {e}")
        return {}


def llm_op_pipeline_measurement(jax, cfg, params, *, replicas: int,
                                slots: int, page_size: int,
                                prompt_len: int, new_tokens: int,
                                n_conversations: int, steps: int):
    """Workflow-native inference point: interleaved multi-step
    conversations (``llm.generate → tool op → llm.generate``) driven
    through the WORKFLOW surface against a paged gateway fleet, next to
    the same traffic as raw gateway submits — the surface-cost number —
    and with session affinity on vs round-robin routing — the
    conversation-locality number (aggregate radix prefix hit rate).
    Wrapped so a hiccup never loses the headline metric."""
    try:
        from concurrent import futures as _futures

        from lzy_tpu import Lzy, llm, op
        from lzy_tpu.gateway import (
            GatewayService, PrefixAffinityRouter, ReplicaFleet,
            RoundRobinRouter)
        from lzy_tpu.serving import PagedInferenceEngine
        from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig

        @op
        def extend(g, extra: list) -> list:
            return g.full_tokens() + list(extra)

        base_len = max(page_size, prompt_len - prompt_len % page_size)
        prompts = [list(range(1, base_len + 1)) + [i % 50 + 2]
                   for i in range(n_conversations)]

        def build_gw(router):
            fleet = ReplicaFleet(lambda: PagedInferenceEngine(
                cfg, params, slots=slots, page_size=page_size,
                max_queue=4 * n_conversations))
            gw = GatewayService(fleet, router=router, model_name="bench",
                                max_waiters=replicas * slots + 2)
            for _ in range(replicas):
                fleet.add_replica()
            # warm prefill buckets + decode once, off-clock
            gw.generate(prompts[0], max_new_tokens=2, timeout_s=600)
            return gw, fleet

        def drive_workflow(router, tag):
            """steps rounds of one llm_op per conversation, rounds
            barriered (step N+1 needs step N's output), conversations
            fanning out through the graph executor's concurrency."""
            gw, fleet = build_gw(router)
            try:
                llm.configure(gw)
                reg = DefaultStorageRegistry()
                reg.register_storage(
                    "default",
                    StorageConfig(uri=f"mem://bench-llm-{tag}"),
                    default=True)
                lzy = Lzy(storage_registry=reg)
                convs = [llm.Conversation(f"bench-{tag}-{i}")
                         for i in range(n_conversations)]
                total = 0
                t0 = time.perf_counter()
                with lzy.workflow(f"bench-{tag}") as wf:
                    cur = [list(p) for p in prompts]
                    for s in range(steps):
                        gens = []
                        for i, conv in enumerate(convs):
                            g = llm.generate(
                                cur[i], max_new_tokens=new_tokens,
                                greedy=True, cache=False,
                                conversation=conv, timeout_s=600)
                            gens.append(g)
                            cur[i] = extend(g, [60 + i + s])
                        wf.barrier()
                        total += sum(len(list(g.tokens)) for g in gens)
                dt = time.perf_counter() - t0
                agg = fleet.aggregate()
                hit = (agg["prefix_hit_tokens"]
                       / max(1, agg["prefix_lookup_tokens"]))
                return total / dt, round(hit, 4)
            finally:
                llm.configure(None)
                gw.close()

        def drive_raw():
            """The same conversation traffic as raw gateway submits —
            no workflow graph, no session hint (the pre-llm_op client
            shape)."""
            gw, _fleet = build_gw(PrefixAffinityRouter(page_size))
            try:
                def one_conv(i):
                    cur, n = list(prompts[i]), 0
                    for s in range(steps):
                        res = gw.generate(cur,
                                          max_new_tokens=new_tokens,
                                          timeout_s=600, greedy=True)
                        n += len(res["tokens"])
                        cur = cur + res["tokens"] + [60 + i + s]
                    return n
                t0 = time.perf_counter()
                with _futures.ThreadPoolExecutor(n_conversations) as pool:
                    total = sum(pool.map(one_conv,
                                         range(n_conversations)))
                return total / (time.perf_counter() - t0)
            finally:
                gw.close()

        _log(f"llm_op pipeline: {n_conversations} conversations x "
             f"{steps} steps x {new_tokens} tokens, {replicas} "
             f"replicas...")
        tps_aff, hit_aff = drive_workflow(
            PrefixAffinityRouter(page_size), "aff")
        _tps_rr, hit_rr = drive_workflow(RoundRobinRouter(), "rr")
        tps_raw = drive_raw()
        _log(f"llm_op pipeline: {tps_aff:.1f} tok/s via workflow "
             f"(raw gateway {tps_raw:.1f}); radix hit rate "
             f"{hit_aff} affinity vs {hit_rr} round-robin")
        return {"llm_op_pipeline_tokens_per_s": round(tps_aff, 1),
                "llm_op_raw_gateway_tokens_per_s": round(tps_raw, 1),
                "llm_op_affinity_prefix_hit_rate": hit_aff,
                "llm_op_rr_prefix_hit_rate": hit_rr,
                "llm_op_conversations": n_conversations,
                "llm_op_steps": steps}
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"llm_op pipeline skipped: {type(e).__name__}: {e}")
        return {}


def agent_pipeline_measurement(jax, cfg, params, *, replicas: int,
                               slots: int, page_size: int,
                               prompt_len: int, new_tokens: int,
                               n_conversations: int, steps: int):
    """Workflow-aware scheduling point (lzy_tpu/llm/sched.py): the SAME
    agent-pipeline trace — interleaved ``generate → tool op → generate``
    chains — driven FUSED (KV parked across the tool gap + speculative
    next-step prefill, the default) and UNFUSED (``LZY_WFSCHED_FUSE=0``),
    reporting per-step TTFT past step 1 (where the pin and the
    speculation can pay), pipeline throughput, and the admission fan-in
    plane's dedup numbers (identical in-flight greedy rows reaching the
    fleet as ONE engine request). Runs in the CPU-fallback round with
    scaled-down shapes. Wrapped so a hiccup never loses the headline."""
    try:
        from lzy_tpu import Lzy, llm, op
        from lzy_tpu.gateway import (
            GatewayService, PrefixAffinityRouter, ReplicaFleet)
        from lzy_tpu.serving import PagedInferenceEngine
        from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig

        @op
        def extend(g, extra: list) -> list:
            return g.full_tokens() + list(extra)

        base_len = max(page_size, prompt_len - prompt_len % page_size)
        prompts = [list(range(1, base_len + 1)) + [i % 50 + 2]
                   for i in range(n_conversations)]

        def build_gw():
            fleet = ReplicaFleet(lambda: PagedInferenceEngine(
                cfg, params, slots=slots, page_size=page_size,
                max_queue=4 * n_conversations))
            gw = GatewayService(fleet,
                                router=PrefixAffinityRouter(page_size),
                                model_name="bench",
                                max_waiters=replicas * slots + 2)
            for _ in range(replicas):
                fleet.add_replica()
            # warm prefill buckets + decode once, off-clock
            gw.generate(prompts[0], max_new_tokens=2, timeout_s=600)
            return gw, fleet

        def lzy_for(tag):
            reg = DefaultStorageRegistry()
            reg.register_storage(
                "default", StorageConfig(uri=f"mem://bench-agent-{tag}"),
                default=True)
            return Lzy(storage_registry=reg)

        def drive(tag, fused):
            """The pipeline trace once; returns (tok/s, mean TTFT of
            steps >= 2, scheduler stats)."""
            saved = {k: os.environ.get(k)
                     for k in ("LZY_WFSCHED_FUSE", "LZY_WFSCHED_SPECULATE")}
            if not fused:
                os.environ["LZY_WFSCHED_FUSE"] = "0"
                os.environ["LZY_WFSCHED_SPECULATE"] = "0"
            gw, fleet = build_gw()
            try:
                llm.configure(gw)      # scheduler reads the flags here
                lzy = lzy_for(tag)
                convs = [llm.Conversation(f"agent-{tag}-{i}")
                         for i in range(n_conversations)]
                step_ttft, total = [], 0
                t0 = time.perf_counter()
                with lzy.workflow(f"agent-{tag}") as wf:
                    cur = [list(p) for p in prompts]
                    for s in range(steps):
                        gens = []
                        for i, conv in enumerate(convs):
                            g = llm.generate(
                                cur[i], max_new_tokens=new_tokens,
                                greedy=True, cache=False,
                                conversation=conv, timeout_s=600)
                            gens.append(g)
                            cur[i] = extend(g, [60 + i + s])
                        wf.barrier()
                        if s >= 1:     # step 1 has no pin either way
                            step_ttft += [g.ttft_ms for g in gens
                                          if g.ttft_ms is not None]
                        total += sum(len(list(g.tokens)) for g in gens)
                dt = time.perf_counter() - t0
                sched = llm.current_scheduler()
                stats = sched.stats() if sched is not None else {}
                ttft = (sum(step_ttft) / len(step_ttft)
                        if step_ttft else None)
                return total / dt, ttft, stats
            finally:
                llm.configure(None)
                gw.close()
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        _log(f"agent pipeline: {n_conversations} chains x {steps} steps "
             f"x {new_tokens} tokens, {replicas} replicas, fused vs "
             f"unfused...")
        tps_fused, ttft_fused, fstats = drive("fused", True)
        tps_plain, ttft_plain, _ = drive("plain", False)

        # the fan-in plane: identical in-flight greedy rows must reach
        # the fleet as exactly ONE engine request
        gw, fleet = build_gw()
        try:
            llm.configure(gw)
            lzy = lzy_for("fanin")
            n_rows = max(4, n_conversations)
            base = gw.stats()["requests_finished"]
            with lzy.workflow("agent-fanin"):
                outs = llm.generate_batch(
                    [list(prompts[0])] * n_rows,
                    max_new_tokens=new_tokens, greedy=True,
                    cache=False, timeout_s=600)
            n_rows = len(list(outs))
            fanin_requests = gw.stats()["requests_finished"] - base
            sched = llm.current_scheduler()
            dedup_hits = (sched.stats()["dedup_hits"]
                          if sched is not None else 0)
        finally:
            llm.configure(None)
            gw.close()

        _log(f"agent pipeline: fused {tps_fused:.1f} tok/s, step TTFT "
             f"{ttft_fused} ms (unfused {tps_plain:.1f} tok/s, "
             f"{ttft_plain} ms); parks {fstats.get('parks', 0)}, "
             f"speculations {fstats.get('speculations', 0)}; fan-in "
             f"{n_rows} rows -> {fanin_requests} engine requests "
             f"({dedup_hits} dedup hits)")
        out = {"agent_pipeline_fused_tokens_per_s": round(tps_fused, 1),
               "agent_pipeline_unfused_tokens_per_s": round(tps_plain, 1),
               "agent_pipeline_fused_parks": fstats.get("parks", 0),
               "agent_pipeline_fused_speculations":
                   fstats.get("speculations", 0),
               "agent_pipeline_fanin_rows": n_rows,
               "agent_pipeline_fanin_engine_requests": fanin_requests,
               "agent_pipeline_dedup_hits": dedup_hits}
        if ttft_fused is not None:
            out["agent_pipeline_fused_step_ttft_ms"] = round(ttft_fused, 3)
        if ttft_plain is not None:
            out["agent_pipeline_unfused_step_ttft_ms"] = \
                round(ttft_plain, 3)
        return out
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"agent pipeline skipped: {type(e).__name__}: {e}")
        return {}


def stream_measurement(jax, cfg, params, *, slots: int, prompt_len: int,
                       new_tokens: int):
    """Best-effort streaming-delivery point (docs/serving.md "Streaming
    delivery"): TTFT (open → first frame) and inter-token p99 over the
    chunked long-poll surface (``serving/streams``) — the exact
    open/poll/ack path ``InferStream`` serves over gRPC, minus the wire,
    so the number isolates the session layer's delivery cadence next to
    the engine's own decode rate. Rides the CPU-fallback path like
    every serving probe."""
    try:
        import numpy as np

        from lzy_tpu.serving import InferenceEngine
        from lzy_tpu.service.inference import InferenceService

        engine = InferenceEngine(cfg, params, slots=slots).start()
        svc = InferenceService(engine, model_name="bench")
        try:
            rng = np.random.default_rng(3)
            prompt = [int(t) for t in rng.integers(
                1, cfg.vocab_size, prompt_len)]
            _log("stream: warming the decode path...")
            svc.generate(prompt, max_new_tokens=4, greedy=True,
                         timeout_s=600)
            _log(f"stream: timing long-poll delivery of {new_tokens} "
                 f"tokens...")
            t_open = time.perf_counter()
            opened = svc.streams.open(prompt, max_new_tokens=new_tokens,
                                      greedy=True, timeout_s=600)
            rid = opened["request_id"]
            arrivals = []
            pos = 0
            ttft = None
            while True:
                frame = svc.streams.poll(rid, pos, wait_s=0.5)
                now = time.perf_counter()
                n = len(frame["tokens"])
                if n and ttft is None:
                    ttft = now - t_open
                arrivals.extend([now] * n)
                pos += n
                if frame["done"]:
                    break
            gaps = (np.diff(np.asarray(arrivals))
                    if len(arrivals) > 1 else np.asarray([0.0]))
            p99 = float(np.quantile(gaps, 0.99))
            _log(f"stream: ttft {1000 * (ttft or 0):.1f} ms, "
                 f"inter-token p99 {1000 * p99:.2f} ms over {pos} "
                 f"tokens")
            return {
                "stream_ttft_ms": round(1000 * (ttft or 0.0), 3),
                "stream_inter_token_p99_ms": round(1000 * p99, 3),
                "stream_tokens": pos,
            }
        finally:
            svc.close()
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"stream skipped: {type(e).__name__}: {e}")
        return {}


def gateway_restart_measurement(jax, cfg, params, *, replicas: int,
                                slots: int, prompt_len: int,
                                new_tokens: int):
    """Best-effort control-plane recovery point (docs/serving.md
    "Control-plane recovery"): kill a journal-backed gateway mid-stream,
    recover a successor (lease re-adoption + fence resubmission), and
    time kill → FIRST post-restart token at the fence — the
    client-visible blackout of a gateway death. Also checks the resumed
    stream is byte-identical to the pre-kill prefix + an uninterrupted
    continuation (greedy), so the number is only reported for a CORRECT
    recovery. Rides the CPU-fallback path like every serving probe."""
    try:
        import numpy as np

        from lzy_tpu.durable.store import OperationStore
        from lzy_tpu.gateway import (
            GatewayJournal, GatewayService, PrefixAffinityRouter,
            ReplicaFleet, recover_gateway, simulate_gateway_death)
        from lzy_tpu.serving import InferenceEngine

        _log(f"gwreco: building {replicas} journal-backed replicas...")
        journal = GatewayJournal(OperationStore(":memory:"))

        def factory():
            return InferenceEngine(cfg, params, slots=slots)

        fleet = ReplicaFleet(factory)
        gw = GatewayService(fleet, router=PrefixAffinityRouter(8),
                            model_name="bench", journal=journal)
        for _ in range(replicas):
            fleet.add_replica()
        rng = np.random.default_rng(5)
        prompt = [int(t) for t in rng.integers(1, cfg.vocab_size,
                                               prompt_len)]
        # warm the decode path so the timed window measures RECOVERY,
        # not a first-compile
        gw.generate(prompt, max_new_tokens=2, greedy=True,
                    timeout_s=600)
        opened = gw.streams.open(prompt, max_new_tokens=new_tokens,
                                 greedy=True, timeout_s=600)
        rid = opened["request_id"]
        pos, seen = 0, []
        deadline = time.perf_counter() + 300
        # fast short polls: the kill must land MID-decode, before the
        # tiny bench model races through the whole budget
        while len(seen) < 2 and time.perf_counter() < deadline:
            frame = gw.streams.poll(rid, pos, wait_s=0.02)
            seen.extend(frame["tokens"])
            pos += len(frame["tokens"])
            if frame["done"]:
                break
        if pos >= new_tokens:
            _log("gwreco skipped: generation finished before the kill "
                 "(model too fast for a mid-decode death)")
            gw.close()
            return {}
        _log(f"gwreco: killing the gateway at fence {pos}...")
        engines = {r.id: r.engine for r in fleet.replicas()}
        t_kill = time.perf_counter()
        simulate_gateway_death(gw)
        fleet2 = ReplicaFleet(factory)
        gw2 = GatewayService(fleet2, router=PrefixAffinityRouter(8),
                             model_name="bench", journal=journal)
        report = recover_gateway(
            gw2, engine_source=lambda r, vms: engines.get(r))
        # first post-restart token AT THE FENCE via the original token
        first_token_ms = None
        final = list(seen)
        while time.perf_counter() < deadline:
            frame = gw2.streams.poll(rid, pos, wait_s=1.0)
            if frame["tokens"] and first_token_ms is None:
                first_token_ms = 1000 * (time.perf_counter() - t_kill)
            final.extend(frame["tokens"])
            pos += len(frame["tokens"])
            if frame["done"]:
                break
        gw2.close()
        if first_token_ms is None or len(final) != new_tokens:
            _log("gwreco skipped: the resumed stream never finished")
            return {}
        if final[:len(seen)] != seen:
            _log("gwreco skipped: fence divergence (NOT reporting a "
                 "broken recovery as a latency number)")
            return {}
        _log(f"gwreco: kill -> first post-restart token "
             f"{first_token_ms:.1f} ms ({len(report.adopted)} adopted, "
             f"{len(report.resubmitted)} resubmitted, recovery "
             f"{1000 * report.recovery_s:.1f} ms)")
        return {
            "gateway_restart_recovery_ms": round(first_token_ms, 3),
            "gateway_restart_adopted": len(report.adopted),
            "gateway_restart_recovery_internal_ms": round(
                1000 * report.recovery_s, 3),
        }
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"gwreco skipped: {type(e).__name__}: {e}")
        return {}


def capacity_curve_measurement():
    """Best-effort operating-curve point (docs/serving.md "Capacity &
    load testing"): the lzy_tpu/load virtual-clock harness replays a
    synthetic multi-tenant trace against fleet-in-threads SimEngine
    gateways and reports TTFT/inter-token p99 vs replica count plus a
    shed-rate frontier — the capacity-model numbers ROADMAP item 3 asks
    bench rounds to publish. Pure CPU + virtual time (no accelerator,
    no model), so it rides the CPU-fallback path unchanged; the replay
    speedup factor (virtual seconds per wall second) is the honesty
    metric that these are simulated hours, not wall hours."""
    try:
        from lzy_tpu.load import (
            FleetConfig, SimProfile, TraceConfig, capacity_artifact)

        _log("capacity: replaying synthetic traces on the virtual "
             "clock (replicas 1/2/4 + overload frontier)...")
        trace = TraceConfig(seed=0, duration_s=480.0, users=24,
                            tenants=8)
        fleet = FleetConfig(replicas=2, profile=SimProfile(
            slots=8, max_queue=48, kv_blocks=384))
        frontier_fleet = FleetConfig(replicas=1, retry_limit=3,
                                     profile=SimProfile(
                                         slots=4, max_queue=16,
                                         kv_blocks=160))
        art = capacity_artifact(trace, fleet, replica_counts=[1, 2, 4],
                                load_factors=[1.0, 5.0],
                                frontier_fleet_cfg=frontier_fleet)
        slo = {str(r["replicas"]): {
            "ttft_p50_ms": r["ttft_p50_ms"],
            "ttft_p99_ms": r["ttft_p99_ms"],
            "itl_p99_ms": r["itl_p99_ms"],
            "requests": r["requests"],
        } for r in art["slo_curve"]}
        frontier = {str(r["load_factor"]): {
            "shed_rate": r["shed_rate"],
            "ttft_p99_ms": r["ttft_p99_ms"],
            "peak_queue_depth": r["peak_queue_depth"],
        } for r in art["shed_frontier"]}
        rep = art["replay"]
        _log(f"capacity: {rep['virtual_s']:.0f} virtual s in "
             f"{rep['wall_s']:.1f}s wall ({rep['speedup_x']:.0f}x); "
             f"ttft p99 by replicas: "
             + ", ".join(f"{k}: {v['ttft_p99_ms']:.0f}ms"
                         for k, v in sorted(slo.items())))
        return {
            "capacity_slo_curve": slo,
            "capacity_shed_frontier": frontier,
            "capacity_virtual_s": rep["virtual_s"],
            "capacity_replay_speedup_x": rep["speedup_x"],
            "capacity_virtual_hours_per_wall_s":
                rep["virtual_hours_per_wall_s"],
        }
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"capacity skipped: {type(e).__name__}: {e}")
        return {}


def step_breakdown(jax, loss_fn, params, batch, step_ms: float, n: int = 5):
    """Best-effort fwd/bwd/opt decomposition of the step time.

    Times a jitted forward (loss only) and a jitted value_and_grad; the
    optimizer share is the remainder of the full step. Two extra compiles —
    wrapped so a backend hiccup here never loses the headline metric.
    Caller must have freed the optimizer moments: params + grads + the
    bwd activations only fit in HBM without them.
    """
    try:
        _log("breakdown: timing fwd-only...")

        def timed(fn, *args):
            fn(*args)  # compile + first-run cost
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn(*args)
            # hard sync (see note above): pull one scalar leaf to the host
            leaf = jax.tree_util.tree_leaves(out)[0]
            float(jax.numpy.ravel(leaf)[0])
            return 1000 * (time.perf_counter() - t0) / n

        fwd_ms = timed(jax.jit(loss_fn), params, batch)
        _log("breakdown: timing fwd+bwd...")
        grad_ms = timed(jax.jit(jax.value_and_grad(loss_fn)), params, batch)
        return {
            "fwd_ms": round(fwd_ms, 2),
            "bwd_ms": round(max(grad_ms - fwd_ms, 0.0), 2),
            "opt_ms": round(max(step_ms - grad_ms, 0.0), 2),
        }
    except Exception as e:  # noqa: BLE001 — diagnostics only
        _log(f"breakdown skipped: {type(e).__name__}: {e}")
        return {}


def _apply_platform_contract() -> None:
    """Honor JAX_PLATFORMS at the config level in bench children: the
    pinned axon plugin on this host overrides env vars, so a cpu-platform
    bench run (local verify, CI) would otherwise hang all four probes
    against the dead relay (worker_main/__graft_entry__ recipe)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat or plat == "axon":
        return  # axon is the plugin's own default path
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001 — older jax without the option
        pass


def probe() -> None:
    """Child probe: init the backend under a 120 s watchdog, print one line."""
    _apply_platform_contract()
    try:
        devices = init_devices(120.0)
    except Exception as e:  # noqa: BLE001 — reported to the supervisor
        print(f"init failed: {e}", flush=True)
        # hard-exit: a hung daemon init thread can block normal interpreter
        # teardown past the supervisor's margin
        os._exit(1)
    print(f"ok: {len(devices)}x {devices[0].platform}", flush=True)
    os._exit(0)


if __name__ == "__main__":
    if "--run" in sys.argv:
        run()
    elif "--probe" in sys.argv:
        probe()
    elif "--sharded-probe" in sys.argv:
        sharded_probe_child()
    else:
        supervise()
