"""Benchmark: flagship train-step MFU on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline per BASELINE.md north star: 40% MFU for an @op train step
(the reference publishes no numbers of its own; 0.40 MFU is the target the
TPU build must reach, so vs_baseline = achieved_mfu / 0.40).

Runs on whatever jax.devices() provides: the driver's single real TPU chip,
or CPU for local sanity (tiny shapes, placeholder peak).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import optax


def pick_config(platform: str):
    """Model + batch sized for the target: ~350M-param Llama on one v5e chip
    (fits params + adam moments in 16 GB HBM with room for activations)."""
    from lzy_tpu.models.llama import LlamaConfig

    if platform in ("tpu", "axon"):
        cfg = LlamaConfig(
            vocab_size=32_768, d_model=1024, n_layers=20, n_heads=8,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
            tie_embeddings=True, use_flash_kernel=True,
        )
        batch_size, seq_len = 8, 2048
        steps, warmup = 20, 3
    else:
        cfg = LlamaConfig.tiny(vocab_size=2048)
        batch_size, seq_len = 4, 128
        steps, warmup = 3, 1
    return cfg, batch_size, seq_len, steps, warmup


def main() -> None:
    from lzy_tpu.models import count_params, llama, unbox
    from lzy_tpu.parallel import PEAK_TFLOPS, TrainState, make_train_step, mesh_for, mfu

    devices = jax.devices()
    platform = devices[0].platform
    chip = "v5e" if platform in ("tpu", "axon") else "cpu"
    cfg, batch_size, seq_len, steps, warmup = pick_config(platform)

    mesh = mesh_for(fsdp=-1)
    boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = unbox(boxed)
    n_params = count_params(params)

    tx = optax.adamw(3e-4)
    step, shard_state, _ = make_train_step(
        llama.make_loss_fn(cfg), tx, mesh=mesh, param_logical_axes=axes,
        batch_logical_axes=("batch", "seq"),
    )
    state = shard_state(TrainState.create(params, tx))
    batch = {
        "tokens": jax.random.randint(
            jax.random.PRNGKey(1), (batch_size, seq_len), 0, cfg.vocab_size
        )
    }

    # hard sync via host transfer: each step consumes the previous state, so
    # materializing the last loss proves the whole chain executed
    # (block_until_ready alone does not flush on relayed TPU platforms)
    for _ in range(warmup):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_s = batch_size * seq_len * steps / dt
    achieved_mfu = mfu(tokens_per_s, n_params, len(devices), chip=chip)

    print(json.dumps({
        "metric": "llama_train_step_mfu",
        "value": round(achieved_mfu, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(achieved_mfu / 0.40, 4),
        "detail": {
            "platform": platform,
            "chips": len(devices),
            "params": n_params,
            "tokens_per_s": round(tokens_per_s, 1),
            "step_time_ms": round(1000 * dt / steps, 2),
            "batch": batch_size,
            "seq_len": seq_len,
        },
    }))


if __name__ == "__main__":
    main()
