"""IAM, version-gate, execution-GC, and CLI tests."""

import subprocess
import sys
import time

import pytest

from lzy_tpu import Lzy, op
from lzy_tpu.iam import (
    READER,
    WORKFLOW_MANAGE,
    WORKFLOW_RUN,
    AuthError,
    IamService,
)
from lzy_tpu.durable import OperationStore
from lzy_tpu.service import InProcessCluster


@op
def plus_one(x: int) -> int:
    return x + 1


@pytest.fixture()
def auth_cluster(tmp_path):
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"), with_iam=True)
    yield c
    c.shutdown()


class TestIam:
    def test_token_roundtrip(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        token = iam.create_subject("alice")
        subject = iam.authenticate(token)
        assert subject.id == "alice" and subject.role == "OWNER"
        store.close()

    def test_bad_tokens_rejected(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        token = iam.create_subject("alice")
        with pytest.raises(AuthError, match="malformed"):
            iam.authenticate("garbage")
        with pytest.raises(AuthError, match="signature"):
            iam.authenticate(token[:-4] + "0000")
        iam.remove_subject("alice")
        with pytest.raises(AuthError, match="unknown subject"):
            iam.authenticate(token)
        store.close()

    def test_token_expiry(self, tmp_path):
        import time

        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store, max_token_age_s=0.0)
        token = iam.create_subject("alice")
        time.sleep(1.1)  # issued_at has 1 s resolution
        with pytest.raises(AuthError, match="expired"):
            iam.authenticate(token)
        store.close()

    def test_token_rotation_revokes_old_generation(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        old = iam.create_subject("alice")
        assert iam.authenticate(old).id == "alice"
        new = iam.rotate_subject("alice")
        with pytest.raises(AuthError, match="revoked"):
            iam.authenticate(old)
        assert iam.authenticate(new).id == "alice"
        store.close()

    def test_ott_redeems_exactly_once(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        iam.create_subject("vm/v1", kind="WORKER", role="WORKER")
        ott = iam.issue_ott("vm/v1")
        assert iam.is_ott(ott) and not iam.is_ott("vm/v1:1:0:sig")
        # an OTT is not a bearer token
        with pytest.raises(AuthError):
            iam.authenticate(ott)
        assert iam.redeem_ott(ott) == "vm/v1"
        with pytest.raises(AuthError, match="already redeemed|unknown"):
            iam.redeem_ott(ott)
        store.close()

    def test_ott_subject_mismatch_does_not_burn(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        ott = iam.issue_ott("vm/a")
        # probing with the wrong subject refuses WITHOUT consuming…
        with pytest.raises(AuthError, match="vm/a"):
            iam.redeem_ott(ott, expect_subject="vm/b")
        # …so the legitimate holder still boots
        assert iam.redeem_ott(ott, expect_subject="vm/a") == "vm/a"
        store.close()

    def test_expired_otts_swept_from_store(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        iam.issue_ott("vm/dead", ttl_s=-1.0)
        assert len(store.kv_list(IamService._OTT_NS)) == 1
        iam.issue_ott("vm/live")          # sweep runs on every issue
        assert len(store.kv_list(IamService._OTT_NS)) == 1
        store.close()

    def test_ott_expires(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        ott = iam.issue_ott("vm/v1", ttl_s=-1.0)
        with pytest.raises(AuthError, match="expired"):
            iam.redeem_ott(ott)
        # expiry consumed it too: no second chance to race the clock
        with pytest.raises(AuthError, match="already redeemed|unknown"):
            iam.redeem_ott(ott)
        store.close()

    def test_secret_survives_restart(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        token = IamService(store).create_subject("alice")
        # "rebooted" service over the same store validates old tokens
        assert IamService(store).authenticate(token).id == "alice"
        store.close()

    def test_reader_cannot_run_workflows(self, tmp_path):
        store = OperationStore(str(tmp_path / "iam.db"))
        iam = IamService(store)
        token = iam.create_subject("bob", role=READER)
        subject = iam.authenticate(token)
        with pytest.raises(AuthError, match="lacks"):
            iam.authorize(subject, WORKFLOW_RUN)
        iam.authorize(subject, "workflow.read")
        store.close()

    def test_workflow_requires_token(self, auth_cluster):
        lzy = auth_cluster.lzy()  # no token
        with pytest.raises(AuthError):
            with lzy.workflow("wf"):
                pass

    def test_workflow_with_token_runs(self, auth_cluster):
        token = auth_cluster.iam.create_subject("alice")
        lzy = auth_cluster.lzy(token=token)
        with lzy.workflow("wf"):
            assert plus_one(1) == 2

    def test_execution_id_cannot_be_hijacked(self, auth_cluster):
        """Re-starting an existing execution id must be rejected, or another
        subject could overwrite ownership and orphan the session."""
        from lzy_tpu import __version__

        alice = auth_cluster.iam.create_subject("alice")
        mallory = auth_cluster.iam.create_subject("mallory")
        execution_id = auth_cluster.client.start_workflow(
            "alice", "wf", "mem://x", token=alice, client_version=__version__
        )
        with pytest.raises(RuntimeError, match="already exists"):
            auth_cluster.client.start_workflow(
                "mallory", "wf", "mem://x", execution_id=execution_id,
                token=mallory, client_version=__version__,
            )
        auth_cluster.client.finish_workflow(execution_id, token=alice)

    def test_other_user_cannot_touch_execution(self, auth_cluster):
        alice = auth_cluster.iam.create_subject("alice")
        mallory = auth_cluster.iam.create_subject("mallory")
        lzy = auth_cluster.lzy(token=alice)
        with lzy.workflow("wf") as wf:
            plus_one(1)
            with pytest.raises(AuthError, match="does not own"):
                auth_cluster.client.abort_workflow(
                    wf.execution_id, token=mallory
                )


class TestVersionGate:
    def test_old_client_rejected(self, auth_cluster):
        token = auth_cluster.iam.create_subject("alice")
        with pytest.raises(RuntimeError, match="unsupported client version"):
            auth_cluster.client.start_workflow(
                "alice", "wf", "mem://x", token=token, client_version="0.0.1"
            )

    def test_versionless_client_rejected(self, auth_cluster):
        """Pre-gate SDKs send no version at all — exactly who the gate is for."""
        token = auth_cluster.iam.create_subject("alice")
        with pytest.raises(RuntimeError, match="unsupported client version"):
            auth_cluster.client.start_workflow(
                "alice", "wf", "mem://x", token=token
            )

    def test_current_client_accepted(self, auth_cluster):
        from lzy_tpu import __version__

        token = auth_cluster.iam.create_subject("alice")
        execution_id = auth_cluster.client.start_workflow(
            "alice", "wf", "mem://x", token=token, client_version=__version__
        )
        auth_cluster.client.finish_workflow(execution_id, token=token)


class TestExecutionGc:
    def test_stale_active_execution_reaped(self, tmp_path):
        cluster = InProcessCluster(db_path=str(tmp_path / "meta.db"))
        try:
            from lzy_tpu import __version__

            execution_id = cluster.client.start_workflow(
                "u", "wf", "mem://x", client_version=__version__
            )
            doc = cluster.store.kv_get("executions", execution_id)
            doc["started_at"] = time.time() - 100_000
            cluster.store.kv_put("executions", execution_id, doc)
            reaped = cluster.workflow_service.gc_tick(ttl_s=3600)
            assert reaped == [execution_id]
            assert cluster.store.kv_get(
                "executions", execution_id)["status"] == "ABORTED"
            assert cluster.workflow_service.gc_tick(ttl_s=3600) == []
        finally:
            cluster.shutdown()


class TestCli:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "lzy_tpu", *args],
            capture_output=True, text=True, cwd="/root/repo", timeout=120,
        )

    def test_version(self):
        from lzy_tpu import __version__

        result = self.run_cli("version")
        assert result.returncode == 0
        assert __version__ in result.stdout

    def test_executions_and_vms(self, tmp_path):
        db = str(tmp_path / "meta.db")
        cluster = InProcessCluster(
            db_path=db, storage_uri=f"file://{tmp_path}/storage"
        )
        try:
            lzy = cluster.lzy()
            with lzy.workflow("cli-wf"):
                assert plus_one(1) == 2
        finally:
            cluster.shutdown()
        result = self.run_cli("--db", db, "executions")
        assert result.returncode == 0, result.stderr
        assert "cli-wf" in result.stdout
        assert "FINISHED" in result.stdout
        result = self.run_cli("--db", db, "graphs")
        assert result.returncode == 0
        import re

        # a real row: the graph op DONE with 1/1 tasks (not just the header)
        assert re.search(r"cli-wf\s+DONE\s+1\s+1", result.stdout), result.stdout

    def test_missing_db_errors(self):
        result = self.run_cli("executions")
        assert result.returncode == 2
        assert "--db" in result.stderr


class TestWorkerTokenRefresh:
    def test_refresh_past_half_life(self, tmp_path):
        """Cached/reused VMs outliving the token lifetime get a reissued
        credential via the heartbeat path instead of aging out."""
        import time

        from lzy_tpu.durable import OperationsExecutor, OperationStore
        from lzy_tpu.service.allocator import RUNNING, AllocatorService, Vm
        from lzy_tpu.service.backends import ThreadVmBackend
        from lzy_tpu.types import VmSpec

        store = OperationStore(str(tmp_path / "m.db"))
        executor = OperationsExecutor(store, workers=1)
        # half-life 2 s with ≥1 s slack on both sides: issued_at truncates
        # to whole seconds, so a sub-second margin would be flaky
        iam = IamService(store, max_token_age_s=4.0)
        svc = AllocatorService(
            store, executor, ThreadVmBackend(None, None),
            [VmSpec(label="cpu", cpu_count=1, ram_gb=1)], iam=iam,
        )
        tok = iam.create_subject("vm/vm-1", kind="WORKER", role="WORKER")
        vm = Vm(id="vm-1", session_id="s", pool_label="cpu", status=RUNNING,
                gang_id="g", host_index=0, gang_size=1, worker_token=tok)
        svc._vms[vm.id] = vm
        assert svc.refresh_worker_token("vm-1") is None  # inside half-life
        time.sleep(3.1)                                  # past 0.5 * 4.0s
        fresh = svc.refresh_worker_token("vm-1")
        assert fresh and fresh != tok
        assert iam.authenticate(fresh).id == "vm/vm-1"
        assert svc.vm("vm-1").worker_token == fresh      # persisted
        executor.shutdown()
        store.close()

    def test_worker_token_holder_rotation(self):
        from lzy_tpu.rpc.control import WorkerToken

        t = WorkerToken("old")
        assert t.accepts("old") and not t.accepts("new") and not t.accepts(None)
        t.rotate("new")
        assert t.accepts("new") and t.accepts("old")     # one-rotation grace
        t.rotate("newer")
        assert not t.accepts("old")

    def test_worker_token_bootstrap_swap_drops_ott(self):
        """The OTT→durable swap must not keep the burned OTT as an accepted
        credential — a leaked launch env would stay usable against the
        worker's own WorkerApi until the next refresh otherwise."""
        from lzy_tpu.rpc.control import WorkerToken

        t = WorkerToken("ott/abc123")
        t.rotate("vm/v:1:0:sig")
        assert t.accepts("vm/v:1:0:sig")
        assert not t.accepts("ott/abc123")
        assert t.previous is None


class TestAuthCli:
    def test_create_rotate_revoke_flow(self, tmp_path):
        import subprocess
        import sys as _sys

        db = str(tmp_path / "meta.db")

        def cli(*args):
            return subprocess.run(
                [_sys.executable, "-m", "lzy_tpu", "--db", db, "auth", *args],
                capture_output=True, text=True, cwd="/root/repo", timeout=60,
            )

        created = cli("create", "alice")
        assert created.returncode == 0, created.stderr
        token = created.stdout.strip()

        from lzy_tpu.durable import OperationStore

        store = OperationStore(db)
        iam = IamService(store)
        assert iam.authenticate(token).id == "alice"

        rotated = cli("rotate", "alice")
        new_token = rotated.stdout.strip()
        with pytest.raises(AuthError, match="revoked"):
            iam.authenticate(token)
        assert iam.authenticate(new_token).id == "alice"

        assert "removed" in cli("revoke", "alice").stdout
        with pytest.raises(AuthError, match="unknown subject"):
            iam.authenticate(new_token)
        store.close()
