"""Serving fleet gateway: routing, failover, autoscaling, RPC surface.

The gateway is a correctness-transparent layer: whatever replica a
request lands on, the reply must be bit-identical to the single-engine
path (greedy AND sampled), including across a mid-stream replica death —
the failover fences the already-emitted tokens and the retry continues
from them. The cache-aware part is a throughput property with an in-tree
baseline: the same shared-prefix workload through the same fleet must
show a strictly higher aggregate radix hit rate under prefix-affinity
routing than under round-robin.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.gateway import (
    Autoscaler, GatewayService, HealthPolicy, HealthTracker,
    PrefixAffinityRouter, ReplicaFleet, RoundRobinRouter, chunk_hashes)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import InferenceEngine, PagedInferenceEngine

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _make_gateway(cfg, params, *, replicas=3, slots=2, paged=False,
                  router=None, autoscaler=None, start_engines=True,
                  allocator=None, **engine_kw):
    def factory():
        if paged:
            return PagedInferenceEngine(cfg, params, slots=slots,
                                        page_size=PAGE, **engine_kw)
        return InferenceEngine(cfg, params, slots=slots, **engine_kw)

    fleet = ReplicaFleet(factory, allocator=allocator,
                         start_engines=start_engines)
    gw = GatewayService(
        fleet, router=router or PrefixAffinityRouter(PAGE),
        autoscaler=autoscaler, model_name="tiny")
    for _ in range(replicas):
        fleet.add_replica()
    return gw, fleet


class TestChunkHashes:
    def test_chain_property(self):
        a = chunk_hashes(list(range(24)), 8)
        b = chunk_hashes(list(range(16)), 8)
        assert len(a) == 3 and len(b) == 2
        assert a[:2] == b            # shared prefix -> shared chain hashes

    def test_divergence_breaks_the_chain(self):
        a = chunk_hashes(list(range(24)), 8)
        other = list(range(8)) + [99] * 16
        c = chunk_hashes(other, 8)
        assert a[0] == c[0] and a[1] != c[1] and a[2] != c[2]

    def test_partial_chunk_ignored(self):
        assert chunk_hashes([1, 2, 3], 8) == []


class TestPrefixAffinityRouter:
    def test_routes_to_expected_prefix_holder(self):
        r = PrefixAffinityRouter(4)
        prompt = list(range(12))
        loads = {"a": 0, "b": 0}
        first, why = r.choose(prompt, loads)
        assert why == "load"
        r.observe(first, prompt)
        again, why = r.choose(prompt, loads)
        assert (again, why) == (first, "prefix")
        # a prompt sharing only the first chunk still prefers the holder
        sibling = prompt[:4] + [60, 61, 62, 63]
        got, why = r.choose(sibling, loads)
        assert (got, why) == (first, "prefix")

    def test_imbalance_bound_overrides_affinity(self):
        r = PrefixAffinityRouter(4, max_imbalance=2)
        prompt = list(range(8))
        r.observe("hot", prompt)
        got, why = r.choose(prompt, {"hot": 3, "cold": 0})
        assert (got, why) == ("cold", "load")
        got, why = r.choose(prompt, {"hot": 2, "cold": 0})
        assert (got, why) == ("hot", "prefix")

    def test_forget_drops_the_index(self):
        r = PrefixAffinityRouter(4)
        prompt = list(range(8))
        r.observe("a", prompt)
        assert r.match_len("a", prompt) == 8
        r.forget("a")
        assert r.match_len("a", prompt) == 0

    def test_index_is_bounded_lru(self):
        r = PrefixAffinityRouter(2, index_chains_per_replica=4)
        for i in range(8):
            r.observe("a", [i * 2, i * 2 + 1])
        assert r.stats()["indexed_chains"]["a"] == 4
        # oldest chains evicted, newest retained
        assert r.match_len("a", [14, 15]) == 2
        assert r.match_len("a", [0, 1]) == 0

    def test_eviction_never_strands_orphan_descendants(self):
        """Chains match ancestor-to-descendant, so eviction must take the
        deepest entries of the oldest prompt first — evicting an ancestor
        while its descendant survives would leave permanently
        unmatchable index entries."""
        r = PrefixAffinityRouter(2, index_chains_per_replica=3)
        r.observe("a", [1, 2, 3, 4])        # depths 0,1 at clock 1
        r.observe("a", [9, 8, 7, 6])        # depths 0,1 at clock 2
        # cap 3: the OLD prompt's deepest chain went, its ancestor stayed
        assert r.match_len("a", [1, 2]) == 2
        assert r.match_len("a", [1, 2, 3, 4]) == 2
        assert r.match_len("a", [9, 8, 7, 6]) == 4

    def test_round_robin_cycles(self):
        r = RoundRobinRouter()
        loads = {"a": 0, "b": 9, "c": 0}
        picks = [r.choose([1], loads)[0] for _ in range(6)]
        assert picks == ["a", "b", "c", "a", "b", "c"]


class TestHealthTracker:
    def test_failure_streak_marks_dead_and_success_resets(self):
        h = HealthTracker(HealthPolicy(max_consecutive_failures=3))
        for _ in range(2):
            h.record_failure("r")
        assert h.verdict("r") is None
        h.record_success("r")
        for _ in range(2):
            h.record_failure("r")
        assert h.verdict("r") is None          # streak was reset
        h.record_failure("r")
        assert "consecutive" in h.verdict("r")

    def test_heartbeat_staleness(self):
        h = HealthTracker(HealthPolicy(heartbeat_timeout_s=30))
        assert h.verdict("r", heartbeat_ts=1000.0, now=1010.0) is None
        assert "stale" in h.verdict("r", heartbeat_ts=1000.0, now=1031.0)
        # unleased replicas have no heartbeat signal at all
        assert h.verdict("r", heartbeat_ts=None, now=1e12) is None

    def test_engine_death_is_immediate(self):
        h = HealthTracker()
        assert h.verdict("r", engine_closed=True) == "engine loop died"


class TestAutoscaler:
    def test_up_requires_sustained_pressure(self):
        a = Autoscaler(max_replicas=4, up_queue_per_replica=4,
                       up_sustain_s=5, cooldown_s=10)
        assert a.tick(0, replicas=2, queue_depth=20, busy=8, slots=8) is None
        assert a.tick(3, replicas=2, queue_depth=20, busy=8, slots=8) is None
        d = a.tick(6, replicas=2, queue_depth=20, busy=8, slots=8)
        assert d.direction == "up"
        # cooldown suppresses the next verdict
        assert a.tick(8, replicas=3, queue_depth=30, busy=12,
                      slots=12) is None

    def test_pressure_window_resets_when_queue_drains(self):
        a = Autoscaler(up_queue_per_replica=4, up_sustain_s=5)
        assert a.tick(0, replicas=1, queue_depth=9, busy=4, slots=4) is None
        assert a.tick(4, replicas=1, queue_depth=0, busy=1, slots=4) is None
        # pressure returns: the window starts over
        assert a.tick(6, replicas=1, queue_depth=9, busy=4, slots=4) is None
        assert a.tick(12, replicas=1, queue_depth=9, busy=4,
                      slots=4).direction == "up"

    def test_down_on_sustained_idle_respects_min(self):
        a = Autoscaler(min_replicas=2, down_busy_fraction=0.25,
                       down_sustain_s=30, cooldown_s=0)
        assert a.tick(0, replicas=3, queue_depth=0, busy=0, slots=12) is None
        d = a.tick(31, replicas=3, queue_depth=0, busy=0, slots=12)
        assert d.direction == "down"
        a2 = Autoscaler(min_replicas=2, down_sustain_s=30)
        a2.tick(0, replicas=2, queue_depth=0, busy=0, slots=8)
        assert a2.tick(31, replicas=2, queue_depth=0, busy=0,
                       slots=8) is None      # at the floor

    def test_max_replicas_caps_up(self):
        a = Autoscaler(max_replicas=2, up_sustain_s=0, cooldown_s=0)
        a.tick(0, replicas=2, queue_depth=99, busy=8, slots=8)
        assert a.tick(1, replicas=2, queue_depth=99, busy=8,
                      slots=8) is None


class TestGatewayParity:
    def test_greedy_bit_identical_over_three_replicas(self, tiny_model):
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=3)
        try:
            prompts = [[3 + i, 5, 7] for i in range(6)]
            replicas_used = set()
            for p in prompts:
                res = gw.generate(p, max_new_tokens=4, timeout_s=120)
                assert res["status"] == "ok" and res["failovers"] == 0
                assert res["tokens"] == _oracle_tokens(cfg, params, p, 4)
                replicas_used.add(res["replica"])
            s = gw.stats()
            assert s["replicas"] == 3 and s["requests_finished"] == 6
        finally:
            gw.close()

    def test_sampled_bit_identical_to_single_engine(self, tiny_model):
        """One sampled request through a fresh 3-replica fleet must match
        a fresh single engine bit-for-bit: every replica seeds the same
        rng stream, and the first request consumes the same draws."""
        cfg, params = tiny_model
        kw = dict(temperature=0.8, top_k=20, seed=7)
        solo = InferenceEngine(cfg, params, slots=2, **kw)
        ref = solo.submit([5, 9, 3], max_new_tokens=6)
        while not ref.done:
            solo.step()
        gw, _ = _make_gateway(cfg, params, replicas=3, **kw)
        try:
            res = gw.generate([5, 9, 3], max_new_tokens=6, timeout_s=120)
            assert res["tokens"] == ref.result(0)
        finally:
            gw.close()

    def test_request_scoped_errors_do_not_fail_over(self, tiny_model):
        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=2)
        try:
            with pytest.raises(ValueError, match="exceeds"):
                gw.generate([1] * 10, max_new_tokens=cfg.max_seq_len,
                            timeout_s=10)
            assert gw.stats()["failovers"] == 0
        finally:
            gw.close()

    def test_fleet_wide_backpressure(self, tiny_model):
        from lzy_tpu.rpc.core import Unavailable

        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2, slots=1,
                                  start_engines=False, max_queue=1)
        try:
            # fill every replica's admission queue directly; no loops run,
            # so the gateway sees AdmissionError from each and only then
            # surfaces retryable backpressure
            for replica in fleet.replicas():
                replica.engine.submit([1, 2], max_new_tokens=2)
            with pytest.raises(Unavailable, match="no replica can admit"):
                gw.generate([3, 4], max_new_tokens=2, timeout_s=5)
        finally:
            gw.close()


class TestPrefixAffinityHitRate:
    """The acceptance property: on a shared-prefix workload the affinity
    router concentrates each prefix family on one replica, so the
    fleet-aggregate radix hit rate beats round-robin on the SAME fleet
    shape and workload."""

    def _drive(self, cfg, params, router):
        gw, fleet = _make_gateway(cfg, params, replicas=3, paged=True,
                                  router=router)
        try:
            # four families over three replicas: round-robin cannot stay
            # aligned (family i lands on a different replica every round),
            # while affinity pins each family wherever it first landed
            families = [
                list(range(0, 16)),           # two full PAGE-chunks each
                list(range(20, 36)),
                list(range(40, 56)),
                list(range(8, 24)),
            ]
            for round_ in range(3):
                for fam, prefix in enumerate(families):
                    prompt = prefix + [60 + fam, 50 + round_, round_]
                    res = gw.generate(prompt, max_new_tokens=2,
                                      timeout_s=120)
                    assert res["status"] == "ok"
            agg = fleet.aggregate()
            assert agg["prefix_lookup_tokens"] > 0
            return (agg["prefix_hit_tokens"] / agg["prefix_lookup_tokens"],
                    gw.stats())
        finally:
            gw.close()

    def test_affinity_beats_round_robin(self, tiny_model):
        cfg, params = tiny_model
        affinity_rate, affinity_stats = self._drive(
            cfg, params, PrefixAffinityRouter(PAGE))
        rr_rate, _ = self._drive(cfg, params, RoundRobinRouter())
        assert affinity_rate > rr_rate, (
            f"prefix-affinity routing must raise the aggregate radix hit "
            f"rate over round-robin (affinity {affinity_rate:.3f} vs rr "
            f"{rr_rate:.3f})")
        # and the router actually routed repeats by prefix
        assert affinity_stats["routed_by_prefix"] > 0
        assert affinity_stats["fleet_prefix_hit_rate"] == round(
            affinity_rate, 4)


class TestFailover:
    def test_replica_killed_mid_decode_completes_elsewhere(self,
                                                           tiny_model):
        """Kill the serving replica's engine loop mid-stream: the request
        must complete on another replica with output identical to an
        uninterrupted single-engine run, the already-emitted tokens
        fenced (never repeated, never dropped), and the dead replica
        retired from routing."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=3)
        result = {}

        def run():
            try:
                result["res"] = gw.generate([7, 2, 8, 1],
                                            max_new_tokens=24,
                                            timeout_s=120)
            except BaseException as e:  # surfaced in the main thread
                result["err"] = e

        try:
            t = threading.Thread(target=run)
            t.start()
            victim, req = None, None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                for replica in fleet.replicas():
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim, req = replica, live[0]
                        break
                if victim:
                    break
                time.sleep(0.005)
            assert victim is not None, "request never reached mid-decode"

            def boom():
                raise RuntimeError("replica host on fire")

            victim.engine.step = boom
            t.join(120)
            assert "err" not in result, result.get("err")
            res = result["res"]
            assert res["tokens"] == _oracle_tokens(cfg, params,
                                                   [7, 2, 8, 1], 24)
            assert res["failovers"] == 1 and res["status"] == "ok"
            assert victim.id not in [r.id for r in fleet.replicas()]
            assert gw.stats()["failovers"] == 1
        finally:
            gw.close()


class TestLeasedFleet:
    def test_replicas_lease_through_the_allocator(self, tiny_model):
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.allocator import IDLE, RUNNING

        cfg, params = tiny_model
        cluster = InProcessCluster()
        gw, fleet = _make_gateway(cfg, params, replicas=2,
                                  allocator=cluster.allocator)
        try:
            for replica in fleet.replicas():
                assert replica.vm_ids, "replica must hold a lease"
                vm = cluster.allocator.vm(replica.vm_ids[0])
                assert vm.status == RUNNING
                assert vm.heartbeat_ts > 0
            res = gw.generate([5, 9, 3], max_new_tokens=3, timeout_s=120)
            assert res["tokens"] == _oracle_tokens(cfg, params,
                                                   [5, 9, 3], 3)
            # draining frees the gang back to the session cache (IDLE)...
            victim = fleet.replicas()[0]
            fleet.drain(victim.id)
            gw.tick()
            assert victim.id not in [r.id for r in fleet.replicas()]
            assert cluster.allocator.vm(victim.vm_ids[0]).status == IDLE
            # fleet aggregates stay monotonic across the retirement: the
            # drained replica's served tokens are banked, not dropped
            assert fleet.aggregate()["tokens_generated"] >= 3
            # ...and the next lease reuses the warm gang
            fresh = fleet.add_replica()
            assert fresh.vm_ids == victim.vm_ids
        finally:
            gw.close()
            cluster.shutdown()

    def test_stale_heartbeat_retires_the_replica(self, tiny_model):
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model
        cluster = InProcessCluster()
        gw, fleet = _make_gateway(cfg, params, replicas=2,
                                  allocator=cluster.allocator)
        try:
            victim = fleet.replicas()[0]
            horizon = time.time() + 10 * HealthPolicy().heartbeat_timeout_s
            dead = fleet.check_health(now=horizon)
            # ALL replicas look stale at that horizon; the point is that
            # staleness alone retires them without any request traffic
            assert victim.id in dead
            assert victim.id not in [r.id for r in fleet.replicas()]
        finally:
            gw.close()
            cluster.shutdown()


class TestAutoscaleIntegration:
    def test_queue_pressure_scales_up_then_idle_drains(self, tiny_model):
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model
        cluster = InProcessCluster()
        scaler = Autoscaler(min_replicas=1, max_replicas=3,
                            up_queue_per_replica=4, up_sustain_s=0.5,
                            down_busy_fraction=0.25, down_sustain_s=1.0,
                            cooldown_s=0.1)
        gw, fleet = _make_gateway(cfg, params, replicas=1,
                                  autoscaler=scaler,
                                  allocator=cluster.allocator)
        try:
            only = fleet.replicas()[0]
            backlog = [only.engine.submit([1 + i, 2, 3], max_new_tokens=40)
                       for i in range(8)]
            t0 = time.time()
            assert gw.tick(now=t0) is None          # window opens
            assert gw.tick(now=t0 + 1.0) == "up"    # sustained -> lease
            assert len(fleet.replicas()) == 2
            assert all(r.vm_ids for r in fleet.replicas())
            for req in backlog:
                req.result(timeout=120)
            t1 = time.time()
            assert gw.tick(now=t1) is None          # idle window opens
            assert gw.tick(now=t1 + 2.0) == "down"
            gw.tick(now=t1 + 3.0)                   # reap the drained one
            assert len(fleet.replicas()) == 1
            assert gw.stats()["scale_ups"] == 1
            assert gw.stats()["scale_downs"] == 1
        finally:
            gw.close()
            cluster.shutdown()


class TestFleetRecovery:
    def test_fleet_releases_to_min_replicas_after_total_loss(self,
                                                            tiny_model):
        """Health-based retirement can take the fleet to zero, where no
        queue pressure can ever build (nothing admits) — the tick must
        re-lease back to the autoscaler's floor on its own."""
        cfg, params = tiny_model
        scaler = Autoscaler(min_replicas=2, max_replicas=4)
        gw, fleet = _make_gateway(cfg, params, replicas=2,
                                  autoscaler=scaler)
        try:
            for replica in fleet.replicas():
                replica.engine.close()        # closed engine == dead
            assert gw.tick() == "up"          # retire both, re-lease one
            assert gw.tick() == "up"          # ...and the second
            assert len(fleet.replicas()) == 2
            assert gw.tick() is None          # at the floor: steady state
            res = gw.generate([5, 9, 3], max_new_tokens=3, timeout_s=120)
            assert res["tokens"] == _oracle_tokens(cfg, params,
                                                   [5, 9, 3], 3)
        finally:
            gw.close()


class TestGatewayRpc:
    def test_generate_and_fleet_stats_over_the_control_plane(
            self, tiny_model, tmp_path):
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model

        def factory(cluster):
            gw, _ = _make_gateway(cfg, params, replicas=3)
            return gw

        cluster = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            inference_factory=factory,
        )
        try:
            client = RpcInferenceClient(cluster.rpc_server.address)
            try:
                res = client.generate([5, 9, 3], max_new_tokens=4,
                                      timeout_s=120)
                assert res["tokens"] == _oracle_tokens(cfg, params,
                                                       [5, 9, 3], 4)
                assert res["replica"] and res["routed_by"]
                stats = client.stats()
                assert stats["gateway"] is True and stats["replicas"] == 3
                fs = client.fleet_stats()
                assert len(fs["replicas"]) == 3
                assert {r["state"] for r in fs["replicas"]} == {"READY"}
            finally:
                client.close()
        finally:
            cluster.shutdown()

    def test_fleet_stats_not_found_on_single_engine_plane(
            self, tiny_model, tmp_path):
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.inference import InferenceService

        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=1).start()
        cluster = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            inference_service=InferenceService(engine, model_name="tiny"),
        )
        try:
            client = RpcInferenceClient(cluster.rpc_server.address)
            try:
                # a single-engine plane does not serve the method at all
                # (UNIMPLEMENTED -> RuntimeError client-side)
                with pytest.raises(RuntimeError):
                    client.fleet_stats()
            finally:
                client.close()
        finally:
            cluster.shutdown()
