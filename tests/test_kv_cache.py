"""Paged KV-cache pool + radix prefix caching (lzy_tpu/serving/kv_cache).

Two layers of coverage:

- **Pool/tree units**: refcount discipline, LRU eviction order (the tree
  uses a logical clock, so order is deterministic), the
  only-unreferenced-blocks-evict invariant, and free/cached accounting.
- **Engine integration**: the paged engine must be BIT-IDENTICAL to the
  dense sequential oracle — with prefix caching cold and hot, greedy and
  sampled — because the paged attention path gathers blocks back into
  exactly the dense layout before the shared softmax code runs. Pressure
  tests drive the engine past the block budget and assert eviction takes
  cached blocks in LRU order, preemption takes the youngest request, and
  in-flight requests are never corrupted. Deadline tests cover the
  ``cancelled`` terminal status for slot-resident and queued requests.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import (
    BlockPool, InferenceEngine, NoFreeBlocks, PagedInferenceEngine,
    RadixCache)

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    """Solo generate() continuation (dense sequential-path oracle)."""
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drive(eng, *reqs, rounds=200):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        eng.step()
    raise AssertionError("requests did not finish")


class TestBlockPool:
    def test_alloc_refcount_release_cycle(self):
        pool = BlockPool(4, PAGE)
        assert pool.free_count() == 3          # block 0 is scratch
        a = pool.alloc()
        assert a != 0 and pool.refcount(a) == 1
        assert pool.incref(a) == 2
        assert pool.decref(a) == 1
        assert pool.decref(a) == 0
        pool.release_to_free(a)
        assert pool.free_count() == 3

    def test_exhaustion_raises(self):
        pool = BlockPool(3, PAGE)
        pool.alloc(), pool.alloc()
        with pytest.raises(NoFreeBlocks):
            pool.alloc()

    def test_freeing_referenced_block_is_a_bug(self):
        pool = BlockPool(3, PAGE)
        b = pool.alloc()
        with pytest.raises(AssertionError):
            pool.release_to_free(b)


class TestRadixCache:
    def _filled(self, n_blocks=16):
        """Cache with two 2-block prompts inserted and fully released:
        every block cached-unreferenced (evictable)."""
        kv = RadixCache(n_blocks, PAGE)
        pa = list(range(16))          # blocks: chunks (0..7), (8..15)
        pb = list(range(16, 32))
        ba = kv.allocate(2)
        kv.insert(pa, ba)
        kv.release(ba)
        bb = kv.allocate(2)
        kv.insert(pb, bb)
        kv.release(bb)
        return kv, pa, pb

    def test_match_whole_blocks_only(self):
        kv, pa, _ = self._filled()
        blocks, n = kv.match(pa[:12])          # 1.5 chunks → 1 block
        assert n == 8 and len(blocks) == 1
        assert kv.pool.refcount(blocks[0]) == 1
        kv.release(blocks)

    def test_match_refs_pin_against_eviction(self):
        kv, pa, pb = self._filled(n_blocks=5)  # 4 usable, all cached
        held, n = kv.match(pa)
        assert n == 16
        # allocating everything evictable must take pb's blocks, not pa's
        kv.allocate(2)
        assert kv.match_len(pa) == 16, "referenced blocks were evicted"
        assert kv.match_len(pb) == 0
        kv.release(held)

    def test_lru_eviction_order_is_deterministic(self):
        kv, pa, pb = self._filled(n_blocks=5)
        # touch pa AFTER pb: pb's leaves become the LRU victims
        kv.match_len(pb)                       # probe does NOT bump LRU
        held, _ = kv.match(pa)
        kv.release(held)                       # unpinned again, but recent
        kv.allocate(2)
        assert kv.match_len(pa) == 16
        assert kv.match_len(pb) == 0

    def test_eviction_is_leaf_first(self):
        kv = RadixCache(4, PAGE)               # 3 usable: the whole chain
        prompt = list(range(24))               # 3 chained blocks
        blocks = kv.allocate(3)
        kv.insert(prompt, blocks)
        kv.release(blocks)
        kv.allocate(1)                         # evicts ONE block: the leaf
        assert kv.match_len(prompt) == 16      # parents survive

    def test_available_counts_free_plus_evictable(self):
        kv, pa, _ = self._filled(n_blocks=9)   # 8 usable, 4 cached
        assert kv.available() == 8
        held, _ = kv.match(pa)                 # pin 2
        assert kv.available() == 6
        kv.release(held)
        assert kv.available() == 8

    def test_allocate_never_overcommits(self):
        kv = RadixCache(4, PAGE)
        kv.allocate(3)
        with pytest.raises(NoFreeBlocks):
            kv.allocate(1)

    def test_insert_keeps_existing_node_block(self):
        kv = RadixCache(8, PAGE)
        prompt = list(range(8))
        first = kv.allocate(1)
        assert kv.insert(prompt, first) == 1
        dup = kv.allocate(1)
        assert kv.insert(prompt, dup) == 0     # node exists; dup stays private
        kv.release(first)
        kv.release(dup)                        # private dup → free list
        assert kv.match_len(prompt) == 8


class TestKvMetricsExported:
    def test_kv_metrics_in_registry(self):
        from lzy_tpu.utils.metrics import REGISTRY

        kv = RadixCache(8, PAGE)
        blocks = kv.allocate(2)
        kv.insert(list(range(16)), blocks)
        kv.release(blocks)
        kv.match(list(range(16)))
        text = REGISTRY.exposition()
        for name in ("lzy_kv_blocks", "lzy_kv_blocks_free",
                     "lzy_kv_blocks_cached", "lzy_kv_evictions_total",
                     "lzy_kv_prefix_hit_tokens_total",
                     "lzy_kv_prefix_hit_rate"):
            assert name in text


class TestPagedEngineParity:
    """Acceptance criterion: with prefix caching enabled, requests sharing
    a >= 2-block prompt prefix decode bit-identically to the dense
    sequential oracle, and the stats report the reuse."""

    SHARED = [5, 9, 3, 7, 1, 2, 8, 4, 6, 0, 5, 9, 3, 7, 1, 2]  # 2 blocks

    def test_prefix_hit_is_bit_identical_and_reported(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        a = eng.submit(self.SHARED + [11, 12, 13], max_new_tokens=8)
        _drive(eng, a)
        assert a.result(0) == _oracle_tokens(cfg, params, a.prompt, 8)
        assert eng.stats().prefill_tokens_saved == 0     # cold cache

        b = eng.submit(self.SHARED + [21, 22], max_new_tokens=6)
        c = eng.submit(self.SHARED + [31], max_new_tokens=6)
        _drive(eng, b, c)
        assert b.result(0) == _oracle_tokens(cfg, params, b.prompt, 6)
        assert c.result(0) == _oracle_tokens(cfg, params, c.prompt, 6)
        s = eng.stats()
        # both hit the 2-block (16-token) shared prefix
        assert s.prefill_tokens_saved == 32
        assert s.prefix_hit_rate > 0
        assert s.kv_page_size == PAGE

    def test_staggered_requests_and_slot_reuse(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        a = eng.submit([5, 9, 3], max_new_tokens=12)
        eng.step()
        eng.step()
        b = eng.submit([7, 2, 8, 1, 4], max_new_tokens=4)
        eng.step()
        assert len(b.tokens) >= 1, "B waited for the running batch to drain"
        _drive(eng, a, b)
        assert a.result(0) == _oracle_tokens(cfg, params, a.prompt, 12)
        assert b.result(0) == _oracle_tokens(cfg, params, b.prompt, 4)
        # C lands in a vacated slot whose blocks went back to the pool
        c = eng.submit([7, 2, 8, 1], max_new_tokens=5)
        _drive(eng, c)
        assert c.result(0) == _oracle_tokens(cfg, params, c.prompt, 5)

    def test_sampled_decode_matches_dense_engine(self, tiny_model):
        """Same seed, same arrival schedule, temperature > 0: the paged
        engine must reproduce the dense engine's sampled stream exactly
        (both consume the engine-wide rng in the same order)."""
        cfg, params = tiny_model
        kw = dict(slots=2, temperature=0.8, top_k=20, seed=7)
        dense = InferenceEngine(cfg, params, **kw)
        paged = PagedInferenceEngine(cfg, params, page_size=PAGE, **kw)
        d1 = dense.submit([5, 9, 3, 7], max_new_tokens=6)
        p1 = paged.submit([5, 9, 3, 7], max_new_tokens=6)
        dense.step(), paged.step()
        d2 = dense.submit([8, 1], max_new_tokens=5)
        p2 = paged.submit([8, 1], max_new_tokens=5)
        _drive(dense, d1, d2)
        _drive(paged, p1, p2)
        assert p1.result(0) == d1.result(0)
        assert p2.result(0) == d2.result(0)

    def test_full_block_prompt_and_one_token_request(self, tiny_model):
        """Edge shapes: a prompt that is exactly N full blocks (the match
        cap must still leave one token to forward), and max_new_tokens=1
        (slot never activates; blocks release at prefill)."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE)
        exact = self.SHARED                     # 16 tokens = 2 blocks
        a = eng.submit(exact, max_new_tokens=4)
        _drive(eng, a)
        assert a.result(0) == _oracle_tokens(cfg, params, exact, 4)
        b = eng.submit(exact, max_new_tokens=1)
        _drive(eng, b)
        assert b.result(0) == _oracle_tokens(cfg, params, exact, 1)
        # the second run may only match 1 block (15 of 16 tokens offered)
        assert eng.stats().prefill_tokens_saved >= 8
        assert eng.stats().busy == 0

    def test_eos_frees_blocks(self, tiny_model):
        cfg, params = tiny_model
        prompt = [5, 9, 3]
        first = _oracle_tokens(cfg, params, prompt, 1)[0]
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   eos_token=first)
        r = eng.submit(prompt, max_new_tokens=16)
        eng.step()
        assert r.done and r.result(0) == [first]
        s = eng.stats()
        assert s.busy == 0
        # every block is either free or cached-unreferenced
        assert s.kv_blocks_free + s.kv_blocks_cached == s.kv_blocks_total


class TestCachePressure:
    def test_squeeze_preempts_youngest_never_corrupts_oldest(self,
                                                             tiny_model):
        """Deterministic squeeze: 7 usable blocks, two growing requests.
        The younger must be preempted with a clean error; the older must
        run to completion BIT-IDENTICAL to the oracle (its blocks were
        never touched)."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   kv_blocks=8)
        a = eng.submit([5, 9, 3, 7, 1, 2, 8, 4, 6], max_new_tokens=30)
        b = eng.submit([11, 12, 13, 14, 15, 16, 17], max_new_tokens=30)
        for _ in range(120):
            if a.done and b.done:
                break
            eng.step()
        assert a.error is None
        assert a.result(0) == _oracle_tokens(cfg, params, a.prompt, 30)
        assert b.error is not None and "preempted" in b.error
        assert len(b.tokens) > 0            # it generated until the squeeze
        s = eng.stats()
        assert s.kv_blocks_free + s.kv_blocks_cached == s.kv_blocks_total

    def test_eviction_takes_lru_cached_blocks_first(self, tiny_model):
        """Fill the pool with two finished requests' cached prefixes, then
        admit a third that needs eviction: the LRU prefix goes, the
        recently-matched one survives."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE,
                                   kv_blocks=8)              # 7 usable
        old = list(range(16))                                # 2 blocks
        hot = list(range(16, 32))                            # 2 blocks
        r1 = eng.submit(old + [40], max_new_tokens=2)
        _drive(eng, r1)
        r2 = eng.submit(hot + [41], max_new_tokens=2)
        _drive(eng, r2)
        # touch 'hot' again so 'old' is the LRU victim
        r3 = eng.submit(hot + [42], max_new_tokens=2)
        _drive(eng, r3)
        assert eng.kv.match_len(hot) == 16
        # a big new prompt forces eviction of the remaining cold blocks
        r4 = eng.submit(list(range(32, 32 + 33)), max_new_tokens=2)
        _drive(eng, r4)
        assert r4.result(0) == _oracle_tokens(cfg, params, r4.prompt, 2)
        assert eng.stats().kv_evictions > 0
        assert eng.kv.match_len(old) == 0, "LRU prefix should be gone"

    def test_refcount_integrity_after_eos_and_cancel(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        a = eng.submit(list(range(20)), max_new_tokens=20)
        b = eng.submit(list(range(16)) + [50], max_new_tokens=3)
        eng.step()
        eng.step()       # both resident
        a.cancel()
        _drive(eng, a, b)
        assert a.status == "cancelled"
        assert b.result(0) == _oracle_tokens(cfg, params, b.prompt, 3)
        # no block may retain a reference once nothing is in flight
        pool = eng.kv.pool
        assert all(pool.refcount(blk) == 0
                   for blk in range(pool.n_blocks)), "leaked block refs"
        s = eng.stats()
        assert s.kv_blocks_free + s.kv_blocks_cached == s.kv_blocks_total

    def test_never_coverable_prompt_rejected_at_submit(self, tiny_model):
        """A prompt needing more blocks than the pool can EVER supply must
        fail fast at submit — queued it would park at the head of the
        admission queue forever and starve every request behind it."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   kv_blocks=4)               # 3 usable
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit(list(range(32)), max_new_tokens=2)     # needs 4
        # 2 prompt blocks + growth into the 3rd: completes inside the pool
        ok = eng.submit(list(range(16)), max_new_tokens=2)
        _drive(eng, ok)
        assert ok.result(0) == _oracle_tokens(cfg, params, ok.prompt, 2)

    def test_admission_waits_for_block_budget(self, tiny_model):
        """A prompt whose blocks cannot be covered yet must WAIT in the
        queue (head-of-line) — not fail — and admit once blocks free."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   kv_blocks=8)               # 7 usable
        a = eng.submit(list(range(32)), max_new_tokens=8)     # 4 blocks
        eng.step()
        big = eng.submit(list(range(30, 62)), max_new_tokens=2)   # 4 more
        eng.step()
        assert not a.done
        assert not big.done and len(big.tokens) == 0
        assert eng.stats().queue_depth == 1                  # still queued
        _drive(eng, a, big)
        assert big.result(0) == _oracle_tokens(cfg, params, big.prompt, 2)


class TestDeadlines:
    def test_slot_resident_deadline_evicts_mid_decode(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE)
        r = eng.submit([5, 9, 3], max_new_tokens=200, deadline_s=0.2)
        deadline = time.monotonic() + 30
        while not r.done and time.monotonic() < deadline:
            eng.step()
            time.sleep(0.01)
        assert r.status == "cancelled"
        assert "deadline" in (r.error or "")
        assert len(r.tokens) > 0              # partial output stays readable
        s = eng.stats()
        assert s.busy == 0 and s.requests_cancelled == 1
        assert s.kv_blocks_free + s.kv_blocks_cached == s.kv_blocks_total

    def test_queued_request_expires_at_pop(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        hog = eng.submit([5, 9, 3], max_new_tokens=100)
        doomed = eng.submit([1, 2], max_new_tokens=5, deadline_s=0.05)
        eng.step()
        time.sleep(0.1)
        eng.step()
        assert doomed.done and doomed.status == "cancelled"
        assert not hog.done

    def test_deadline_surfaces_as_cancelled_status_over_rpc_service(
            self, tiny_model):
        """InferGenerate's surface: a deadline-cancelled request RETURNS
        (not raises) with status "cancelled" and the partial tokens."""
        from lzy_tpu.service.inference import InferenceService

        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1,
                                   page_size=PAGE).start()
        try:
            svc = InferenceService(eng, model_name="tiny")
            res = svc.generate([5, 9, 3], max_new_tokens=100_000 // 500,
                               timeout_s=30, deadline_s=0.2)
            assert res["status"] == "cancelled"
            assert res["model"] == "tiny"
            ok = svc.generate([5, 9, 3], max_new_tokens=2, timeout_s=30)
            assert ok["status"] == "ok"
            assert ok["tokens"] == _oracle_tokens(cfg, params, [5, 9, 3], 2)
        finally:
            eng.close()

    def test_rejects_nonpositive_deadline(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        with pytest.raises(ValueError, match="deadline"):
            eng.submit([1, 2], max_new_tokens=2, deadline_s=0.0)
