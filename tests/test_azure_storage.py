"""Executed coverage for ``storage/azure.py`` (VERDICT component 16).

The container has no azure SDK, so these tests install the in-process
stub from ``fake_azure`` into ``sys.modules`` and run the REAL client
code — construction through the lazy import (both credential forms),
every object op, ranged reads through the parallel download engine, and
the block-blob multipart path with per-part retries and the
nothing-committed-on-failure guarantee. The gated ImportError contract
(no SDK → clear error at construction) keeps its own test at the bottom.
"""

import io

import pytest

from fake_azure import FakeAzureError, install

from lzy_tpu.storage.api import StorageConfig
from lzy_tpu.storage.transfer import (
    TransferConfig, download, upload_bytes)


@pytest.fixture()
def az(monkeypatch):
    """(client, fake service) — a real AzureStorageClient over the
    in-memory blob service, connection-string credentialed."""
    fake = install(monkeypatch)
    from lzy_tpu.storage.registry import client_for

    client = client_for(StorageConfig(
        uri="azure://container/prefix",
        connection_string="DefaultEndpointsProtocol=https;AccountName=f"))
    assert client.scheme == "azure"
    return client, fake


SMALL_CFG = TransferConfig(part_size=64, max_workers=4, retries=3,
                           backoff_s=0.001)


class TestObjectOps:
    def test_write_read_roundtrip_counts_bytes(self, az):
        client, _ = az
        payload = b"x" * 1000
        n = client.write("azure://container/a/obj", io.BytesIO(payload))
        assert n == 1000
        out = io.BytesIO()
        assert client.read("azure://container/a/obj", out) == 1000
        assert out.getvalue() == payload

    def test_read_range(self, az):
        client, _ = az
        client.write("azure://container/r", io.BytesIO(b"0123456789"))
        assert client.read_range("azure://container/r", 2, 3) == b"234"
        assert client.read_range("azure://container/r", 7) == b"789"

    def test_exists_size_delete(self, az):
        client, _ = az
        assert not client.exists("azure://container/missing")
        client.write("azure://container/e", io.BytesIO(b"abc"))
        assert client.exists("azure://container/e")
        assert client.size("azure://container/e") == 3
        client.delete("azure://container/e")
        assert not client.exists("azure://container/e")

    def test_list_scoped_to_prefix(self, az):
        client, _ = az
        keys = [f"azure://container/list/{i:02d}" for i in range(5)]
        for uri in keys:
            client.write(uri, io.BytesIO(b"d"))
        client.write("azure://container/other", io.BytesIO(b"d"))
        assert list(client.list("azure://container/list/")) == keys

    def test_sign_uri_connection_string_appends_sas(self, az):
        client, _ = az
        client.write("azure://container/signed", io.BytesIO(b"d"))
        url = client.sign_uri("azure://container/signed")
        assert url.startswith("https://") and "sig=" in url

    def test_sign_uri_sas_client_reuses_its_signature(self, monkeypatch):
        """A SAS-credentialed client must NOT sign twice — blob.url
        already carries the signature."""
        install(monkeypatch)
        from lzy_tpu.storage.azure import AzureStorageClient

        client = AzureStorageClient(StorageConfig(
            uri="azure://container/prefix",
            endpoint="https://fakeaccount.blob",
            sas_signature="sv=real&sig=abc"))
        url = client.sign_uri("azure://container/x")
        assert url.startswith("https://") and "sig=" not in url

    def test_missing_credentials_rejected(self, monkeypatch):
        install(monkeypatch)
        from lzy_tpu.storage.azure import AzureStorageClient

        with pytest.raises(ValueError, match="connection_string"):
            AzureStorageClient(StorageConfig(uri="azure://container/p"))


class TestRangedDownload:
    def test_parallel_ranged_download_via_transfer_engine(self, az,
                                                          tmp_path):
        """The generic download path (size + concurrent read_range
        parts) against the azure client: byte-identical reassembly."""
        client, fake = az
        payload = bytes(range(256)) * 3                # 768 B -> 12 parts
        client.write("azure://container/big", io.BytesIO(payload))
        dest = tmp_path / "out.bin"
        n = download(client, "azure://container/big", str(dest),
                     config=SMALL_CFG)
        assert n == len(payload)
        assert dest.read_bytes() == payload
        assert fake.calls["download_blob"] >= 12       # ranged fan-out

    def test_ranged_read_retries_recover(self, az, tmp_path):
        client, fake = az
        payload = b"r" * 300
        client.write("azure://container/retry", io.BytesIO(payload))
        fake.fail_next["download_blob"] = 2
        dest = tmp_path / "retry.bin"
        assert download(client, "azure://container/retry", str(dest),
                        config=SMALL_CFG) == 300
        assert dest.read_bytes() == payload


class TestMultipart:
    def test_small_payload_uses_single_upload(self, az):
        client, fake = az
        data = b"s" * SMALL_CFG.part_size              # == part: no blocks
        n = client.multipart_upload(
            "azure://container/small", size=len(data),
            read_span=lambda off, ln: data[off:off + ln],
            config=SMALL_CFG, advance=lambda n: None)
        assert n == len(data)
        assert "stage_block" not in fake.calls
        out = io.BytesIO()
        client.read("azure://container/small", out)
        assert out.getvalue() == data

    def test_blocks_commit_in_offset_order(self, az):
        client, fake = az
        data = bytes(range(256)) * 2                   # 512 B -> 8 blocks
        n = upload_bytes(client, "azure://container/big-up", data,
                         config=SMALL_CFG)
        assert n == len(data)
        assert fake.calls["stage_block"] == 8
        assert fake.calls["commit_block_list"] == 1
        out = io.BytesIO()
        client.read("azure://container/big-up", out)
        assert out.getvalue() == data
        assert fake.dangling_blocks() == 0

    def test_per_block_retry_recovers(self, az):
        client, fake = az
        fake.fail_next["stage_block"] = 2              # two throttles
        data = b"r" * 300
        assert upload_bytes(client, "azure://container/retry-up", data,
                            config=SMALL_CFG) == 300
        assert fake.calls["stage_block"] >= 5 + 2      # 5 blocks + retries
        out = io.BytesIO()
        client.read("azure://container/retry-up", out)
        assert out.getvalue() == data

    def test_exhausted_retries_commit_nothing(self, az):
        """Azure has no abort call — the abort contract is that a failed
        multipart NEVER commits: the target blob must not appear, and
        only service-side garbage (uncommitted blocks) remains."""
        client, fake = az
        fake.fail_next["stage_block"] = 10 * SMALL_CFG.retries
        with pytest.raises(Exception):
            upload_bytes(client, "azure://container/doomed", b"d" * 300,
                         config=SMALL_CFG)
        assert "commit_block_list" not in fake.calls
        assert not client.exists("azure://container/doomed")

    def test_commit_failure_leaves_no_visible_blob(self, az):
        client, fake = az
        fake.fail_next["commit_block_list"] = 10 * SMALL_CFG.retries
        with pytest.raises(Exception):
            upload_bytes(client, "azure://container/half", b"h" * 300,
                         config=SMALL_CFG)
        assert not client.exists("azure://container/half")


def test_without_azure_sdk_construction_fails_clearly():
    """The gated contract on this image (no azure SDK): a clear
    ImportError at construction, never at first use."""
    try:
        import azure.storage.blob  # noqa: F401

        pytest.skip("azure SDK genuinely installed; gate does not apply")
    except ImportError:
        pass
    from lzy_tpu.storage.azure import AzureStorageClient

    with pytest.raises(ImportError, match="azure-storage-blob"):
        AzureStorageClient(StorageConfig(
            uri="azure://container/prefix", connection_string="x"))
