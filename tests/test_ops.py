"""Kernel tests: chunked attention and the Pallas flash kernel (interpret mode
on CPU; the same code compiles natively on TPU) against a dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.ops import chunked_attention, flash_attention


def dense_reference(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (d ** -0.5)
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def make_qkv(b=2, h=2, t=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, t, d), dtype) for k in ks)


class TestChunkedAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        out = chunked_attention(q, k, v, causal=causal, block_size=64)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v = make_qkv(t=128, d=32)

        def loss_chunked(q, k, v):
            return chunked_attention(q, k, v, causal=True, block_size=32).sum()

        def loss_dense(q, k, v):
            return dense_reference(q, k, v, True).sum()

        g1 = jax.grad(loss_chunked, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        q, k, v = make_qkv(t=128, d=32, seed=3)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal,
                                  block_q=32, block_kv=32)
            return (out * out).sum()

        def loss_dense(q, k, v):
            out = dense_reference(q, k, v, causal)
            return (out * out).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_bfloat16_inputs(self):
        q, k, v = make_qkv(dtype=jnp.bfloat16, seed=5)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
        assert out.dtype == jnp.bfloat16
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
        )

    def test_rejects_misaligned_seq(self):
        q, k, v = make_qkv(t=100)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, block_kv=64)

    def test_jit_compose(self):
        q, k, v = make_qkv(t=128, d=32)
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, block_q=32, block_kv=32
        ))(q, k, v)
        assert out.shape == q.shape


def dense_masked_reference(q, k, v, kv_mask, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (d ** -0.5)
    s = jnp.where(kv_mask[:, None, None, :], s, -1e30)
    if causal:
        t = q.shape[2]
        s = jnp.where(np.tril(np.ones((t, t), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


class TestFlashKvMask:
    """Padding-mask support (encoder models): the mask rides into the
    kernels as a KV bias; forward and all gradients must match a dense
    masked softmax."""

    def make_mask(self, b, t, valid):
        mask = np.zeros((b, t), bool)
        for i, n in enumerate(valid):
            mask[i, :n] = True
        return jnp.asarray(mask)

    def test_matches_dense_masked(self):
        q, k, v = make_qkv(b=3, h=2, t=256, d=64)
        kv_mask = self.make_mask(3, 256, [256, 200, 128])
        out = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
        ref = dense_masked_reference(q, k, v, kv_mask)
        # padded QUERY rows attend over valid keys in both impls; compare all
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_dense_masked(self):
        q, k, v = make_qkv(b=2, h=2, t=128, d=32, seed=3)
        kv_mask = self.make_mask(2, 128, [128, 96])

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = dense_masked_reference(q, k, v, kv_mask)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_causal_plus_mask(self):
        q, k, v = make_qkv(b=2, h=2, t=256, d=32, seed=5)
        kv_mask = self.make_mask(2, 256, [256, 160])
        out = flash_attention(q, k, v, causal=True, kv_mask=kv_mask)
        ref = dense_masked_reference(q, k, v, kv_mask, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_fully_masked_batch_row_is_zero(self):
        q, k, v = make_qkv(b=2, h=1, t=128, d=32)
        kv_mask = self.make_mask(2, 128, [128, 0])   # row 1: nothing to attend

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=False,
                                           kv_mask=kv_mask))

        out = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(np.asarray(out[1]), 0.0, atol=1e-6)
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g)))
            np.testing.assert_allclose(np.asarray(g[1]), 0.0, atol=1e-6)

    def test_bad_mask_shape_rejected(self):
        q, k, v = make_qkv(b=2, h=1, t=128, d=32)
        with pytest.raises(ValueError, match="kv_mask shape"):
            flash_attention(q, k, v, kv_mask=jnp.ones((2, 64), bool))


class TestBertFlashPath:
    def test_bert_flash_matches_naive(self):
        import dataclasses

        from lzy_tpu.models.bert import BertConfig, BertMlm

        cfg = BertConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=2,
                         d_ff=128, max_seq_len=128, dtype=jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 128), 0, 512)
        attn_mask = jnp.asarray(
            np.arange(128)[None, :] < np.array([[128], [80]])
        )
        model = BertMlm(cfg)
        params = model.init(jax.random.PRNGKey(1), tokens, attn_mask)
        naive = model.apply(params, tokens, attn_mask)
        flash_cfg = dataclasses.replace(cfg, use_flash_kernel=True)
        flash = BertMlm(flash_cfg).apply(params, tokens, attn_mask)
        np.testing.assert_allclose(np.asarray(flash), np.asarray(naive),
                                   atol=2e-4, rtol=2e-4)


class TestChunkedCrossEntropy:
    """ops/chunked_ce.py must match the dense logits path exactly — value AND
    gradients — while never materializing [N, V]."""

    def _setup(self, n=12, d=16, v=64, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
        head = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)
        return x, head, labels

    def _dense(self, x, head, labels, mask=None):
        from lzy_tpu.models.common import cross_entropy_loss

        logits = jnp.einsum("nd,vd->nv", x, head,
                            preferred_element_type=jnp.float32)
        return cross_entropy_loss(logits, labels, mask)

    def test_forward_matches_dense(self):
        from lzy_tpu.ops.chunked_ce import chunked_cross_entropy

        x, head, labels = self._setup()
        fused = chunked_cross_entropy(x, head, labels, chunk=16)
        assert jnp.allclose(fused, self._dense(x, head, labels), atol=1e-5)

    def test_gradients_match_dense(self):
        from lzy_tpu.ops.chunked_ce import chunked_cross_entropy

        x, head, labels = self._setup()
        gx_f, gh_f = jax.grad(
            lambda a, h: chunked_cross_entropy(a, h, labels, chunk=16),
            argnums=(0, 1))(x, head)
        gx_d, gh_d = jax.grad(
            lambda a, h: self._dense(a, h, labels), argnums=(0, 1))(x, head)
        assert jnp.allclose(gx_f, gx_d, atol=1e-5)
        assert jnp.allclose(gh_f, gh_d, atol=1e-5)

    def test_mask_weighting_matches(self):
        import numpy as np

        from lzy_tpu.ops.chunked_ce import chunked_cross_entropy

        x, head, labels = self._setup()
        mask = jnp.asarray(
            np.random.default_rng(1).integers(0, 2, size=labels.shape),
            jnp.float32)
        fused = chunked_cross_entropy(x, head, labels, chunk=16, mask=mask)
        dense = self._dense(x, head, labels, mask)
        assert jnp.allclose(fused, dense, atol=1e-5)
        gx_f = jax.grad(lambda a: chunked_cross_entropy(
            a, head, labels, chunk=16, mask=mask))(x)
        gx_d = jax.grad(lambda a: self._dense(a, head, labels, mask))(x)
        assert jnp.allclose(gx_f, gx_d, atol=1e-5)

    def test_batched_and_indivisible_chunk(self):
        import numpy as np

        from lzy_tpu.ops.chunked_ce import chunked_cross_entropy

        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 6, 16)), jnp.float32)
        head = jnp.asarray(rng.standard_normal((60, 16)), jnp.float32)  # 60 % 16 != 0
        labels = jnp.asarray(rng.integers(0, 60, size=(2, 6)), jnp.int32)
        fused = chunked_cross_entropy(x, head, labels, chunk=16)
        dense = self._dense(x.reshape(12, 16), head, labels.reshape(12))
        assert jnp.allclose(fused, dense, atol=1e-5)

    def test_fused_llama_loss_matches_dense(self):
        import dataclasses

        from lzy_tpu.models import llama, unbox

        cfg = llama.LlamaConfig.tiny(vocab_size=128)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
        dense_loss = llama.make_loss_fn(cfg)(params, {"tokens": tokens})
        fused_cfg = dataclasses.replace(cfg, fused_ce=True)
        fused_loss = llama.make_loss_fn(fused_cfg)(params, {"tokens": tokens})
        assert jnp.allclose(dense_loss, fused_loss, atol=1e-4)

    def test_generate_works_with_fused_ce_config(self):
        import dataclasses

        from lzy_tpu.models import generate, llama, unbox

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=128),
                                  fused_ce=True)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        prompt = jnp.array([[5, 7, 9]], jnp.int32)
        out = generate(cfg, params, prompt, max_new_tokens=4,
                       temperature=0.0)
        assert out.shape[1] == prompt.shape[1] + 4


class TestSegmentedAttention:
    """Packed-document masking: attention confined to equal segment ids, in
    both the Pallas kernel (with its data-dependent block skipping) and the
    chunked fallback, forward and backward."""

    @staticmethod
    def dense_segmented(q, k, v, seg, causal):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (d ** -0.5)
        keep = seg[:, None, :, None] == seg[:, None, None, :]
        if causal:
            t = q.shape[2]
            keep = keep & np.tril(np.ones((t, t), bool))[None, None]
        s = jnp.where(keep, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))

    @staticmethod
    def packed_segments(b=2, t=256, seed=1):
        """Non-decreasing ids with uneven document lengths per row."""
        rng = np.random.default_rng(seed)
        out = np.zeros((b, t), np.int32)
        for i in range(b):
            cuts = np.sort(rng.choice(np.arange(1, t), size=3, replace=False))
            out[i] = np.searchsorted(cuts, np.arange(t), side="right")
        return jnp.asarray(out)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_matches_dense(self, causal):
        q, k, v = make_qkv()
        seg = self.packed_segments()
        out = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                              block_q=128, block_kv=128)
        ref = self.dense_segmented(q, k, v, seg, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_dense(self, causal):
        q, k, v = make_qkv()
        seg = self.packed_segments()
        out = chunked_attention(q, k, v, causal=causal, segment_ids=seg,
                                block_size=64)
        ref = self.dense_segmented(q, k, v, seg, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_gradients_match_dense(self):
        q, k, v = make_qkv(t=256, d=32)
        seg = self.packed_segments()

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, segment_ids=seg,
                                   block_q=128, block_kv=128).sum()

        def loss_dense(q, k, v):
            return self.dense_segmented(q, k, v, seg, True).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_segments_plus_kv_mask_compose(self):
        q, k, v = make_qkv()
        seg = self.packed_segments()
        mask = jnp.ones(seg.shape, bool).at[:, -64:].set(False)
        out = flash_attention(q, k, v, causal=False, segment_ids=seg,
                              kv_mask=mask)
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (d ** -0.5)
        keep = (seg[:, None, :, None] == seg[:, None, None, :]) \
            & mask[:, None, None, :]
        ref = jnp.einsum("bhqk,bhkd->bhqd",
                         jax.nn.softmax(jnp.where(keep, s, -1e30), -1),
                         v.astype(jnp.float32))
        # flash semantics: a query whose whole document is masked out gets
        # zero output (naive softmax would give a uniform average instead)
        ref = jnp.where(keep.any(-1)[..., None], ref, 0.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_segment_equals_plain(self):
        q, k, v = make_qkv()
        seg = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        out = flash_attention(q, k, v, causal=True, segment_ids=seg)
        ref = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)

    def test_bad_segment_shape_rejected(self):
        q, k, v = make_qkv()
        with pytest.raises(ValueError, match="segment_ids"):
            flash_attention(q, k, v, segment_ids=jnp.zeros((2, 8), jnp.int32))

    @pytest.mark.parametrize("causal", [False, True])
    def test_repeated_id_in_nonadjacent_runs_is_a_new_document(self, causal):
        """Documents are contiguous RUNS: reusing an id later must start a
        new document, identically in the flash kernel (whose block skipping
        is run-based) and the chunked fallback."""
        q, k, v = make_qkv()
        seg = jnp.asarray(
            np.concatenate([np.zeros(64), np.ones(64), np.zeros(128)])
            .astype(np.int32)[None, :].repeat(2, 0)
        )
        out_flash = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                                    block_q=128, block_kv=128)
        out_chunk = chunked_attention(q, k, v, causal=causal,
                                      segment_ids=seg, block_size=64)
        # run-normalized ids = what both paths must behave like
        runs = jnp.asarray(
            np.concatenate([np.zeros(64), np.ones(64), 2 * np.ones(128)])
            .astype(np.int32)[None, :].repeat(2, 0)
        )
        ref = self.dense_segmented(q, k, v, runs, causal)
        np.testing.assert_allclose(np.asarray(out_flash), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(out_chunk), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
