"""Kernel tests: chunked attention and the Pallas flash kernel (interpret mode
on CPU; the same code compiles natively on TPU) against a dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.ops import chunked_attention, flash_attention


def dense_reference(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (d ** -0.5)
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def make_qkv(b=2, h=2, t=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, t, d), dtype) for k in ks)


class TestChunkedAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        out = chunked_attention(q, k, v, causal=causal, block_size=64)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v = make_qkv(t=128, d=32)

        def loss_chunked(q, k, v):
            return chunked_attention(q, k, v, causal=True, block_size=32).sum()

        def loss_dense(q, k, v):
            return dense_reference(q, k, v, True).sum()

        g1 = jax.grad(loss_chunked, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        q, k, v = make_qkv(t=128, d=32, seed=3)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal,
                                  block_q=32, block_kv=32)
            return (out * out).sum()

        def loss_dense(q, k, v):
            out = dense_reference(q, k, v, causal)
            return (out * out).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_bfloat16_inputs(self):
        q, k, v = make_qkv(dtype=jnp.bfloat16, seed=5)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
        assert out.dtype == jnp.bfloat16
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
        )

    def test_rejects_misaligned_seq(self):
        q, k, v = make_qkv(t=100)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, block_kv=64)

    def test_jit_compose(self):
        q, k, v = make_qkv(t=128, d=32)
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, block_q=32, block_kv=32
        ))(q, k, v)
        assert out.shape == q.shape
