"""Fake PostgreSQL DBAPI driver for exercising PostgresOperationStore
everywhere (no server on CI hosts; VERDICT r4 #2 asks the durable tiers
to run against a second backend).

It implements exactly the DBAPI slice the store uses — ``cursor()``,
``execute(sql, params)``, fetchone/fetchall/rowcount, autocommit — by
back-translating the PG dialect (``%s`` placeholders,
``IS NOT DISTINCT FROM``) onto a SQLite file, which IS a faithful
executor for this store's SQL (the canonical dialect is SQLite's). The
real-server leg still exists behind ``LZY_PG_DSN``; this fake covers
the translation layer, the retry discipline (injectable 40001s) and the
multi-plane integrity paths on every run.
"""

import sqlite3
import threading


class FakePgError(Exception):
    def __init__(self, msg, pgcode=None):
        super().__init__(msg)
        self.pgcode = pgcode


class FakePgIntegrityError(FakePgError):
    pass


def _back_translate(sql: str) -> str:
    return sql.replace("IS NOT DISTINCT FROM %s", "IS ?").replace("%s", "?")


class FakePgCursor:
    def __init__(self, conn):
        self._conn = conn
        self._cur = None

    def execute(self, sql, params=()):
        if self._conn.fail_next_sqlstates:
            code = self._conn.fail_next_sqlstates.pop(0)
            raise FakePgError(f"injected SQLSTATE {code}", pgcode=code)
        try:
            self._cur = self._conn.sqlite.execute(
                _back_translate(sql), params)
            self._conn.sqlite.commit()  # autocommit semantics
        except sqlite3.IntegrityError as e:
            raise FakePgIntegrityError(str(e), pgcode="23505") from e
        return self

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    @property
    def rowcount(self):
        return self._cur.rowcount


class FakePgConnection:
    def __init__(self, path):
        self.sqlite = sqlite3.connect(path, check_same_thread=False)
        self.autocommit = True
        self.fail_next_sqlstates = []   # test hook: inject retryable errors
        self._lock = threading.RLock()

    def cursor(self):
        return FakePgCursor(self)

    def commit(self):
        pass

    def rollback(self):
        self.sqlite.rollback()

    def close(self):
        self.sqlite.close()


def fake_connect(path):
    """Drop-in for pg_store.connect, bound to a sqlite file 'server'."""
    conn = FakePgConnection(path)
    return conn, FakePgIntegrityError, lambda e: getattr(e, "pgcode", None)
