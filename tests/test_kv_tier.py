"""Tiered KV cache: HBM → host RAM → storage, with cross-replica import.

Acceptance criterion (ISSUE 11): a replica that misses a prefix locally
imports a sibling's (or the storage tier's) blocks instead of
re-prefilling — demonstrated by bit-identical greedy output against the
uninterrupted ``generate()`` oracle with ``lzy_kvtier_imports_total``
moved and prefill-tokens-saved accounted — and ANY tier/transport
failure (including the ``kvtier.demote``/``kvtier.import`` chaos
faults at rate 1.0) degrades to a local re-prefill with the request
never failing.

Layers:

- host-tier units: LRU within the byte budget, take/peek/restore
  semantics, storage spill in the ``kv_block_manifest`` format;
- engine integration: radix eviction demotes instead of drops,
  admission promotes back, provenance rides the re-insert;
- the gateway's fleet-global prefix index + cross-replica import;
- invariants: a payload lives in exactly one tier
  (``audit_kv_tier``), byte accounting, double-residency detection;
- fixed-seed chaos: every tier op failing leaves greedy output
  bit-identical.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lzy_tpu.chaos import (
    CHAOS, FaultPlan, InvariantViolation, audit_engine, audit_kv_tier)
from lzy_tpu.chaos.faults import ERROR
from lzy_tpu.gateway import (
    GatewayService, GlobalKVIndex, ReplicaFleet, RoundRobinRouter)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import PagedInferenceEngine, RadixCache
from lzy_tpu.serving.kv_tier import HostKVTier, StorageKVTier, TierEntry

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


@pytest.fixture(autouse=True)
def _disarmed():
    CHAOS.disarm()
    yield
    CHAOS.disarm()


def _oracle(cfg, params, prompt, n):
    out = generate(cfg, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _run(engine, prompt, n=6):
    """Drive a synchronous engine to one request's completion."""
    req = engine.submit(prompt, max_new_tokens=n)
    for _ in range(500):
        engine.step()
        if req.done:
            break
    assert req.done, "request never finished"
    assert req.error is None, req.error
    return list(req.tokens)


def _entry(chain, nbytes=64, origin=None):
    return tuple(chain), {"k": np.zeros((nbytes // 4,), np.float32)}, origin


# ---------------------------------------------------------------------------
# host-tier units


class TestHostTierUnits:
    def test_put_take_peek_roundtrip(self):
        tier = HostKVTier(1 << 16, PAGE)
        chain, leaves, _ = _entry(range(PAGE))
        assert tier.put(chain, leaves)
        assert tier.peek(chain) is not None          # peek keeps it
        entry = tier.take(chain)
        assert entry is not None and entry.chain == chain
        assert entry.tier == "host"
        assert tier.take(chain) is None              # take popped it
        # promotions count LANDED promotions, not takes (a failed
        # promotion restores the entry and must not inflate the stat)
        assert tier.stats()["promotions"] == 0
        tier.note_promoted(entry.tier)
        assert tier.stats()["promotions"] == 1

    def test_budget_lru_evicts_oldest_without_storage(self):
        # budget fits exactly two 256-byte entries; the third put must
        # evict the LRU one, counted as a drop (no lower tier)
        tier = HostKVTier(512, PAGE)
        c1, l1, _ = _entry(range(PAGE), 256)
        c2, l2, _ = _entry(range(PAGE, 2 * PAGE), 256)
        c3, l3, _ = _entry(range(2 * PAGE, 3 * PAGE), 256)
        tier.put(c1, l1)
        tier.put(c2, l2)
        tier.peek(c1)        # peek must NOT refresh LRU (read-only)
        tier.put(c3, l3)
        assert tier.peek(c1) is None                 # oldest evicted
        assert tier.peek(c2) is not None
        assert tier.peek(c3) is not None
        s = tier.stats()
        assert s["dropped"] == 1 and s["host_bytes"] <= 512

    def test_oversize_entry_drops_immediately(self):
        tier = HostKVTier(64, PAGE)
        chain, leaves, _ = _entry(range(PAGE), 256)
        assert not tier.put(chain, leaves)
        assert tier.stats()["host_blocks"] == 0
        assert tier.stats()["dropped"] == 1

    def test_overflow_spills_to_storage_in_manifest_format(self):
        from lzy_tpu.channels.kv_transfer import (
            KV_MANIFEST_FORMAT, parse_kv_manifest)
        from lzy_tpu.storage.mem import MemStorageClient

        storage = MemStorageClient()
        st = StorageKVTier(storage, "mem://bucket/kvtier", PAGE)
        tier = HostKVTier(256, PAGE, storage=st)
        c1, l1, o1 = _entry(range(PAGE), 256, origin="replica-9")
        c2, l2, _ = _entry(range(PAGE, 2 * PAGE), 256)
        tier.put(c1, l1, origin=o1)
        tier.put(c2, l2)                 # budget overflow: c1 -> storage
        # spills upload on a worker thread (never the engine's
        # scheduling thread); flush before asserting on the landing
        assert tier.flush_spills()
        assert tier.peek(c1) is None
        assert tier.stats()["demotions_to_storage"] == 1
        # the spilled object IS a kv_block_manifest naming a whole payload
        doc = parse_kv_manifest(storage.read_bytes(st._uri(c1)))
        assert doc["format"] == KV_MANIFEST_FORMAT
        assert doc["tokens"] == list(c1)
        assert doc["prefilled_by"] == "replica-9"
        for meta in doc["leaves"].values():
            assert storage.exists(meta["uri"])       # leaves landed first
        # promotion falls through host -> storage; provenance survives
        entry = tier.take(c1)
        assert entry is not None and entry.tier == "storage"
        assert entry.origin == "replica-9"
        np.testing.assert_array_equal(entry.leaves["k"], l1["k"])

    def test_storage_rejects_a_foreign_chain(self):
        from lzy_tpu.storage.mem import MemStorageClient

        storage = MemStorageClient()
        st = StorageKVTier(storage, "mem://bucket/kvtier2", PAGE)
        chain, leaves, _ = _entry(range(PAGE), 64)
        st.put(TierEntry(chain, leaves))
        other = tuple(range(PAGE, 2 * PAGE))
        # copy the spilled manifest under the OTHER chain's uri: the
        # token check must fail closed (garbage KV must never scatter)
        storage.write_bytes(st._uri(other), storage.read_bytes(
            st._uri(chain)))
        assert st.get(other) is None
        assert st.get(chain) is not None


# ---------------------------------------------------------------------------
# engine integration: demote on eviction, promote at admission


class TestTierEngine:
    def _engine(self, tiny_model, **kw):
        cfg, params = tiny_model
        kw.setdefault("slots", 1)
        kw.setdefault("page_size", PAGE)
        kw.setdefault("kv_blocks", 5)    # 4 usable: evictions guaranteed
        return PagedInferenceEngine(cfg, params, **kw)

    def test_eviction_demotes_and_admission_promotes_bit_identical(
            self, tiny_model):
        cfg, params = tiny_model
        eng = self._engine(tiny_model, kv_host_tier_bytes=1 << 20)
        try:
            a = list(range(1, 3 * PAGE + 1)) + [5]
            b = list(range(30, 54)) + [7]
            ta = _run(eng, a)
            assert ta == _oracle(cfg, params, a, 6)
            _run(eng, b)                 # evicts A's blocks -> host tier
            s = eng.kv_tier.stats()
            assert s["demotions"] > 0 and s["host_blocks"] > 0
            audit_engine(eng)
            saved_before = eng.kv.stats().prefill_tokens_saved
            ta2 = _run(eng, a)           # promoted back from host RAM
            assert ta2 == ta
            assert eng.kv_tier.stats()["promotions"] > 0
            # the promoted prefix counts as prefill work SAVED — the
            # honest accounting the acceptance criterion asks for
            assert eng.kv.stats().prefill_tokens_saved > saved_before
            audit_engine(eng)
            st = eng.stats()
            assert st.kv_tier_demotions > 0 and st.kv_tier_promotions > 0
            assert st.kv_host_tier_bytes is not None
        finally:
            eng.close()

    def test_storage_tier_warms_a_fresh_replica(self, tiny_model):
        """Cross-replica warm-up through the fleet-shared storage rung:
        engine 1 demotes through its host tier into storage; a FRESH
        engine 2 sharing the storage root promotes those chains at
        admission — the autoscale/failover cache-warm-up path, bit
        identical to an uninterrupted local run."""
        from lzy_tpu.storage.mem import MemStorageClient

        cfg, params = tiny_model
        st = StorageKVTier(MemStorageClient(), "mem://bucket/fleet-tier",
                           PAGE)
        a = list(range(1, 3 * PAGE + 1)) + [5]
        b = list(range(30, 54)) + [7]
        e1 = self._engine(tiny_model, kv_host_tier_bytes=0,
                          kv_storage_tier=st)
        try:
            ta = _run(e1, a)
            _run(e1, b)                  # A's blocks spill to storage
            assert e1.kv_tier.flush_spills()
            assert st.stats()["storage_blocks"] > 0
        finally:
            e1.close()
        e2 = self._engine(tiny_model, kv_host_tier_bytes=0,
                          kv_storage_tier=st)
        try:
            ta2 = _run(e2, a)
            assert ta2 == ta == _oracle(cfg, params, a, 6)
            assert e2.kv_tier.stats()["promotions_from_storage"] > 0
            assert e2.kv.stats().prefill_tokens_saved > 0
            audit_engine(e2)
        finally:
            e2.close()

    def test_mismatched_quant_tier_fails_closed(self, tiny_model):
        """A quantized pool must not scatter an fp tier payload (and
        vice versa): promotion fails closed and the prompt re-prefills —
        wrong-but-served is the one outcome the tier may never produce."""
        from lzy_tpu.storage.mem import MemStorageClient

        cfg, params = tiny_model
        st = StorageKVTier(MemStorageClient(), "mem://bucket/quant-tier",
                           PAGE)
        a = list(range(1, 3 * PAGE + 1)) + [5]
        b = list(range(30, 54)) + [7]
        e1 = self._engine(tiny_model, kv_host_tier_bytes=0,
                          kv_storage_tier=st)
        try:
            _run(e1, a)
            _run(e1, b)
        finally:
            e1.close()
        e2 = self._engine(tiny_model, kv_host_tier_bytes=0,
                          kv_storage_tier=st, kv_quant="int8")
        try:
            ta = _run(e2, a)             # promotion refused, local prefill
            assert len(ta) == 6
            # nothing from the fp spill may be resident in the int8 pool
            assert e2.kv_imports == 0
            audit_engine(e2)
        finally:
            e2.close()


# ---------------------------------------------------------------------------
# the gateway's fleet-global prefix index + cross-replica import


def _build_gateway(cfg, params, *, kv_index=True, replicas=2, **ekw):
    ekw.setdefault("slots", 2)
    ekw.setdefault("page_size", PAGE)
    ekw.setdefault("kv_blocks", 32)
    fleet = ReplicaFleet(
        lambda: PagedInferenceEngine(cfg, params, **ekw))
    gw = GatewayService(
        fleet,
        # round-robin pins request i to replica (i % N): the second
        # request DETERMINISTICALLY lands on the cold replica — the
        # shape the cross-replica import exists for
        router=RoundRobinRouter(PAGE),
        kv_index=GlobalKVIndex(PAGE) if kv_index else None,
        model_name="tiny")
    for _ in range(replicas):
        fleet.add_replica()
    return gw, fleet


class TestCrossReplicaImport:
    def test_cold_replica_imports_instead_of_reprefilling(
            self, tiny_model):
        """THE acceptance test: shared-prefix traffic routed to a cold
        replica imports the warm sibling's blocks over the transport —
        greedy output bit-identical to the oracle, imports counted,
        prefill tokens saved on the importer."""
        from lzy_tpu.gateway.kv_index import IMPORTS

        cfg, params = tiny_model
        gw, fleet = _build_gateway(cfg, params)
        try:
            shared = list(range(1, 4 * PAGE + 1))
            p1, p2 = shared + [5], shared + [9]
            imports_before = sum(IMPORTS._values.values())
            r1 = gw.generate(p1, max_new_tokens=6, timeout_s=120)
            assert r1["tokens"] == _oracle(cfg, params, p1, 6)
            gw.tick()        # replicas advertise into the global index
            r2 = gw.generate(p2, max_new_tokens=6, timeout_s=120)
            assert r2["tokens"] == _oracle(cfg, params, p2, 6)
            assert r2["replica"] != r1["replica"]
            # staged AND used: the sibling's export was staged for this
            # attempt, and the prefix match really hit its blocks
            assert r2["kv_import_staged_from"] == r1["replica"]
            assert r2["kv_import_from"] == r1["replica"]
            assert r2["kv_import_tier"] == "hbm"
            assert r2["kv_import_ms"] is not None
            stats = gw.stats()
            assert stats["kvtier_imports"] == 1
            assert stats["kvtier_import_bytes"] > 0
            cold = fleet.get(r2["replica"]).engine
            assert cold.kv_imports == 1
            assert cold.kv.stats().prefill_tokens_saved >= 4 * PAGE
            # the wire metric the acceptance criterion names
            imports_now = sum(IMPORTS._values.values())
            assert imports_now > imports_before
            for replica in fleet.replicas():
                audit_engine(replica.engine)
        finally:
            gw.close()

    def test_transport_death_degrades_to_local_reprefill(
            self, tiny_model):
        from lzy_tpu.channels.kv_transfer import InMemoryKVTransport

        cfg, params = tiny_model
        gw, fleet = _build_gateway(cfg, params)
        try:
            gw.kv_transport = InMemoryKVTransport()
            gw.kv_transport.fail_next_fetch = 1
            shared = list(range(1, 4 * PAGE + 1))
            r1 = gw.generate(shared + [5], max_new_tokens=6,
                             timeout_s=120)
            gw.tick()
            r2 = gw.generate(shared + [9], max_new_tokens=6,
                             timeout_s=120)
            # the transfer died mid-stream; the request NEVER fails —
            # the cold replica re-prefilled locally
            assert r2["status"] == "ok"
            assert r2["tokens"] == _oracle(cfg, params, shared + [9], 6)
            assert r2["kv_import_from"] is None
            assert gw.stats()["kvtier_reprefill_fallbacks"] == 1
        finally:
            gw.close()

    def test_index_forgets_retired_replicas(self, tiny_model):
        cfg, params = tiny_model
        gw, fleet = _build_gateway(cfg, params)
        try:
            shared = list(range(1, 3 * PAGE + 1))
            r1 = gw.generate(shared + [5], max_new_tokens=4,
                             timeout_s=120)
            gw.tick()
            assert gw.kv_index.stats()["replicas_advertising"] >= 1
            gw.kv_index.forget(r1["replica"])
            idx = gw.kv_index.stats()["indexed_chains"]
            assert r1["replica"] not in idx
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# invariants


class TestTierInvariants:
    def test_double_residency_is_caught(self):
        kv = RadixCache(8, PAGE)
        tier = HostKVTier(1 << 16, PAGE)
        chain = list(range(PAGE))
        blocks = kv.allocate(1)
        kv.insert(chain, blocks)
        kv.release(blocks)
        # bypass the discard hook: the SAME chain filed in the tier
        tier.restore(TierEntry(tuple(chain),
                               {"k": np.zeros((4,), np.float32)}))
        with pytest.raises(InvariantViolation, match="double residency"):
            audit_kv_tier(kv, tier)

    def test_byte_drift_is_caught(self):
        kv = RadixCache(8, PAGE)
        tier = HostKVTier(1 << 16, PAGE)
        tier.put(tuple(range(PAGE, 2 * PAGE)),
                 {"k": np.zeros((4,), np.float32)})
        tier._bytes += 1
        with pytest.raises(InvariantViolation, match="byte accounting"):
            audit_kv_tier(kv, tier)

    def test_partial_chain_is_caught(self):
        kv = RadixCache(8, PAGE)
        tier = HostKVTier(1 << 16, PAGE)
        tier.restore(TierEntry(tuple(range(PAGE - 1)),
                               {"k": np.zeros((4,), np.float32)}))
        with pytest.raises(InvariantViolation, match="whole-block"):
            audit_kv_tier(kv, tier)

    def test_clean_tier_audits_clean(self):
        kv = RadixCache(8, PAGE)
        tier = HostKVTier(1 << 16, PAGE)
        tier.put(tuple(range(PAGE)), {"k": np.zeros((4,), np.float32)})
        audit_kv_tier(kv, tier)


# ---------------------------------------------------------------------------
# fixed-seed chaos: every tier op failing must be invisible to clients


@pytest.mark.chaos
class TestKvTierChaos:
    def test_all_demotions_failing_stays_bit_identical(self, tiny_model):
        """kvtier.demote at rate 1.0: every demotion is injected dead —
        the tier degrades to classic eviction, greedy output stays
        bit-identical to the generate() oracle, auditors stay clean."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE,
                                   kv_blocks=5,
                                   kv_host_tier_bytes=1 << 20)
        plan = CHAOS.arm(FaultPlan(20260811, rate=1.0, modes=(ERROR,),
                                   points=("kvtier.demote",)))
        try:
            a = list(range(1, 3 * PAGE + 1)) + [5]
            b = list(range(30, 54)) + [7]
            assert _run(eng, a) == _oracle(cfg, params, a, 6)
            assert _run(eng, b) == _oracle(cfg, params, b, 6)
            assert _run(eng, a) == _oracle(cfg, params, a, 6)
            assert plan.fired > 0, plan.describe()
            assert eng.kv_tier.stats()["host_blocks"] == 0
            assert eng.kv_tier.stats()["dropped"] > 0
            audit_engine(eng)
        finally:
            CHAOS.disarm()
            eng.close()

    def test_all_promotions_failing_stays_bit_identical(self, tiny_model):
        """kvtier.import at rate 1.0: every promotion attempt dies —
        admission falls back to a full local re-prefill, bit-identical,
        popped entries restored to the tier (no payload leak)."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE,
                                   kv_blocks=5,
                                   kv_host_tier_bytes=1 << 20)
        try:
            a = list(range(1, 3 * PAGE + 1)) + [5]
            b = list(range(30, 54)) + [7]
            ta = _run(eng, a)
            _run(eng, b)
            demoted = eng.kv_tier.stats()["host_blocks"]
            assert demoted > 0
            plan = CHAOS.arm(FaultPlan(20260812, rate=1.0, modes=(ERROR,),
                                       points=("kvtier.import",)))
            assert _run(eng, a) == ta == _oracle(cfg, params, a, 6)
            assert plan.fired > 0, plan.describe()
            CHAOS.disarm()
            # nothing was promoted while the point was armed (the fault
            # fires before any entry is popped), and the re-prefill's
            # radix insert reclaimed A's chains for HBM — one tier owns
            # them, which is exactly what the auditor checks
            assert eng.kv_tier.stats()["promotions"] == 0
            audit_engine(eng)
            # the quiet tail: evict A again, then promote it cleanly
            _run(eng, b)
            assert _run(eng, a) == ta
            assert eng.kv_tier.stats()["promotions"] > 0
            audit_engine(eng)
        finally:
            CHAOS.disarm()
            eng.close()

    def test_gateway_import_fault_never_fails_the_request(
            self, tiny_model):
        """kvtier.import injected at the gateway's cross-replica staging:
        the import attempt dies, the fallback is counted, and the routed
        replica serves bit-identically by re-prefilling."""
        cfg, params = tiny_model
        gw, fleet = _build_gateway(cfg, params)
        plan = CHAOS.arm(FaultPlan(20260813, rate=1.0, modes=(ERROR,),
                                   points=("kvtier.import",)))
        try:
            shared = list(range(1, 4 * PAGE + 1))
            r1 = gw.generate(shared + [5], max_new_tokens=6,
                             timeout_s=120)
            gw.tick()
            r2 = gw.generate(shared + [9], max_new_tokens=6,
                             timeout_s=120)
            assert r2["status"] == "ok"
            assert r2["tokens"] == _oracle(cfg, params, shared + [9], 6)
            assert r2["kv_import_from"] is None
            assert r1["status"] == "ok"
            assert plan.fired > 0, plan.describe()
            assert gw.stats()["kvtier_reprefill_fallbacks"] >= 1
            for replica in fleet.replicas():
                audit_engine(replica.engine)
        finally:
            CHAOS.disarm()
            gw.close()


class TestBatchedDemotionGathers:
    """Satellite (ROADMAP item 2 remainder): one eviction round's
    per-block device→host copies coalesce into a single gather per
    cache leaf (``RadixCache.on_evict_batch`` →
    ``PagedInferenceEngine._demote_blocks``)."""

    def test_one_gather_per_leaf_per_eviction_round(self, tiny_model):
        cfg, params = tiny_model
        # pool: 1 scratch + 6 usable. Request A caches a 4-block chain;
        # request B (disjoint 4-block prompt + growth) then needs more
        # than the free list holds — ONE allocate call evicts several of
        # A's blocks in a single round.
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE,
                                   kv_blocks=7,
                                   kv_host_tier_bytes=8 << 20)
        try:
            a = list(range(1, 4 * PAGE + 1)) + [3]
            b = [(11 * i) % 60 + 1 for i in range(4 * PAGE)] + [9]
            assert _run(eng, a) == _oracle(cfg, params, a, 6)
            assert eng.kv_tier_gather_rounds == 0
            rounds_before = eng.kv_tier_gather_rounds
            ops_before = eng.kv_tier_gather_ops
            demoted_before = eng.kv_tier.stats()["demotions"]
            assert _run(eng, b) == _oracle(cfg, params, b, 6)
            rounds = eng.kv_tier_gather_rounds - rounds_before
            ops = eng.kv_tier_gather_ops - ops_before
            demoted = eng.kv_tier.stats()["demotions"] - demoted_before
            n_leaves = sum(1 for k in eng._kv_leaf_keys()
                           if k is not None)
            # the count-of-transfers contract: >= 2 blocks demoted in
            # ONE round, paying exactly one gather PER LEAF — not one
            # per (leaf x block) as the per-block path did
            assert demoted >= 2, demoted
            assert rounds == 1, (rounds, demoted)
            assert ops == n_leaves, (ops, n_leaves, demoted)
            # demoted payloads are real: each chain is promotable
            assert eng.kv_tier.stats()["host_blocks"] == demoted
            audit_engine(eng)
            audit_kv_tier(eng.kv, eng.kv_tier)
        finally:
            eng.close()

    def test_batched_demotions_promote_back_bit_identical(self,
                                                          tiny_model):
        """The batched payloads are byte-correct: re-running the evicted
        prompt promotes the demoted chain back and the output stays
        bit-identical with prefill tokens saved."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=PAGE,
                                   kv_blocks=7,
                                   kv_host_tier_bytes=8 << 20)
        try:
            a = list(range(1, 4 * PAGE + 1)) + [3]
            b = [(11 * i) % 60 + 1 for i in range(4 * PAGE)] + [9]
            _run(eng, a)
            _run(eng, b)                     # batch-demotes A's chain
            saved_before = eng.kv.stats().prefill_tokens_saved
            promoted_before = eng.kv_tier.stats()["promotions"]
            assert _run(eng, a) == _oracle(cfg, params, a, 6)
            assert eng.kv_tier.stats()["promotions"] > promoted_before
            assert eng.kv.stats().prefill_tokens_saved > saved_before
            audit_engine(eng)
            audit_kv_tier(eng.kv, eng.kv_tier)
        finally:
            eng.close()
