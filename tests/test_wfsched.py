"""Workflow-aware serving scheduler (``lzy_tpu/llm/sched.py``): the
acceptance properties and failure paths.

- **In-flight dedup**: N identical in-flight greedy calls reach the
  fleet as exactly ONE engine request whose reply fans out to every
  waiter; sampled/streaming calls never dedup; a cancelled or failed
  leader is its own outcome — followers re-dispatch, they do not
  inherit it.
- **Fused op chains**: step 2 of a ``generate → tool-op → generate``
  chain hard-pins to the replica holding the parked KV and re-prefills
  NOTHING of the shared prefix (asserted via ``prefill_tokens_saved``),
  bit-identical to the unfused oracle.
- **Failure paths**: replica death mid-tool-gap drops the lease and
  the chain falls back to the routed path (still bit-identical); a
  parked chain's TTL expiry releases it at the next engine round; KV
  pressure sheds parked chains BEFORE any resident request suffers.
"""

import threading
import time

import jax
import pytest

from lzy_tpu import Lzy, llm
from lzy_tpu.llm.sched import WorkflowScheduler
from lzy_tpu.gateway import GatewayService, PrefixAffinityRouter, ReplicaFleet
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate as oracle_generate
from lzy_tpu.serving import PagedInferenceEngine
from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig
from lzy_tpu.utils.clock import SYSTEM_CLOCK

import jax.numpy as jnp
import numpy as np

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


@pytest.fixture(autouse=True)
def _clean_backend():
    yield
    llm.configure(None)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = oracle_generate(cfg, params,
                          jnp.asarray([prompt_ids], jnp.int32),
                          max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _make_gateway(cfg, params, *, replicas=2, slots=2, **engine_kw):
    def factory():
        return PagedInferenceEngine(cfg, params, slots=slots,
                                    page_size=PAGE, **engine_kw)

    fleet = ReplicaFleet(factory)
    gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                        model_name="tiny")
    for _ in range(replicas):
        fleet.add_replica()
    return gw, fleet


def _local_lzy(uri: str) -> Lzy:
    reg = DefaultStorageRegistry()
    reg.register_storage("default", StorageConfig(uri=uri), default=True)
    return Lzy(storage_registry=reg)


def _wait_until(pred, timeout=15.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _prefill_saved(fleet) -> int:
    return sum(r.engine.stats().prefill_tokens_saved or 0
               for r in fleet.replicas())


def _parked_released(reason: str) -> float:
    from lzy_tpu.serving.engine import _PARKED_RELEASED

    return sum(v for k, v in _PARKED_RELEASED._values.items()
               if reason in str(k))


# -- in-flight dedup (the admission fan-in plane) -----------------------------

class _GatedBackend:
    """Fake serving plane: every generate blocks on ``gate`` (so the
    test controls overlap) and is counted."""

    def __init__(self, replies=None):
        self.calls = 0
        self.gate = threading.Event()
        self._lock = threading.Lock()
        self._replies = replies

    def model_digest(self):
        return "fake-digest"

    def generate(self, prompt, **kw):
        with self._lock:
            self.calls += 1
            n = self.calls
        if not self.gate.wait(30):
            raise TimeoutError("test gate never opened")
        if self._replies is not None:
            return self._replies(n)
        return {"tokens": [100 + n], "status": "ok"}


def _dispatch_into(sched, results, i, prompt, **kw):
    def run():
        try:
            results[i] = sched.dispatch(prompt, **kw)
        except BaseException as e:  # noqa: BLE001 — asserted by the test
            results[i] = e

    t = threading.Thread(target=run)
    t.start()
    return t


class TestInflightDedup:
    def test_identical_greedy_calls_collapse_to_one_request(self):
        """Acceptance: N identical in-flight greedy calls reach the
        plane as exactly 1 request; every waiter gets the reply, with
        its OWN token list."""
        be = _GatedBackend()
        sched = WorkflowScheduler(be, dedup=True, fuse=False)
        try:
            results = {}
            threads = [_dispatch_into(sched, results, 0, [1, 2, 3],
                                      max_new_tokens=4, greedy=True)]
            assert _wait_until(lambda: be.calls == 1)
            threads += [_dispatch_into(sched, results, i, [1, 2, 3],
                                       max_new_tokens=4, greedy=True)
                        for i in (1, 2, 3)]
            assert _wait_until(
                lambda: sched.stats()["dedup_waiting"] == 3)
            be.gate.set()
            for t in threads:
                t.join(30)
            assert be.calls == 1
            assert all(results[i] == {"tokens": [101], "status": "ok"}
                       for i in range(4))
            # fan-out copies, never aliases: a waiter mutating its
            # Generation's tokens must not corrupt a sibling's
            lists = [results[i]["tokens"] for i in range(4)]
            for i in range(4):
                for j in range(i + 1, 4):
                    assert lists[i] is not lists[j]
            s = sched.stats()
            assert s["dispatches"] == 4
            assert s["dedup_hits"] == 3
            assert s["dedup_waiting"] == 0
        finally:
            sched.close()

    def test_different_slo_identity_never_dedups(self):
        """Same prompt, different tenant: a follower must not ride a
        reply another tenant's quota paid for."""
        be = _GatedBackend()
        sched = WorkflowScheduler(be, dedup=True, fuse=False)
        try:
            results = {}
            t1 = _dispatch_into(sched, results, 0, [1, 2], greedy=True,
                                max_new_tokens=4, tenant="a")
            assert _wait_until(lambda: be.calls == 1)
            t2 = _dispatch_into(sched, results, 1, [1, 2], greedy=True,
                                max_new_tokens=4, tenant="b")
            assert _wait_until(lambda: be.calls == 2)
            be.gate.set()
            t1.join(30)
            t2.join(30)
            assert sched.stats()["dedup_hits"] == 0
        finally:
            sched.close()

    @pytest.mark.parametrize("kw", [
        {"greedy": None},                       # sampled: a draw, not a
        {"greedy": False},                      # function of the inputs
        {"greedy": True, "stream": object()},   # stream: one channel
    ])
    def test_sampled_and_streaming_calls_never_dedup(self, kw):
        be = _GatedBackend()
        sched = WorkflowScheduler(be, dedup=True, fuse=False)
        try:
            results = {}
            t1 = _dispatch_into(sched, results, 0, [7, 8],
                                max_new_tokens=4, **kw)
            assert _wait_until(lambda: be.calls == 1)
            t2 = _dispatch_into(sched, results, 1, [7, 8],
                                max_new_tokens=4, **kw)
            # both are IN the backend concurrently — no rendezvous
            assert _wait_until(lambda: be.calls == 2)
            be.gate.set()
            t1.join(30)
            t2.join(30)
            assert sched.stats()["dedup_hits"] == 0
        finally:
            sched.close()

    def test_cancelled_leader_does_not_fail_followers(self):
        """A deadline-truncated leader reply (status 'cancelled') is the
        LEADER's outcome: the follower re-dispatches and completes."""
        be = _GatedBackend(replies=lambda n: (
            {"tokens": [1], "status": "cancelled"} if n == 1
            else {"tokens": [7, 8], "status": "ok"}))
        sched = WorkflowScheduler(be, dedup=True, fuse=False)
        try:
            results = {}
            t1 = _dispatch_into(sched, results, 0, [5, 5],
                                max_new_tokens=4, greedy=True)
            assert _wait_until(lambda: be.calls == 1)
            t2 = _dispatch_into(sched, results, 1, [5, 5],
                                max_new_tokens=4, greedy=True)
            assert _wait_until(
                lambda: sched.stats()["dedup_waiting"] == 1)
            be.gate.set()
            t1.join(30)
            t2.join(30)
            assert results[0] == {"tokens": [1], "status": "cancelled"}
            assert results[1] == {"tokens": [7, 8], "status": "ok"}
            assert be.calls == 2
            assert sched.stats()["dedup_hits"] == 0
        finally:
            sched.close()

    def test_failed_leader_does_not_fail_followers(self):
        """A leader that RAISES fails only its own caller — the
        follower becomes the new leader and succeeds."""
        calls = {"n": 0}
        gate = threading.Event()

        class RaiseThenOk:
            def model_digest(self):
                return "d"

            def generate(self, prompt, **kw):
                calls["n"] += 1
                if calls["n"] == 1:
                    gate.wait(30)
                    raise RuntimeError("leader replica on fire")
                return {"tokens": [9], "status": "ok"}

        sched = WorkflowScheduler(RaiseThenOk(), dedup=True, fuse=False)
        try:
            results = {}
            t1 = _dispatch_into(sched, results, 0, [3, 3],
                                max_new_tokens=2, greedy=True)
            assert _wait_until(lambda: calls["n"] == 1)
            t2 = _dispatch_into(sched, results, 1, [3, 3],
                                max_new_tokens=2, greedy=True)
            assert _wait_until(
                lambda: sched.stats()["dedup_waiting"] == 1)
            gate.set()
            t1.join(30)
            t2.join(30)
            assert isinstance(results[0], RuntimeError)
            assert results[1] == {"tokens": [9], "status": "ok"}
            assert calls["n"] == 2
        finally:
            sched.close()

    def test_follower_timeout_falls_back_to_its_own_dispatch(self):
        """A leader that outlives the follower's budget must not hold
        the follower hostage: past ``timeout_s`` it dispatches for
        itself (no dedup credit)."""
        calls = {"n": 0}
        gate = threading.Event()

        class SlowLeader:
            def model_digest(self):
                return "d"

            def generate(self, prompt, **kw):
                calls["n"] += 1
                n = calls["n"]
                if n == 1:
                    gate.wait(30)        # the leader, wedged
                return {"tokens": [n], "status": "ok"}

        sched = WorkflowScheduler(SlowLeader(), dedup=True, fuse=False)
        try:
            results = {}
            t1 = _dispatch_into(sched, results, 0, [4, 4],
                                max_new_tokens=2, greedy=True)
            assert _wait_until(lambda: calls["n"] == 1)
            t2 = _dispatch_into(sched, results, 1, [4, 4],
                                max_new_tokens=2, greedy=True,
                                timeout_s=0.3)
            t2.join(30)                  # returns while leader is stuck
            assert results[1] == {"tokens": [2], "status": "ok"}
            assert calls["n"] == 2
            assert sched.stats()["dedup_hits"] == 0
            gate.set()
            t1.join(30)
            assert results[0] == {"tokens": [1], "status": "ok"}
        finally:
            sched.close()

    def test_batch_rows_dedup_through_the_real_fleet(self, tiny_model):
        """`llm.generate_batch` with identical greedy rows: the fleet
        serves exactly the UNIQUE rows; every duplicate adopts a copy.
        Sampled rows never collapse."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://wfsched-batch")
            pa, pb = [5, 9, 3, 1], [7, 2, 8, 1, 4]
            base = gw.stats()["requests_finished"]
            with lzy.workflow("fanin"):
                outs = llm.generate_batch([pa, pa, pb, pa],
                                          max_new_tokens=4, greedy=True)
            outs = list(outs)
            assert gw.stats()["requests_finished"] - base == 2
            ea = _oracle_tokens(cfg, params, pa, 4)
            eb = _oracle_tokens(cfg, params, pb, 4)
            assert [g.tokens for g in outs] == [ea, ea, eb, ea]
            assert outs[0].status == outs[1].status == "ok"
            sched = llm.current_scheduler()
            assert sched.stats()["dedup_hits"] >= 2
            # sampled rows: each is its own draw — no collapse
            base = gw.stats()["requests_finished"]
            with lzy.workflow("fanin-sampled"):
                outs = llm.generate_batch([pa, pa, pa], max_new_tokens=4)
            assert len(list(outs)) == 3
            assert gw.stats()["requests_finished"] - base == 3
        finally:
            gw.close()


# -- fused op chains against the real fleet -----------------------------------

class TestFusedChain:
    P1 = [5, 9, 3, 1, 2, 6, 7, 4, 11, 12, 13, 14]      # 12 tokens

    def _run_chain(self, cfg, params, uri):
        """One generate → tool-gap → generate conversation; returns
        (g1, g2, step2_prefill_saved, sched_stats, gateway)."""
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy(uri)
            conv = llm.Conversation(f"chain-{uri[-6:]}")
            with lzy.workflow("step1"):
                g1 = llm.generate(self.P1, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            sched = llm.current_scheduler()
            sched.drain()                 # park + speculation settled
            saved0 = _prefill_saved(fleet)
            p2 = list(g1.full_tokens()) + [41, 42]
            with lzy.workflow("step2"):
                g2 = llm.generate(p2, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            return (g1, g2, _prefill_saved(fleet) - saved0,
                    sched.stats())
        finally:
            gw.close()
            llm.configure(None)

    def test_fused_step_skips_the_whole_shared_prefix(
            self, tiny_model, monkeypatch):
        """Acceptance: with fusion on, step 2 routes 'fused' to the
        pinned replica and its prefill matches EVERY whole page of the
        parked + speculated chain — step-1 prompt AND reply pages (16
        of 19 prompt tokens; 8-token pages) — where the unfused path
        re-prefills the reply positions (8 matched). Greedy output is
        bit-identical to the unfused oracle either way."""
        cfg, params = tiny_model
        monkeypatch.delenv("LZY_WFSCHED_FUSE", raising=False)
        g1f, g2f, saved_fused, stats_f = self._run_chain(
            cfg, params, "mem://wfsched-fused")
        monkeypatch.setenv("LZY_WFSCHED_FUSE", "0")
        g1u, g2u, saved_unfused, stats_u = self._run_chain(
            cfg, params, "mem://wfsched-plain")
        # bit-identity vs the monolithic oracle, fused and unfused
        e1 = _oracle_tokens(cfg, params, self.P1, 5)
        p2 = self.P1 + e1 + [41, 42]
        e2 = _oracle_tokens(cfg, params, p2, 5)
        assert g1f.tokens == g1u.tokens == e1
        assert g2f.tokens == g2u.tokens == e2
        # the fused chain pinned step 2 to the replica holding the KV
        assert g2f.routed_by == "fused"
        assert g2f.replica == g1f.replica
        assert stats_f["parks"] >= 1 and stats_f["speculations"] >= 1
        # ...and re-prefilled nothing of the shared prefix: the step-1
        # prompt page came from the ordinary radix cache, the reply
        # page ONLY exists because the speculation prefilled it
        assert saved_fused == 16
        # unfused: session affinity still finds the prompt page, but
        # the reply positions are decode output — never tree-cached —
        # so the shared prefix IS re-prefilled past the first page
        assert g2u.routed_by == "session"
        assert saved_unfused == 8
        assert stats_u["parks"] == 0 and stats_u["speculations"] == 0

    def test_replica_death_mid_gap_drops_lease_and_falls_back(
            self, tiny_model):
        """The pinned replica dies during the tool gap: the health tick
        retires it, the fusion lease (and its parked KV) dies with it,
        and step 2 serves bit-identically over the routed path."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://wfsched-kill")
            conv = llm.Conversation("killed-gap")
            p1 = TestFusedChain.P1
            with lzy.workflow("step1"):
                g1 = llm.generate(p1, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            llm.current_scheduler().drain()
            assert gw.stats()["wf_parked_sessions"] == 1
            rid = gw.router.session_replica(conv.id)
            victim = fleet.get(rid)
            assert victim.engine.stats().kv_parked_chains == 1
            released0 = _parked_released("shutdown")
            victim.engine.close()         # mid-gap death
            gw.tick()                     # health check reaps it...
            # ...dropping the lease AND the engine-side pins
            assert gw.stats()["wf_parked_sessions"] == 0
            assert _parked_released("shutdown") == released0 + 1
            assert rid not in [r.id for r in fleet.replicas()]
            p2 = list(g1.full_tokens()) + [41]
            with lzy.workflow("step2"):
                g2 = llm.generate(p2, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            assert g2.status == "ok"
            assert g2.tokens == _oracle_tokens(cfg, params, p2, 5)
            assert g2.replica != rid
            assert g2.routed_by != "fused"
        finally:
            gw.close()

    def test_replica_death_without_health_tick_still_serves(
            self, tiny_model):
        """No tick between the death and step 2: the stale lease points
        at a corpse. The routed loop consumes the pin, finds the
        replica unroutable, and degrades to ordinary routing — one
        re-prefill, never a wrong token."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://wfsched-kill-lazy")
            conv = llm.Conversation("killed-gap-lazy")
            p1 = TestFusedChain.P1
            with lzy.workflow("step1"):
                g1 = llm.generate(p1, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            llm.current_scheduler().drain()
            rid = gw.router.session_replica(conv.id)
            fleet.get(rid).engine.close()
            p2 = list(g1.full_tokens()) + [41]
            with lzy.workflow("step2"):
                g2 = llm.generate(p2, max_new_tokens=5, greedy=True,
                                  conversation=conv)
            assert g2.status == "ok"
            assert g2.tokens == _oracle_tokens(cfg, params, p2, 5)
            assert g2.replica != rid
            assert g2.routed_by != "fused"
        finally:
            gw.close()


# -- engine-side park lifecycle (TTL, pressure) -------------------------------

class _OffsetClock:
    """System clock plus a test-advanced offset — park TTLs observe the
    jump without the test sleeping through them."""

    def __init__(self):
        self.offset = 0.0

    def now(self):
        return SYSTEM_CLOCK.now() + self.offset

    def time(self):
        return SYSTEM_CLOCK.time() + self.offset

    def sleep(self, seconds):
        SYSTEM_CLOCK.sleep(seconds)

    def wait(self, event, timeout=None):
        return SYSTEM_CLOCK.wait(event, timeout)

    def event(self):
        return SYSTEM_CLOCK.event()


def _run_to_done(eng, req, rounds=200):
    for _ in range(rounds):
        if req.done:
            return
        eng.step()
    raise AssertionError(f"request {req.id} never finished")


class TestEnginePark:
    def test_park_ttl_expiry_sweeps_the_chain(self, tiny_model):
        cfg, params = tiny_model
        clk = _OffsetClock()
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   clock=clk)
        prompt = list(range(1, 13))
        a = eng.submit(prompt, max_new_tokens=2, greedy=True)
        _run_to_done(eng, a)
        assert eng.park_chain("conv:ttl", prompt, ttl_s=5.0)
        s = eng.stats()
        assert s.kv_parked_chains == 1
        assert s.kv_parked_blocks == 1       # one whole 8-token page
        # re-park refreshes the one pin, never duplicates it
        assert eng.park_chain("conv:ttl", prompt, ttl_s=5.0)
        assert eng.stats().kv_parked_chains == 1
        released0 = _parked_released("ttl")
        clk.offset += 10.0                   # the tool gap overran
        eng.step()                           # next round sweeps
        assert eng.stats().kv_parked_chains == 0
        assert _parked_released("ttl") == released0 + 1
        assert not eng.unpark_chain("conv:ttl")   # double-release: no-op

    def test_park_declines_when_nothing_is_cached(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        assert not eng.park_chain("conv:none", [60] * 12, ttl_s=5.0)
        assert eng.stats().kv_parked_chains == 0

    def test_pressure_sheds_parked_before_any_resident_request(
            self, tiny_model):
        """KV pressure: a parked tool-gap chain is strictly cheaper to
        lose than resident work — the admission gate sheds it (reason
        'pressure') and BOTH live requests finish ok, bit-identical,
        with nobody preempted."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                   kv_blocks=12)
        pa = list(range(1, 13))              # 12 tokens, 2 blocks
        a = eng.submit(pa, max_new_tokens=2, greedy=True)
        _run_to_done(eng, a)
        assert eng.park_chain("conv:gap", pa, ttl_s=300.0)
        # b occupies 6 of the 12 blocks and stays resident (41 + 7
        # tokens fit its 6 pages exactly — no decode growth)
        pb = [(i * 7) % 60 + 1 for i in range(41)]
        b = eng.submit(pb, max_new_tokens=7, greedy=True)
        for _ in range(200):
            if b.tokens:
                break
            eng.step()
        assert b.tokens and not b.done
        # c needs 6 blocks; free pool is 5 with the pin held — the gate
        # must shed the parked chain, not queue c behind the tool gap
        released0 = _parked_released("pressure")
        pc = [(i * 11) % 60 + 1 for i in range(47)]
        c = eng.submit(pc, max_new_tokens=1, greedy=True)
        _run_to_done(eng, b)
        _run_to_done(eng, c)
        assert _parked_released("pressure") == released0 + 1
        assert eng.stats().kv_parked_chains == 0
        # the residents never paid for it: no preemption, exact output
        assert b.error is None and c.error is None
        assert b.result(0) == _oracle_tokens(cfg, params, pb, 7)
        assert c.result(0) == _oracle_tokens(cfg, params, pc, 1)
