"""Workflow-native LLM inference (``lzy_tpu/llm`` + token streams).

The acceptance properties this file pins:

- a multi-step workflow (``generate → plain op → generate``) through the
  gateway is greedy **bit-identical** to the monolithic ``generate()``
  oracle;
- a cached ``llm_op`` re-execution **skips the fleet entirely**;
- a ``TokenStreamChannel`` resumes **byte-identically** across an
  injected mid-stream replica death (the fence IS the stream position);
- conversation-affinity routing measurably **beats round-robin** on
  aggregate radix prefix hit rate;
- generations round-trip the whiteboard index as versioned fields.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu import Lzy, llm, op
from lzy_tpu.channels.token_stream import (
    STREAMS, StorageTokenStreamReader, StorageTokenStreamWriter,
    StreamFailed, StreamSpliceError, TokenStreamChannel)
from lzy_tpu.gateway import (
    GatewayService, PrefixAffinityRouter, ReplicaFleet, RoundRobinRouter)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate as oracle_generate
from lzy_tpu.serving import InferenceEngine, PagedInferenceEngine
from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig
from lzy_tpu.storage.registry import client_for

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


@pytest.fixture(autouse=True)
def _clean_backend():
    yield
    llm.configure(None)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = oracle_generate(cfg, params,
                          jnp.asarray([prompt_ids], jnp.int32),
                          max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _make_gateway(cfg, params, *, replicas=2, slots=2, paged=True,
                  router=None, **engine_kw):
    def factory():
        if paged:
            return PagedInferenceEngine(cfg, params, slots=slots,
                                        page_size=PAGE, **engine_kw)
        return InferenceEngine(cfg, params, slots=slots, **engine_kw)

    fleet = ReplicaFleet(factory)
    gw = GatewayService(fleet,
                        router=router or PrefixAffinityRouter(PAGE),
                        model_name="tiny")
    for _ in range(replicas):
        fleet.add_replica()
    return gw, fleet


def _local_lzy(uri: str) -> Lzy:
    reg = DefaultStorageRegistry()
    reg.register_storage("default", StorageConfig(uri=uri), default=True)
    return Lzy(storage_registry=reg)


# -- token stream channel -----------------------------------------------------

class TestTokenStreamChannel:
    def test_positioned_publish_dedupes_and_appends(self):
        ch = TokenStreamChannel()
        ch.publish(0, [1, 2, 3])
        ch.publish(0, [1, 2, 3, 4])       # overlap verified, 4 appended
        ch.publish(4, [5])
        assert ch.tokens() == [1, 2, 3, 4, 5]
        ch.publish(2, [3, 4, 5])          # full duplicate: no-op
        assert ch.tokens() == [1, 2, 3, 4, 5]

    def test_gap_and_divergence_raise(self):
        ch = TokenStreamChannel()
        ch.publish(0, [1, 2])
        with pytest.raises(StreamSpliceError):
            ch.publish(3, [9])            # gap
        with pytest.raises(StreamSpliceError):
            ch.publish(0, [1, 9])         # fence violation
        assert ch.tokens() == [1, 2]      # stream unharmed

    def test_iteration_sees_every_token_once_then_terminates(self):
        ch = TokenStreamChannel()
        got = []

        def consume():
            for tok in ch:
                got.append(tok)

        t = threading.Thread(target=consume)
        t.start()
        for i in range(5):
            ch.publish(i, [i * 10])
        ch.close("ok")
        t.join(10)
        assert got == [0, 10, 20, 30, 40]
        assert ch.status == "ok"

    def test_failed_stream_raises_for_consumers(self):
        ch = TokenStreamChannel()
        ch.publish(0, [1])
        ch.fail("replica on fire")
        with pytest.raises(StreamFailed):
            list(iter(ch))
        with pytest.raises(StreamFailed):
            ch.read(1, timeout_s=1)

    def test_read_returns_suffix_and_respects_close(self):
        ch = TokenStreamChannel()
        ch.publish(0, [1, 2, 3])
        assert ch.read(1) == [2, 3]
        ch.close("ok")
        assert ch.read(3) == []           # closed, nothing past 3

    def test_registry_rendezvous(self):
        ch = STREAMS.get_or_create("t-reg-1")
        assert STREAMS.get_or_create("t-reg-1") is ch
        assert STREAMS.get("t-reg-1") is ch
        STREAMS.release("t-reg-1")
        assert STREAMS.get("t-reg-1") is None

    def test_storage_spill_round_trip(self):
        client = client_for(StorageConfig(uri="mem://tokspill"))
        w = StorageTokenStreamWriter(client, "mem://tokspill/s1",
                                     chunk_tokens=4)
        w.append([1, 2, 3, 4, 5])         # one full chunk + tail
        w.append([6])
        w.finish("ok")
        r = StorageTokenStreamReader(client, "mem://tokspill/s1")
        doc = r.read_all(timeout_s=5)
        assert doc["tokens"] == [1, 2, 3, 4, 5, 6]
        assert doc["status"] == "ok"
        assert list(StorageTokenStreamReader(
            client, "mem://tokspill/s1").iter_tokens(timeout_s=5)) == \
            [1, 2, 3, 4, 5, 6]

    def test_storage_spill_failure_surfaces(self):
        client = client_for(StorageConfig(uri="mem://tokspill"))
        w = StorageTokenStreamWriter(client, "mem://tokspill/s2")
        w.append([7])
        w.finish("error", error="boom")
        with pytest.raises(StreamFailed):
            StorageTokenStreamReader(
                client, "mem://tokspill/s2").read_all(timeout_s=5)

    def test_stalled_spill_mirror_commits_error_not_truncated_ok(self):
        """If the spill mirror thread outlives the join budget, the
        manifest must record an error — never an 'ok' with fewer tokens
        than the stream carried (a reader would trust the truncation)."""
        from lzy_tpu.llm.op import _finish_spill

        client = client_for(StorageConfig(uri="mem://tokspill"))
        ch = TokenStreamChannel()
        ch.publish(0, [1, 2, 3])
        ch.close("ok")
        w = StorageTokenStreamWriter(client, "mem://tokspill/s3")
        w.append([1])                      # mirror fell behind

        class StalledThread:
            def join(self, timeout=None):
                pass

            def is_alive(self):
                return True

        _finish_spill(ch, w, StalledThread())
        with pytest.raises(StreamFailed, match="stalled"):
            StorageTokenStreamReader(
                client, "mem://tokspill/s3").read_all(timeout_s=5)


# -- direct (workflow-less) surface ------------------------------------------

class TestDirectGenerate:
    def test_direct_call_hits_engine_and_streams(self, tiny_model):
        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2).start()
        try:
            llm.configure(llm.EngineBackend(engine, model_name="tiny"))
            ch = TokenStreamChannel()
            g = llm.generate([7, 2, 8, 1], max_new_tokens=6,
                             greedy=True, stream=ch)
            assert isinstance(g, llm.Generation)
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 6)
            assert g.tokens == oracle
            assert ch.tokens() == oracle and ch.status == "ok"
            assert g.full_tokens() == [7, 2, 8, 1] + oracle
        finally:
            engine.close()

    def test_batch_fans_out_one_node(self, tiny_model):
        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2).start()
        try:
            llm.configure(llm.EngineBackend(engine, model_name="tiny"))
            prompts = [[5, 9, 3], [7, 2, 8, 1]]
            out = llm.generate_batch(prompts, max_new_tokens=4,
                                     greedy=True)
            assert [g.tokens for g in out] == [
                _oracle_tokens(cfg, params, p, 4) for p in prompts]
        finally:
            engine.close()


class TestServiceBackendDegradation:
    def test_session_survives_a_surface_without_stream_or_token(self):
        """RpcInferenceClient's shape: takes session, not stream/token.
        The backend must deliver the session hint instead of letting a
        None-valued extension force the degraded (hint-dropping) path —
        this is what makes conversation affinity work over the wire."""
        calls = {}

        class RpcLike:
            def generate(self, prompt, *, max_new_tokens=64,
                         timeout_s=None, deadline_s=None, greedy=None,
                         tenant=None, priority=None, session=None):
                calls["session"] = session
                return {"tokens": [1], "status": "ok"}

        b = llm.ServiceBackend(RpcLike(), digest="d")
        reply = b.generate([1, 2], max_new_tokens=2, timeout_s=5,
                           deadline_s=None, greedy=True, tenant=None,
                           priority=None, session="conv-1", stream=None)
        assert reply["status"] == "ok"
        assert calls["session"] == "conv-1"

    def test_legacy_surface_gets_terminal_stream_flush(self):
        """A pre-session surface: extensions strip one at a time and an
        attached stream still terminates with the full token sequence."""

        class Legacy:
            def generate(self, prompt, *, max_new_tokens=64,
                         timeout_s=None, deadline_s=None, greedy=None,
                         tenant=None, priority=None):
                return {"tokens": [4, 5], "status": "ok"}

        ch = TokenStreamChannel()
        b = llm.ServiceBackend(Legacy(), digest="d")
        reply = b.generate([1], max_new_tokens=2, session="s", stream=ch)
        assert reply["tokens"] == [4, 5]
        assert ch.tokens() == [4, 5] and ch.status == "ok"


# -- workflow pipeline vs the oracle -----------------------------------------

@op
def tool_extend(g: llm.Generation, extra: list) -> list:
    """The 'tool' step of an agent pipeline: fold the generation back
    into the next prompt."""
    return g.full_tokens() + list(extra)


class TestWorkflowPipeline:
    def test_three_step_conversation_bit_identical_and_pinned(
            self, tiny_model):
        """generate → tool op → generate → tool op → generate through a
        2-replica gateway: every step bit-identical to the monolithic
        oracle, and the conversation pinned to ONE replica by session
        affinity."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-e2e-pipeline")
            conv = llm.Conversation("pipeline-conv")
            with lzy.workflow("agent") as wf:
                p1 = list(range(16)) + [3]
                g1 = llm.generate(p1, max_new_tokens=5, greedy=True,
                                  conversation=conv)
                p2 = tool_extend(g1, [41, 42])
                g2 = llm.generate(p2, max_new_tokens=5, greedy=True,
                                  conversation=conv)
                p3 = tool_extend(g2, [43])
                g3 = llm.generate(p3, max_new_tokens=5, greedy=True,
                                  conversation=conv)
                wb = llm.record_generation(wf, g3, conversation=conv)
            # (a) bit-identity vs the monolithic oracle at every step
            e1 = _oracle_tokens(cfg, params, p1, 5)
            full2 = p1 + e1 + [41, 42]
            e2 = _oracle_tokens(cfg, params, full2, 5)
            full3 = full2 + e2 + [43]
            e3 = _oracle_tokens(cfg, params, full3, 5)
            assert g1.tokens == e1
            assert g2.tokens == e2 and g2.prompt == full2
            assert g3.tokens == e3 and g3.prompt == full3
            # (b) the fused op chain kept the conversation on one
            # replica: after each step the workflow scheduler parks the
            # conversation's KV there, so steps 2 and 3 HARD-pin to the
            # leased replica (routed_by "fused" supersedes the session
            # hint; a lapsed lease falls back to "session")
            assert g1.replica == g2.replica == g3.replica
            assert g2.routed_by in ("fused", "session")
            assert g3.routed_by in ("fused", "session")
            router = gw.router.stats()
            # step 1 has no pin yet; steps 2+3 route pinned either way
            assert router["session_routed"] + \
                router.get("fused_routed", 0) == 2
            # (c) the recorded generation round-trips the index
            found = lzy.whiteboards(name=llm.GENERATION_WB_NAME,
                                    tags=[f"conversation:{conv.id}"])
            assert [w.id for w in found] == [wb.id]
            assert found[0].tokens == e3
            assert found[0].prompt == full3
            assert found[0].model_digest == g3.model_digest
            assert found[0].provenance["replica"] == g3.replica
            assert found[0].provenance["step"] == 3
        finally:
            gw.close()

    def _drive_conversations(self, cfg, params, router):
        """The affinity-vs-round-robin workload: THREE interleaved
        3-step conversations through a 2-replica paged gateway, via the
        workflow surface (3 on 2 so round-robin cannot accidentally
        alias into perfect affinity). Returns the fleet-aggregate radix
        hit rate."""
        gw, fleet = _make_gateway(cfg, params, replicas=2, router=router)
        try:
            llm.configure(gw)
            lzy = _local_lzy(f"mem://llm-aff-{type(router).__name__}")
            convs = [llm.Conversation(f"aff-{i}") for i in range(3)]
            bases = [list(range(16)), list(range(30, 46)),
                     list(range(8, 24))]
            with lzy.workflow("chat") as wf:
                prompts = list(bases)
                for _ in range(3):
                    for i, conv in enumerate(convs):
                        g = llm.generate(prompts[i], max_new_tokens=4,
                                         greedy=True, conversation=conv)
                        prompts[i] = tool_extend(g, [60 + i])
                    wf.barrier()
            agg = fleet.aggregate()
            assert agg["prefix_lookup_tokens"] > 0
            return agg["prefix_hit_tokens"] / agg["prefix_lookup_tokens"]
        finally:
            gw.close()

    def test_conversation_affinity_beats_round_robin(self, tiny_model):
        cfg, params = tiny_model
        affinity = self._drive_conversations(cfg, params,
                                             PrefixAffinityRouter(PAGE))
        rr = self._drive_conversations(cfg, params, RoundRobinRouter())
        assert affinity > rr, (
            f"conversation affinity must raise the aggregate radix hit "
            f"rate over round-robin (affinity {affinity:.3f} vs rr "
            f"{rr:.3f})")


# -- caching ------------------------------------------------------------------

class TestLlmOpCaching:
    def test_cached_rerun_skips_the_fleet(self, tiny_model):
        """Same prompt/params/digest on a second workflow run: the op
        cache satisfies the call and the gateway never sees a request."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-cache")
            from lzy_tpu.llm.metrics import CACHED_HITS

            hits0 = sum(CACHED_HITS._values.values())
            with lzy.workflow("cached"):
                g = llm.generate([5, 9, 3, 1, 2, 6, 7, 4],
                                 max_new_tokens=4, greedy=True)
            first = list(g.tokens)
            served = gw.stats()["requests_finished"]
            assert served == 1
            with lzy.workflow("cached"):
                g2 = llm.generate([5, 9, 3, 1, 2, 6, 7, 4],
                                  max_new_tokens=4, greedy=True)
            assert list(g2.tokens) == first
            assert gw.stats()["requests_finished"] == 1   # fleet skipped
            assert sum(CACHED_HITS._values.values()) == hits0 + 1
        finally:
            gw.close()

    def test_sampled_requests_opt_out_of_the_cache(self, tiny_model):
        """Sampling is a draw, not a function of the inputs: by default
        a non-greedy llm_op re-executes (the fleet is hit again)."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=1,
                                  temperature=0.8, seed=3)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-cache-sampled")
            for _ in range(2):
                with lzy.workflow("sampled"):
                    llm.generate([5, 9, 3, 1, 2, 6, 7, 4],
                                 max_new_tokens=3)
            assert gw.stats()["requests_finished"] == 2
        finally:
            gw.close()

    def test_cancelled_generation_never_poisons_the_cache(self):
        """A deadline-truncated reply (status 'cancelled', partial
        tokens) must NOT be cached: the deadline is excluded from the
        cache key, so a poisoned entry would serve the truncation
        forever — even after the caller raises the deadline."""
        calls = {"n": 0}

        class CancelThenOk:
            def generate(self, prompt, *, max_new_tokens=64,
                         timeout_s=None, deadline_s=None, greedy=None,
                         tenant=None, priority=None, session=None):
                calls["n"] += 1
                if calls["n"] == 1:
                    return {"tokens": [1], "status": "cancelled"}
                return {"tokens": [1, 2, 3, 4], "status": "ok"}

        llm.configure(llm.ServiceBackend(CancelThenOk(), digest="d"))
        lzy = _local_lzy("mem://llm-cache-cancelled")
        with lzy.workflow("doomed"):
            g1 = llm.generate([3, 1, 4], max_new_tokens=4, greedy=True,
                              deadline_s=0.001)
        assert g1.status == "cancelled" and list(g1.tokens) == [1]
        # same key (deadline_s lives in runtime_opts, excluded) — the
        # cancelled result must MISS and the plane must be hit again
        with lzy.workflow("doomed"):
            g2 = llm.generate([3, 1, 4], max_new_tokens=4, greedy=True,
                              deadline_s=60.0)
        assert calls["n"] == 2
        assert g2.status == "ok" and list(g2.tokens) == [1, 2, 3, 4]
        # and a COMPLETE result still caches: third run skips the plane
        with lzy.workflow("doomed"):
            g3 = llm.generate([3, 1, 4], max_new_tokens=4, greedy=True,
                              deadline_s=60.0)
        assert calls["n"] == 2
        assert g3.status == "ok" and list(g3.tokens) == [1, 2, 3, 4]

    def test_model_digest_keys_the_cache(self, tiny_model):
        """A different served model (digest) must MISS a cache entry
        keyed under the old digest — the digest is an op input."""
        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=1)
        try:
            llm.configure(llm.ServiceBackend(gw, digest="model-A"))
            lzy = _local_lzy("mem://llm-cache-digest")
            with lzy.workflow("dig"):
                llm.generate([9, 8, 7, 6, 5, 4, 3, 2], max_new_tokens=3,
                             greedy=True)
            assert gw.stats()["requests_finished"] == 1
            llm.configure(llm.ServiceBackend(gw, digest="model-B"))
            with lzy.workflow("dig"):
                llm.generate([9, 8, 7, 6, 5, 4, 3, 2], max_new_tokens=3,
                             greedy=True)
            assert gw.stats()["requests_finished"] == 2
        finally:
            gw.close()


# -- streaming through the fleet, including mid-stream death ------------------

class TestStreamedGeneration:
    def test_workflow_stream_delivers_incrementally(self, tiny_model):
        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-stream")
            ch = TokenStreamChannel()
            got = []
            consumer = threading.Thread(
                target=lambda: got.extend(iter(ch)))
            consumer.start()
            with lzy.workflow("streamed"):
                g = llm.generate([7, 2, 8, 1], max_new_tokens=8,
                                 greedy=True, stream=ch)
                tokens = list(g.tokens)
            consumer.join(30)
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 8)
            assert tokens == oracle and got == oracle
            assert ch.status == "ok" and ch.resumptions == 0
            # a caller-owned channel is dropped from the rendezvous
            # registry once terminal (the caller holds the object; a
            # long-lived worker must not retain every finished stream)
            assert STREAMS.get(ch.id) is None
        finally:
            gw.close()

    def test_mid_stream_replica_kill_resumes_byte_identically(
            self, tiny_model):
        """Kill the serving replica mid-stream: the gateway fences the
        emitted tokens, the retry resumes the CHANNEL at the fence, and
        the consumer-visible sequence is byte-identical to an
        uninterrupted run (resumptions == 1 is the only trace)."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=3)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-stream-kill")
            ch = TokenStreamChannel()
            result = {}

            def run():
                try:
                    with lzy.workflow("streamed-kill"):
                        g = llm.generate([7, 2, 8, 1],
                                         max_new_tokens=24, greedy=True,
                                         stream=ch, timeout_s=120)
                        result["tokens"] = list(g.tokens)
                        result["failovers"] = g.failovers
                except BaseException as e:  # noqa: BLE001 — main thread
                    result["err"] = e

            t = threading.Thread(target=run)
            t.start()
            victim = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                for replica in fleet.replicas():
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim = replica
                        break
                if victim:
                    break
                time.sleep(0.005)
            assert victim is not None, "request never reached mid-decode"

            def boom():
                raise RuntimeError("replica host on fire")

            victim.engine.step = boom
            t.join(120)
            assert "err" not in result, result.get("err")
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 24)
            assert result["tokens"] == oracle
            assert result["failovers"] == 1
            # the stream: byte-identical, resumed exactly once
            assert ch.tokens() == oracle
            assert ch.resumptions == 1
            assert ch.status == "ok"
        finally:
            gw.close()


# -- chaos: llm.dispatch fault point ------------------------------------------

@pytest.mark.chaos
class TestLlmDispatchChaos:
    def test_fixed_seed_dispatch_fault_is_survived(self, tiny_model):
        """Fixed-seed plan armed at llm.dispatch (rate 1.0, one fault):
        the first dispatch raises the typed error, the backoff retry
        completes the generation, output stays oracle-identical."""
        from lzy_tpu.chaos.faults import CHAOS, ERROR, FaultPlan
        from lzy_tpu.llm.metrics import DISPATCH_RETRIES

        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-chaos")
            retries0 = sum(DISPATCH_RETRIES._values.values())
            CHAOS.arm(FaultPlan(11, rate=1.0, modes=(ERROR,),
                                points=("llm.dispatch",), max_faults=1))
            try:
                with lzy.workflow("chaotic"):
                    g = llm.generate([7, 2, 8, 1], max_new_tokens=5,
                                     greedy=True)
                    tokens = list(g.tokens)
            finally:
                plan = CHAOS.disarm()
            assert plan.fired == 1, plan.describe()
            assert tokens == _oracle_tokens(cfg, params, [7, 2, 8, 1], 5)
            assert sum(DISPATCH_RETRIES._values.values()) == retries0 + 1
        finally:
            gw.close()

    def test_fixed_seed_mid_stream_crash_resumes_fenced(self, tiny_model):
        """The satellite chaos test: a seeded CRASH at ``engine.step``
        (seed 2 fires at that point's 8th working round — mid-stream for
        a 24-token generation) kills the serving replica's loop under a
        workflow-driven streamed generation. The gateway fences the
        emitted tokens, the retry replica re-attaches the channel at the
        fence, and the consumer-visible stream is byte-identical to an
        uninterrupted run — replayable from the printed seed."""
        from lzy_tpu.chaos.faults import CHAOS, CRASH, FaultPlan

        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=2)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-chaos-kill")
            ch = TokenStreamChannel()
            CHAOS.arm(FaultPlan(2, rate=0.15, modes=(CRASH,),
                                points=("engine.step",), max_faults=1))
            try:
                with lzy.workflow("chaotic-stream"):
                    g = llm.generate([7, 2, 8, 1], max_new_tokens=24,
                                     greedy=True, stream=ch,
                                     timeout_s=120)
                    tokens = list(g.tokens)
                    failovers = g.failovers
            finally:
                plan = CHAOS.disarm()
            assert plan.fired == 1, plan.describe()
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 24)
            assert tokens == oracle, plan.describe()
            assert failovers == 1, plan.describe()
            # the stream: byte-identical, resumed exactly once at the
            # fence — the crash's only consumer-visible trace
            assert ch.tokens() == oracle
            assert ch.resumptions == 1
            assert ch.status == "ok"
        finally:
            gw.close()

    def test_exhausted_retries_surface_the_typed_error(self, tiny_model):
        """Every attempt faulted: the op fails with the dispatch error
        (workflow-level retries/caching own what happens next) — and
        with no stream attached nothing hangs."""
        from lzy_tpu.chaos.faults import CHAOS, ERROR, FaultPlan
        from lzy_tpu.core.workflow import RemoteCallError

        cfg, params = tiny_model
        gw, _ = _make_gateway(cfg, params, replicas=1)
        try:
            llm.configure(gw)
            lzy = _local_lzy("mem://llm-chaos-exhaust")
            CHAOS.arm(FaultPlan(11, rate=1.0, modes=(ERROR,),
                                points=("llm.dispatch",)))
            try:
                with pytest.raises(RemoteCallError):
                    with lzy.workflow("doomed"):
                        llm.generate([7, 2, 8, 1], max_new_tokens=3,
                                     greedy=True)
            finally:
                CHAOS.disarm()
        finally:
            gw.close()


# -- KV provenance through the radix tree -------------------------------------

class TestKvProvenance:
    def test_chain_origin_follows_imported_blocks(self, tiny_model):
        """import_kv tags radix nodes with the producing prefill
        replica; a request matching them records it (the disagg reply's
        `prefilled_by` used-semantics), while locally-prefilled chains
        stay origin-free."""
        from lzy_tpu.serving.disagg.kv_export import export_kv, import_kv

        cfg, params = tiny_model
        src = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        dst = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        prompt = list(range(16)) + [3]
        req = src.submit(prompt, max_new_tokens=2)
        while not req.done:
            src.step()
        export = export_kv(src, prompt[:16])
        export.prefilled_by = "prefill-7"
        assert import_kv(dst, export) == 2
        assert dst.kv.chain_origin(prompt[:16]) == "prefill-7"
        # a request through the engine records the used origin
        req2 = dst.submit(prompt, max_new_tokens=2)
        while not req2.done:
            dst.step()
        assert req2.kv_prefilled_by == "prefill-7"
        # locally-prefilled chains carry no origin
        assert src.kv.chain_origin(prompt[:16]) is None
        req3 = src.submit(prompt, max_new_tokens=2)
        while not req3.done:
            src.step()
        assert req3.kv_prefilled_by is None


# -- e2e: InProcessCluster + gateway fleet ------------------------------------

class TestClusterEndToEnd:
    def test_cluster_workflow_against_two_replica_gateway(self):
        """The satellite e2e: a 3-step conversation workflow through an
        InProcessCluster whose serving plane is a 2-replica gateway —
        greedy output bit-identical to the oracle, the conversation
        pinned to one replica, whiteboard fields round-tripping through
        the index."""
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.inference import (
            _build_engine_parts, build_gateway_service)

        cluster = InProcessCluster(
            storage_uri="mem://llm-cluster",
            inference_factory=lambda c: build_gateway_service(
                "tiny", replicas=2, slots=2, paged=True, page_size=PAGE,
                allocator=c.allocator, autoscale=False))
        gw = cluster.inference_service
        try:
            llm.configure(gw)
            cfg, params = _build_engine_parts("tiny", checkpoint=None,
                                              seed=0)
            lzy = cluster.lzy()
            conv = llm.Conversation("cluster-conv")
            with lzy.workflow("cluster-agent") as wf:
                p1 = list(range(16)) + [3]
                g1 = llm.generate(p1, max_new_tokens=4, greedy=True,
                                  conversation=conv)
                p2 = tool_extend(g1, [41])
                g2 = llm.generate(p2, max_new_tokens=4, greedy=True,
                                  conversation=conv)
                p3 = tool_extend(g2, [42])
                g3 = llm.generate(p3, max_new_tokens=4, greedy=True,
                                  conversation=conv)
                wb = llm.record_generation(wf, g3, conversation=conv)
                steps = [(list(g.prompt), list(g.tokens), g.replica,
                          g.routed_by) for g in (g1, g2, g3)]
            # (a) greedy bit-identity vs the generate() oracle
            running = list(range(16)) + [3]
            for i, (prompt, tokens, _, _) in enumerate(steps):
                assert prompt == running, f"step {i + 1} prompt"
                expected = _oracle_tokens(cfg, params, running, 4)
                assert tokens == expected, f"step {i + 1} tokens"
                running = running + expected + [41 + i]
            # (b) affinity kept the conversation on one replica
            replicas = {r for _, _, r, _ in steps}
            assert len(replicas) == 1
            # fused (parked-KV hard pin) when the workflow scheduler's
            # lease held across the tool gap; session otherwise
            assert all(why in ("fused", "session")
                       for why in [w for _, _, _, w in steps][1:])
            # (c) whiteboard round-trip through the cluster's index
            found = lzy.whiteboards(name=llm.GENERATION_WB_NAME,
                                    tags=[f"conversation:{conv.id}"])
            assert [w.id for w in found] == [wb.id]
            assert found[0].tokens == steps[2][1]
            assert found[0].provenance["routed_by"] in ("fused",
                                                        "session")
            # the tenant rode the workflow auth context into the fleet
            tenants = gw.stats()["tenants"]
            assert "test-user" in tenants
            assert tenants["test-user"]["requests_finished"] == 3
        finally:
            cluster.shutdown()
