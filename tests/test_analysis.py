"""lzy-lint: the tier-1 ratchet + the synthetic violation corpus.

Three layers:

- **corpus**: every violation class is proven CAUGHT on its known-bad
  snippet and SILENT on the paired known-good snippet
  (``tests/analysis_corpus/`` — parsed, never imported);
- **ratchet**: the four passes run over the live ``lzy_tpu`` tree and
  any violation whose fingerprint is not in the checked-in baseline
  (``lzy_tpu/analysis/baseline.json`` — which ships EMPTY) fails
  tier-1.  This is the test that makes the PR 5/6/12 bug classes
  unshippable;
- **budget**: the full-tree run must stay under 10 s of wall clock so
  the ratchet never becomes the test people skip.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from lzy_tpu.analysis import core, load_baseline, load_tree, run_passes

pytestmark = pytest.mark.analysis

CORPUS = Path(__file__).parent / "analysis_corpus"
LIVE_ROOT = Path(__file__).resolve().parents[1] / "lzy_tpu"


@pytest.fixture(scope="module")
def corpus_result():
    index = load_tree(CORPUS, rel_to=CORPUS)
    return run_passes(index)


@pytest.fixture(scope="module")
def live_result():
    import time as _time

    t0 = _time.perf_counter()
    index = load_tree(LIVE_ROOT)
    result = run_passes(index)
    elapsed = _time.perf_counter() - t0
    return index, result, elapsed


def _rules_in(result, path: str):
    return {v.rule for v in result.violations if v.path == path}


# -- corpus: each class caught on bad, silent on good -------------------------

CLASS_PAIRS = [
    ("lock-order-inversion",
     "bad_lock_inversion.py", "good_lock_order.py"),
    ("lock-self-reacquire",
     "bad_self_reacquire.py", "good_self_reacquire.py"),
    ("lock-blocking-call",
     "bad_blocking_under_lock.py", "good_blocking_outside_lock.py"),
    ("lock-blocking-call",
     "bad_journal_under_lock.py", "good_journal_outside_lock.py"),
    ("lock-blocking-call",
     "bad_parked_release_under_lock.py",
     "good_parked_release_outside_lock.py"),
    ("jax-donation-alias",
     "bad_donation_alias.py", "good_donation_copy.py"),
    ("jax-traced-python-if",
     "bad_traced_if.py", "good_traced_if.py"),
    ("jax-host-sync-hot-loop",
     "lzy_tpu/serving/bad_host_sync.py",
     "lzy_tpu/serving/good_host_sync.py"),
    ("jax-host-sync-hot-loop",
     "lzy_tpu/serving/bad_shard_host_sync.py",
     "lzy_tpu/serving/good_shard_host_sync.py"),
    ("jax-reupload-hot-loop",
     "lzy_tpu/serving/bad_reupload_hot_loop.py",
     "lzy_tpu/serving/good_reupload_once.py"),
    ("clock-raw-time",
     "bad_raw_clock.py", "good_injected_clock.py"),
    ("chaos-uncaught-error",
     "bad_uncaught_fault.py", "good_caught_fault.py"),
]


class TestCorpus:
    @pytest.mark.parametrize("rule,bad,good", CLASS_PAIRS,
                             ids=[p[0] for p in CLASS_PAIRS])
    def test_bad_caught_good_silent(self, corpus_result, rule, bad,
                                    good):
        assert rule in _rules_in(corpus_result, bad), \
            f"{rule} missed its known-bad snippet {bad}"
        assert not _rules_in(corpus_result, good), \
            f"false positive(s) on {good}: " \
            f"{[v.render() for v in corpus_result.violations if v.path == good]}"

    def test_chaos_contract_side_rules(self, corpus_result):
        rules = _rules_in(corpus_result, "bad_uncaught_fault.py")
        assert "chaos-unregistered-hit" in rules      # corpus.typo
        assert "chaos-unhit-point" in rules           # corpus.dead
        assert "chaos-crash-unhandled" in rules       # corpus.crashy

    def test_blocking_flags_every_category(self, corpus_result):
        msgs = [v.message for v in corpus_result.violations
                if v.path == "bad_blocking_under_lock.py"
                and v.rule == "lock-blocking-call"]
        joined = " | ".join(msgs)
        assert "sleep" in joined
        assert "storage I/O" in joined
        assert "wait" in joined

    def test_donation_flags_both_shapes(self, corpus_result):
        msgs = [v.message for v in corpus_result.violations
                if v.path == "bad_donation_alias.py"]
        assert any("asarray" in m for m in msgs)          # taint shape
        assert any("same expression" in m for m in msgs)  # dup-arg shape

    def test_raw_clock_catches_from_import_too(self, corpus_result):
        lines = [v.line for v in corpus_result.violations
                 if v.path == "bad_raw_clock.py"]
        assert len(lines) >= 4           # import-from + 3+ call sites


class TestSuppressions:
    def test_justified_suppression_silences(self, corpus_result):
        assert not _rules_in(corpus_result, "good_suppression.py")
        suppressed = [v for v in corpus_result.suppressed
                      if v.path == "good_suppression.py"]
        assert suppressed, "the justified disable should still be " \
                           "visible in the suppressed list"

    def test_bare_suppression_is_its_own_violation(self, corpus_result):
        rules = _rules_in(corpus_result, "bad_bare_suppression.py")
        assert "lint-bare-suppression" in rules
        # and it does NOT silence the underlying finding
        assert "clock-raw-time" in rules

    def test_unknown_rule_flagged(self, tmp_path):
        (tmp_path / "x.py").write_text(
            "import time\n"
            "t = time.time()  "
            "# lzy-lint: disable=no-such-rule -- why not\n")
        result = run_passes(load_tree(tmp_path, rel_to=tmp_path))
        rules = {v.rule for v in result.violations}
        assert "lint-unknown-rule" in rules
        assert "clock-raw-time" in rules   # unknown rule silences nothing

    def test_suppression_covers_next_line(self, tmp_path):
        (tmp_path / "x.py").write_text(
            "import time\n"
            "# lzy-lint: disable=clock-raw-time -- fixture justification\n"
            "t = time.time()\n")
        result = run_passes(load_tree(tmp_path, rel_to=tmp_path))
        assert not result.violations
        assert len(result.suppressed) == 1


# -- the ratchet --------------------------------------------------------------

class TestRatchet:
    def test_live_tree_holds_the_baseline(self, live_result):
        _index, result, _elapsed = live_result
        baseline = load_baseline()
        new = baseline.new_violations(result)
        assert not new, (
            "lzy-lint found violation(s) not in the baseline — fix them "
            "or add a justified `# lzy-lint: disable=<rule> -- <why>`:\n"
            + "\n".join(v.render() for v in new))

    def test_baseline_ships_empty(self):
        # the ratchet is at ZERO: accepting a violation into the
        # baseline is a deliberate, reviewed act — this test makes the
        # diff loud
        baseline = load_baseline()
        assert not baseline.accepted, \
            "baseline.json should stay empty; prefer fixing or inline " \
            "suppression with justification"

    def test_every_pass_actually_ran(self, live_result):
        _index, result, _elapsed = live_result
        assert set(result.passes_run) == {"locks", "jax", "clock",
                                          "chaos"}

    def test_wall_clock_budget(self, live_result):
        index, _result, elapsed = live_result
        assert len(index.modules) > 100, "live tree went missing?"
        assert elapsed < 10.0, (
            f"full-tree lzy-lint took {elapsed:.1f}s — over the 10s "
            f"tier-1 budget; profile the passes before this becomes "
            f"the test everyone skips")

    def test_chaos_registry_is_covered(self, live_result):
        # every registered point hit, every hit registered (the rules
        # would fail the ratchet; this asserts the inventory exists and
        # is non-trivial so a refactor cannot silently empty the pass)
        index, _result, _elapsed = live_result
        from lzy_tpu.analysis.chaos_contracts import registry_summary

        registry = registry_summary(index)
        assert len(registry) >= 19      # 19 points as of PR 14
        assert all(p["hits"] for p in registry)

    def test_lock_inventory_scale(self, live_result):
        # the lock-site extraction underlies every lock rule: if the
        # resolver breaks, the pass goes silently blind — pin the scale
        index, _result, _elapsed = live_result
        from lzy_tpu.analysis.locks import lock_sites

        sites = lock_sites(index)
        assert len(sites) >= 200
        assert any("RequestQueue._lock" in s["lock"] for s in sites)
        assert any("ReplicaFleet._lock" in s["lock"] for s in sites)


# -- the CLI ------------------------------------------------------------------

class TestCli:
    def test_json_output_clean(self, capsys):
        from lzy_tpu.analysis.__main__ import main

        rc = main(["--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["clean"] is True
        assert doc["new_violations"] == []
        assert doc["files"] > 100
        assert doc["lock_sites"]
        assert doc["chaos_registry"]

    def test_list_rules(self, capsys):
        from lzy_tpu.analysis.__main__ import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in core.RULES:
            assert rule in out

    def test_subset_of_passes(self, capsys):
        from lzy_tpu.analysis.__main__ import main

        assert main(["--passes", "clock,chaos"]) == 0
        assert "passes=clock,chaos" in capsys.readouterr().out

    def test_corpus_fails_the_cli(self, capsys):
        from lzy_tpu.analysis.__main__ import main

        rc = main(["--root", str(CORPUS), "--no-baseline"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "[NEW]" in out
