"""Smoke-test the deployable control-plane entrypoint (VERDICT r3 #3).

``lzy_tpu.service.serve`` is the control-plane image's ENTRYPOINT
(``docker/Dockerfile.controlplane``) and the only main() composing
workflow + executor + allocator + channels + whiteboards for deployment —
it must not be the one untested module in the tree. This spawns it as a
real subprocess (the same way the container runs it), drives a two-op
workflow with a whiteboard through the gRPC surface, and checks clean
SIGTERM shutdown plus the arg-error paths. Mirrors the role of the
reference's service mains (e.g. ``lzy/lzy-service/.../LzyServiceMain``
started by its docker-compose) without needing a docker daemon.
"""

import dataclasses
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time

import pytest

from lzy_tpu import op, whiteboard
from lzy_tpu.core.lzy import Lzy
from lzy_tpu.runtime.remote import RemoteRuntime
from lzy_tpu.rpc import RpcWorkflowClient
from lzy_tpu.rpc.control import RpcWhiteboardClient
from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig

TESTS_DIR = str(pathlib.Path(__file__).parent)
REPO_ROOT = str(pathlib.Path(__file__).parents[1])


# module level: the serve subprocess's process workers import this module
# (PYTHONPATH below) and resolve the ops by reference
@op
def serve_double(x: int) -> int:
    return x * 2


@op
def serve_add(a: int, b: int) -> int:
    return a + b


@whiteboard("serve_e2e_result")
@dataclasses.dataclass
class ServeResult:
    total: int


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_serve(args, *, timeout_s: float = 30.0):
    """Start serve.py exactly as the container does; wait for readiness."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO_ROOT, TESTS_DIR] +
        ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lzy_tpu.service.serve", *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    deadline = time.monotonic() + timeout_s
    banner = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        banner += line
        if "serving on" in line:
            return proc, banner
    proc.kill()
    raise AssertionError(f"serve.py never became ready; output:\n{banner}"
                         f"{proc.stdout.read() if proc.stdout else ''}")


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serve")
    port = _free_port()
    storage_uri = f"file://{tmp}/storage"
    proc, _ = _spawn_serve([
        "--db", str(tmp / "meta.db"),
        "--storage-uri", storage_uri,
        "--port", str(port),
        "--backend", "process",
        "--gc-period-s", "60",
    ])
    yield proc, f"127.0.0.1:{port}", storage_uri
    if proc.poll() is None:
        proc.kill()
        proc.wait(10)


class TestServeEntrypoint:
    def test_two_op_workflow_with_whiteboard_end_to_end(self, served):
        proc, address, storage_uri = served
        wf_client = RpcWorkflowClient(address)
        wb_client = RpcWhiteboardClient(address)
        storage = DefaultStorageRegistry()
        storage.register_storage(
            "default", StorageConfig(uri=storage_uri), default=True)
        lzy = Lzy(
            runtime=RemoteRuntime(wf_client, poll_period_s=0.1,
                                  stream_logs=False, graph_timeout_s=180),
            storage_registry=storage,
        )
        lzy._whiteboard_client = wb_client
        try:
            with lzy.workflow("serve-smoke") as wf:
                wb = wf.create_whiteboard(ServeResult, tags=["serve-smoke"])
                total = serve_add(serve_double(4), serve_double(9))
                wb.total = total
                assert int(total) == 26
            found = wb_client.query(tags=["serve-smoke"])
            assert len(found) == 1
            assert found[0].status == "FINALIZED"
        finally:
            wf_client.close()
            wb_client.close()
        assert proc.poll() is None, "control plane died during the workflow"

    def test_sigterm_shuts_down_cleanly(self, served):
        # ordered after the workflow test (same module-scoped fixture):
        # shutdown is the last thing the smoke checks
        proc, _, _ = served
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(30)
        out = proc.stdout.read()
        assert rc == 0, f"non-zero exit {rc}; output tail:\n{out[-2000:]}"
        assert "shutting down" in out


class TestServeModel:
    def test_serve_model_generates_over_rpc(self, tmp_path):
        """--serve-model boots the inference plane in the deployable
        process: InferGenerate/InferStats answer on the same gRPC port as
        the workflow surface."""
        from lzy_tpu.rpc import RpcInferenceClient

        port = _free_port()
        proc, banner = _spawn_serve([
            "--db", str(tmp_path / "m.db"),
            "--storage-uri", f"file://{tmp_path}/s",
            "--port", str(port),
            "--serve-model", "tiny",
            "--serve-slots", "2",
        ], timeout_s=120)
        try:
            assert "model=tiny" in banner
            client = RpcInferenceClient(f"127.0.0.1:{port}")
            try:
                res = client.generate([5, 9, 3], max_new_tokens=4,
                                      timeout_s=120)
                assert res["model"] == "tiny"
                assert len(res["tokens"]) == 4
                assert res["ttft_ms"] is not None
                stats = client.stats()
                assert stats["slots"] == 2
                assert stats["requests_finished"] >= 1
            finally:
                client.close()
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10)

    def test_serve_slo_quota_over_the_wire(self, tmp_path):
        """--serve-slo + --tenant-rps: the tenant's second request inside
        the burst window comes back as the typed QuotaExceeded
        (RESOURCE_EXHAUSTED) with the retry_after_s hint rehydrated from
        the wire — the quota-exceeded status end to end through a real
        server process."""
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.serving import QuotaExceeded

        port = _free_port()
        proc, banner = _spawn_serve([
            "--db", str(tmp_path / "m.db"),
            "--storage-uri", f"file://{tmp_path}/s",
            "--port", str(port),
            "--serve-model", "tiny",
            "--serve-slots", "2",
            "--serve-slo",
            # 0.01 req/s: the first request's compile time (seconds)
            # must not refill the bucket before the second call
            "--tenant-rps", "0.01",
            "--tenant-burst-s", "100",
        ], timeout_s=120)
        try:
            client = RpcInferenceClient(f"127.0.0.1:{port}")
            try:
                res = client.generate([5, 9], max_new_tokens=2,
                                      timeout_s=120, tenant="cust-a")
                assert res["status"] == "ok"
                with pytest.raises(QuotaExceeded) as ei:
                    client.generate([5, 9], max_new_tokens=2,
                                    timeout_s=120, tenant="cust-a")
                assert "cust-a" in str(ei.value)
                assert ei.value.retry_after_s is not None
                # another tenant's bucket is untouched
                assert client.generate([5, 9], max_new_tokens=2,
                                       timeout_s=120,
                                       tenant="cust-b")["status"] == "ok"
            finally:
                client.close()
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(10)

    def test_unknown_model_fails_fast(self, tmp_path):
        res = subprocess.run(
            [sys.executable, "-m", "lzy_tpu.service.serve",
             "--db", str(tmp_path / "m.db"),
             "--storage-uri", f"file://{tmp_path}/s",
             "--serve-model", "gpt99"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=120, cwd=REPO_ROOT,
        )
        assert res.returncode != 0
        assert "gpt99" in res.stdout


class TestServeArgErrors:
    def _run(self, args):
        return subprocess.run(
            [sys.executable, "-m", "lzy_tpu.service.serve", *args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=60, cwd=REPO_ROOT,
        )

    def test_missing_storage_uri_fails_fast(self):
        res = self._run([])
        assert res.returncode == 2
        assert "--storage-uri" in res.stdout

    def test_gke_requires_worker_image(self, tmp_path):
        res = self._run([
            "--db", str(tmp_path / "m.db"),
            "--storage-uri", f"file://{tmp_path}/s",
            "--backend", "gke",
        ])
        assert res.returncode == 2
        assert "--worker-image" in res.stdout

    def test_gateway_journal_requires_fleet_front(self, tmp_path):
        res = self._run([
            "--db", str(tmp_path / "m.db"),
            "--storage-uri", f"file://{tmp_path}/s",
            "--serve-model", "tiny",
            "--gateway-journal",
        ])
        assert res.returncode == 2
        assert "--gateway-journal" in res.stdout
