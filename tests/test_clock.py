"""Injectable time (utils/clock): SystemClock contract + VirtualClock
determinism — the seam every latency-bearing component now runs on."""

import threading
import time

from lzy_tpu.utils.clock import SYSTEM_CLOCK, SystemClock, VirtualClock


def _start_parked(clock, target, *args):
    """Start a participant thread and wait until it has parked (the
    serialized-startup discipline the load driver uses)."""
    before = clock.participants
    t = threading.Thread(target=target, args=args, daemon=True)
    t.start()
    while clock.participants < before + 1:
        time.sleep(0.0005)
    clock.settle()
    return t


class TestSystemClock:
    def test_now_is_monotonic_and_time_is_wall(self):
        c = SystemClock()
        a, b = c.now(), c.now()
        assert b >= a
        assert abs(c.time() - time.time()) < 5.0

    def test_wait_and_event(self):
        c = SystemClock()
        ev = c.event()
        assert isinstance(ev, threading.Event)
        assert c.wait(ev, timeout=0.01) is False
        ev.set()
        assert c.wait(ev, timeout=0.01) is True

    def test_module_singleton(self):
        assert isinstance(SYSTEM_CLOCK, SystemClock)


class TestVirtualClockBasics:
    def test_advance_without_participants(self):
        c = VirtualClock()
        assert c.now() == 0.0
        c.advance(10.5)
        assert c.now() == 10.5
        c.advance_to(7.0)           # time never goes backwards
        assert c.now() == 10.5

    def test_time_offsets_by_epoch(self):
        c = VirtualClock(epoch=1000.0)
        c.advance(5.0)
        assert c.time() == 1005.0

    def test_token_bucket_on_virtual_clock(self):
        """The original injectable-clock consumer still composes: a
        bucket drained at t=0 refills exactly with advance()."""
        from lzy_tpu.serving.tenancy import TokenBucket

        c = VirtualClock()
        bucket = TokenBucket(1.0, 2.0, clock=c.now)
        assert bucket.try_take(2.0) is None
        wait = bucket.try_take(1.0)
        assert wait == 1.0          # deterministic: virtual time
        c.advance(1.0)
        assert bucket.try_take(1.0) is None


class TestVirtualClockScheduling:
    def test_sleepers_fire_in_deadline_then_seq_order(self):
        c = VirtualClock()
        order = []

        def worker(name, delay):
            with c.participant():
                c.sleep(delay)
                order.append((name, c.now()))

        for name, delay in (("a", 2.0), ("b", 1.0), ("c", 2.0)):
            _start_parked(c, worker, name, delay)
        c.advance_to(3.0)
        # b first (earlier deadline); a before c (registered earlier)
        assert order == [("b", 1.0), ("a", 2.0), ("c", 2.0)]
        assert c.now() == 3.0

    def test_event_set_wakes_waiter_at_settle(self):
        c = VirtualClock()
        ev = c.event()
        out = {}

        def waiter():
            with c.participant():
                out["flag"] = c.wait(ev, timeout=100.0)
                out["t"] = c.now()

        t = _start_parked(c, waiter)
        c.advance_to(3.0)
        assert "flag" not in out
        ev.set()
        c.settle()
        t.join(5.0)
        assert out == {"flag": True, "t": 3.0}

    def test_wait_timeout_fires_on_advance(self):
        c = VirtualClock()
        ev = c.event()
        out = {}

        def waiter():
            with c.participant():
                out["flag"] = c.wait(ev, timeout=2.5)
                out["t"] = c.now()

        t = _start_parked(c, waiter)
        c.advance_to(10.0)
        t.join(5.0)
        assert out == {"flag": False, "t": 2.5}

    def test_interleaving_is_deterministic(self):
        """Two identical multi-thread schedules produce the identical
        event order — the property every capacity metric rests on."""

        def run_once():
            c = VirtualClock()
            log = []

            def worker(name, period, n):
                with c.participant():
                    for i in range(n):
                        c.sleep(period)
                        log.append((name, round(c.now(), 6)))

            for name, period in (("x", 0.7), ("y", 1.1), ("z", 0.7)):
                _start_parked(c, worker, name, period, 5)
            c.advance_to(10.0)
            return log

        assert run_once() == run_once()

    def test_request_wait_on_virtual_clock(self):
        """serving.scheduler.Request composes: finish() from the driving
        thread wakes a virtually-parked waiter; deadlines expire on
        virtual time."""
        from lzy_tpu.serving.scheduler import Request

        c = VirtualClock()
        req = Request([1, 2, 3], 4, deadline_s=5.0, clock=c)
        out = {}

        def waiter():
            with c.participant():
                out["done"] = req.wait(timeout=60.0)
                out["t"] = c.now()

        t = _start_parked(c, waiter)
        c.advance_to(2.0)
        assert not req.expired
        req.finish()
        c.settle()
        t.join(5.0)
        assert out == {"done": True, "t": 2.0}
        c.advance_to(10.0)
        # the deadline is virtual too (finished requests just don't care)
        req2 = Request([1], 1, deadline_s=1.0, clock=c)
        assert not req2.expired
        c.advance(1.5)
        assert req2.expired
