"""GKE TPU backend: pod-spec construction, idempotent launch/destroy, orphan
reconciliation — unit-tested against a fake k8s API (the reference's
MockKuberClientFactory pattern; ``KuberVmAllocator.java:84-197`` and
``PodSpecBuilder.java:91-150`` are the parity targets)."""

import pytest

from lzy_tpu.service.allocator import ALLOCATING, Vm
from lzy_tpu.service.backends import GkeTpuBackend
from lzy_tpu.service.kube import FakeKubeApi, KubeConflict, KubeNotFound
from lzy_tpu.types import TpuPoolSpec, VmSpec


def make_backend(api=None):
    return GkeTpuBackend(
        control_address="10.0.0.5:8122",
        storage_uri="s3://lzy-bucket/prefix",
        image="gcr.io/proj/lzy-tpu-worker:1.0",
        namespace="lzy-tpu",
        api=api or FakeKubeApi(),
        service_account="lzy-worker",
    )


def make_vm(i=0, gang="gang-1", token="tok-abc"):
    return Vm(id=f"vm-{i}", session_id="sess-1", pool_label="tpu-v5e-16",
              status=ALLOCATING, gang_id=gang, host_index=i, gang_size=2,
              worker_token=token)


V5E_POOL = TpuPoolSpec(label="tpu-v5e-16", tpu_type="v5e", topology="4x4")


class TestPodSpec:
    def test_tpu_slice_selectors_and_chip_resources(self):
        b = make_backend()
        m = b.build_pod_manifest(make_vm(), V5E_POOL)
        sel = m["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
        assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
        res = m["spec"]["containers"][0]["resources"]
        assert res["requests"]["google.com/tpu"] == "8"   # v5e chips per host
        assert res["limits"]["google.com/tpu"] == "8"

    def test_worker_contract(self):
        """The pod runs the standard worker entrypoint with control-plane
        address, vm id, storage, the VM's WORKER token in env, and the pod IP
        advertised for p2p peers (PodSpecBuilder env contract parity)."""
        b = make_backend()
        vm = make_vm(1)
        m = b.build_pod_manifest(vm, V5E_POOL)
        c = m["spec"]["containers"][0]
        args = c["args"]
        assert args[:3] == ["python", "-m", "lzy_tpu.rpc.worker_main"]
        assert "10.0.0.5:8122" in args and "vm-1" in args
        assert "s3://lzy-bucket/prefix" in args
        env = {e["name"]: e for e in c["env"]}
        assert env["LZY_WORKER_TOKEN"]["value"] == "tok-abc"
        assert env["LZY_WORKER_ADVERTISE_HOST"]["valueFrom"]["fieldRef"][
            "fieldPath"] == "status.podIP"
        labels = m["metadata"]["labels"]
        assert labels["lzy/vm-id"] == "vm-1"
        assert labels["lzy/gang-id"] == "gang-1"
        assert labels["lzy/host-index"] == "1"
        assert m["spec"]["serviceAccountName"] == "lzy-worker"

    def test_cpu_pool_has_no_tpu_selectors(self):
        b = make_backend()
        m = b.build_pod_manifest(
            make_vm(), VmSpec(label="cpu-small", cpu_count=4, ram_gb=32)
        )
        assert "nodeSelector" not in m["spec"]
        assert "resources" not in m["spec"]["containers"][0]


class TestLaunchDestroy:
    def test_launch_creates_one_pod_per_gang_host(self):
        api = FakeKubeApi()
        b = make_backend(api)
        for i in range(2):
            b.launch(make_vm(i), V5E_POOL)
        assert sorted(api.pods["lzy-tpu"]) == ["lzy-vm-0", "lzy-vm-1"]

    def test_launch_is_idempotent_across_resume(self):
        api = FakeKubeApi()
        b = make_backend(api)
        vm = make_vm()
        b.launch(vm, V5E_POOL)
        b.launch(vm, V5E_POOL)          # durable-op resume: no error, no dup
        assert api.create_calls == 2 and len(api.pods["lzy-tpu"]) == 1

    def test_destroy_deletes_and_tolerates_missing(self):
        api = FakeKubeApi()
        b = make_backend(api)
        vm = make_vm()
        b.launch(vm, V5E_POOL)
        b.destroy(vm)
        assert api.pods["lzy-tpu"] == {}
        b.destroy(vm)                   # second delete: 404 tolerated

    def test_orphan_reconciliation(self):
        """Pods whose VM record vanished (crash between create and record
        cleanup) are reaped by label; live ones survive."""
        api = FakeKubeApi()
        b = make_backend(api)
        b.launch(make_vm(0), V5E_POOL)
        b.launch(make_vm(1), V5E_POOL)
        deleted = b.reconcile_orphans(live_vm_ids=["vm-0"])
        assert deleted == ["lzy-vm-1"]
        assert list(api.pods["lzy-tpu"]) == ["lzy-vm-0"]


class TestFakeApi:
    def test_conflict_and_not_found_semantics(self):
        api = FakeKubeApi()
        api.create_pod("ns", {"metadata": {"name": "p", "labels": {}}})
        with pytest.raises(KubeConflict):
            api.create_pod("ns", {"metadata": {"name": "p", "labels": {}}})
        with pytest.raises(KubeNotFound):
            api.delete_pod("ns", "absent")
        assert api.list_pods("ns", "a=b") == []


class TestDeadPodRecovery:
    def test_conflict_with_dead_pod_recreates(self):
        """A resume that finds the pod already terminated (ImagePullBackOff,
        crashed worker; restartPolicy=Never) must recreate it, not wait on a
        registration that will never come."""
        api = FakeKubeApi()
        b = make_backend(api)
        vm = make_vm()
        b.launch(vm, V5E_POOL)
        api.pods["lzy-tpu"]["lzy-vm-0"]["status"] = {"phase": "Failed"}
        b.launch(vm, V5E_POOL)
        assert api.pods["lzy-tpu"]["lzy-vm-0"].get("status") is None
        assert api.create_calls == 3      # initial + conflicted + recreate

    def test_conflict_with_live_pod_resumes(self):
        api = FakeKubeApi()
        b = make_backend(api)
        vm = make_vm()
        b.launch(vm, V5E_POOL)
        api.pods["lzy-tpu"]["lzy-vm-0"]["status"] = {"phase": "Running"}
        b.launch(vm, V5E_POOL)            # no recreate
        assert api.pods["lzy-tpu"]["lzy-vm-0"]["status"] == {"phase": "Running"}
        assert api.delete_calls == 0
