"""Model tests: shapes, sharded end-to-end train steps on the 8-device mesh,
loss decrease — the compute slice of BASELINE configs 2–4 at toy sizes."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lzy_tpu.models import (
    BertConfig,
    LlamaConfig,
    ResNetConfig,
    bert,
    count_params,
    llama,
    resnet,
    unbox,
)
from lzy_tpu.parallel import TrainState, fsdp_mesh, make_train_step, mesh_for


def _train(loss_fn, params, axes, batch, mesh, steps=3, accum_steps=1):
    tx = optax.adam(1e-3)
    step, shard_state, _ = make_train_step(
        loss_fn, tx, mesh=mesh, param_logical_axes=axes,
        batch_logical_axes=("batch",), accum_steps=accum_steps,
    )
    state = shard_state(TrainState.create(params, tx))
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


class TestLlama:
    def test_forward_shape_and_dtype(self):
        cfg = LlamaConfig.tiny()
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jnp.ones((2, 16), jnp.int32)
        logits = llama.Llama(cfg).apply({"params": params}, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32  # head always f32

    def test_params_are_annotated(self):
        cfg = LlamaConfig.tiny()
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        assert axes["layer_0"]["attn"]["q_proj"]["kernel"] == (
            "embed", "heads", "head_dim",
        )
        assert axes["embed_tokens"] == ("vocab", "embed")

    def test_fsdp_train_step_loss_decreases(self):
        cfg = LlamaConfig.tiny()
        mesh = fsdp_mesh()
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        batch = {
            "tokens": jax.random.randint(
                jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
            )
        }
        losses, state = _train(
            llama.make_loss_fn(cfg), params, axes, batch, mesh
        )
        assert losses[-1] < losses[0]
        # fsdp actually shards the embed table over the mesh
        emb = state.params["embed_tokens"]
        assert emb.sharding.spec[1] == "fsdp"

    def test_tp_plus_fsdp_mesh(self):
        cfg = LlamaConfig.tiny()
        mesh = mesh_for(tp=2, fsdp=-1)
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        batch = {"tokens": jnp.ones((4, 16), jnp.int32)}
        losses, state = _train(
            llama.make_loss_fn(cfg), params, axes, batch, mesh, steps=2
        )
        gate = state.params["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert gate.sharding.spec == jax.sharding.PartitionSpec("fsdp", "tp")

    def test_ring_attention_path_matches_dense(self):
        cfg_dense = LlamaConfig.tiny()
        cfg_ring = LlamaConfig.tiny()
        cfg_ring = type(cfg_ring)(**{
            **cfg_ring.__dict__, "use_ring_attention": True,
        })
        mesh = mesh_for(sp=8)
        boxed, _ = llama.init_params(cfg_dense, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0,
                                    cfg_dense.vocab_size)
        dense_logits = llama.Llama(cfg_dense).apply({"params": params}, tokens)
        ring_logits = llama.Llama(cfg_ring).apply(
            {"params": params}, tokens, mesh
        )
        np.testing.assert_allclose(
            np.asarray(dense_logits), np.asarray(ring_logits),
            atol=0.1, rtol=0.05,  # bf16 compute tolerance
        )

    def test_llama3_8b_param_count(self):
        cfg = LlamaConfig.llama3_8b()
        # analytic param count ≈ 8.03B (untied lm_head, like Llama-3)
        d, v, l, ff = cfg.d_model, cfg.vocab_size, cfg.n_layers, cfg.d_ff
        attn = d * d + 2 * d * (cfg.n_kv_heads * cfg.head_dim) + d * d
        mlp = 3 * d * ff
        head = 0 if cfg.tie_embeddings else v * d
        total = v * d + l * (attn + mlp + 2 * d) + d + head
        assert 7.9e9 < total < 8.1e9


class TestBert:
    def test_mlm_train_step(self):
        cfg = BertConfig.tiny()
        mesh = fsdp_mesh()
        boxed, axes = bert.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        rng = jax.random.PRNGKey(3)
        tokens = jax.random.randint(rng, (8, 32), 0, cfg.vocab_size)
        batch = {
            "tokens": tokens,
            "labels": tokens,
            "mlm_mask": (jax.random.uniform(rng, (8, 32)) < 0.15),
        }
        losses, _ = _train(bert.make_loss_fn(cfg), params, axes, batch, mesh)
        assert losses[-1] < losses[0]

    def test_base_config_param_count(self):
        cfg = BertConfig.base()
        boxed, _ = bert.init_params(cfg, jax.random.PRNGKey(0))
        n = count_params(unbox(boxed))
        assert 105e6 < n < 120e6  # BERT-base ≈ 110M


class TestResNet:
    def test_forward_and_train(self):
        cfg = ResNetConfig.tiny()
        mesh = fsdp_mesh()
        boxed, axes = resnet.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        batch = {
            "images": jax.random.normal(jax.random.PRNGKey(4), (8, 32, 32, 3)),
            "labels": jnp.zeros((8,), jnp.int32),
        }
        losses, _ = _train(resnet.make_loss_fn(cfg), params, axes, batch,
                           mesh, steps=3)
        assert losses[-1] < losses[0]

    def test_resnet50_param_count(self):
        cfg = ResNetConfig.resnet50()
        boxed, _ = resnet.init_params(cfg, jax.random.PRNGKey(0), image_size=64)
        n = count_params(unbox(boxed))
        assert 23e6 < n < 28e6  # ResNet-50 ≈ 25.5M


class TestGeneration:
    def test_decode_matches_full_forward(self):
        """KV-cache decoding must produce the same greedy continuation as
        repeatedly running the full (cacheless) forward."""
        from lzy_tpu.models import generate as generate_fn

        cfg = LlamaConfig.tiny(vocab_size=64)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        prompt = jnp.array([[5, 9, 3]], jnp.int32)

        out = generate_fn(cfg, params, prompt, max_new_tokens=4)
        assert out.shape == (1, 7)

        # reference: greedy with the full forward each step
        model = llama.Llama(cfg)
        seq = prompt
        for _ in range(4):
            logits = model.apply({"params": params}, seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_eos_padding(self):
        from lzy_tpu.models import generate as generate_fn

        cfg = LlamaConfig.tiny(vocab_size=16)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(1))
        params = unbox(boxed)
        prompt = jnp.zeros((2, 2), jnp.int32)
        out = generate_fn(cfg, params, prompt, max_new_tokens=3,
                                eos_token=1)
        assert out.shape == (2, 5)

    def test_sampled_generation_shape(self):
        from lzy_tpu.models import generate as generate_fn

        cfg = LlamaConfig.tiny(vocab_size=32)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(2))
        params = unbox(boxed)
        out = generate_fn(
            cfg, params, jnp.ones((2, 2), jnp.int32), max_new_tokens=5,
            temperature=0.8, rng=jax.random.PRNGKey(7),
        )
        assert out.shape == (2, 7)
        assert int(out.max()) < 32

    def test_prompt_overflow_rejected(self):
        from lzy_tpu.models import generate as generate_fn

        cfg = LlamaConfig.tiny()
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="exceeds"):
            generate_fn(cfg, unbox(boxed),
                              jnp.zeros((1, 10), jnp.int32),
                              max_new_tokens=cfg.max_seq_len)


class TestLlamaMoe:
    def test_moe_llama_trains(self):
        cfg = LlamaConfig.tiny()
        cfg = dataclasses.replace(cfg, n_experts=4, moe_top_k=2)
        mesh = fsdp_mesh()
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        assert "moe" in params["layer_0"], "MoE layer missing"
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)}
        losses, _ = _train(llama.make_loss_fn(cfg), params, axes, batch, mesh)
        assert losses[-1] < losses[0]


class TestSequenceParallelTraining:
    def test_train_step_through_ring_attention(self):
        """Long-context training is first-class: a full sharded TRAIN step
        (fwd + bwd + optimizer) differentiates through the ppermute ring
        over an sp mesh, with the batch's sequence dim sharded."""
        cfg = LlamaConfig.tiny()
        cfg = type(cfg)(**{**cfg.__dict__, "use_ring_attention": True})
        mesh = mesh_for(sp=4, fsdp=2)
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tx = optax.adam(1e-3)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(params, tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size)}
        losses = []
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
        # the batch really trains with its sequence dim on the sp axis
        emb = state.params["embed_tokens"]
        assert "fsdp" in str(emb.sharding.spec)


class TestUlyssesInModel:
    def test_ulysses_path_matches_dense(self):
        cfg_dense = LlamaConfig.tiny()
        cfg_u = type(cfg_dense)(**{
            **cfg_dense.__dict__, "use_ulysses_attention": True,
        })
        mesh = mesh_for(sp=4, fsdp=2)  # tiny() has 4 heads: heads % sp == 0
        boxed, _ = llama.init_params(cfg_dense, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0,
                                    cfg_dense.vocab_size)
        dense_logits = llama.Llama(cfg_dense).apply({"params": params}, tokens)
        u_logits = llama.Llama(cfg_u).apply({"params": params}, tokens, mesh)
        np.testing.assert_allclose(
            np.asarray(dense_logits), np.asarray(u_logits),
            atol=0.1, rtol=0.05,  # bf16 compute tolerance
        )

    def test_train_step_through_ulysses(self):
        cfg = LlamaConfig.tiny()
        cfg = type(cfg)(**{**cfg.__dict__, "use_ulysses_attention": True})
        mesh = mesh_for(sp=4, fsdp=2)
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adam(1e-3)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(unbox(boxed), tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size)}
        losses = []
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestT5:
    def _cfg(self):
        from lzy_tpu.models.t5 import T5Config

        return T5Config.tiny(vocab_size=97)

    def test_loss_and_grads_finite(self):
        import optax

        from lzy_tpu.models import unbox
        from lzy_tpu.models.t5 import init_params, make_loss_fn

        cfg = self._cfg()
        boxed, axes = init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        batch = {
            "enc_tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 12),
                                             0, cfg.vocab_size),
            "dec_tokens": jax.random.randint(jax.random.PRNGKey(2), (2, 8),
                                             0, cfg.vocab_size),
            "enc_mask": jnp.ones((2, 12), bool),
        }
        loss, grads = jax.value_and_grad(make_loss_fn(cfg))(params, batch)
        assert jnp.isfinite(loss)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert jnp.all(jnp.isfinite(leaf))

    def test_decoder_is_causal(self):
        """Changing a future decoder token must not change earlier logits."""
        from lzy_tpu.models import unbox
        from lzy_tpu.models.t5 import T5, init_params

        cfg = self._cfg()
        boxed, _ = init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        enc = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 97)
        dec = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, 97)
        dec2 = dec.at[0, -1].set((dec[0, -1] + 1) % 97)
        model = T5(cfg)
        l1 = model.apply({"params": params}, enc, dec)
        l2 = model.apply({"params": params}, enc, dec2)
        assert jnp.allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
        # but the encoder DOES influence everything
        enc2 = enc.at[0, 0].set((enc[0, 0] + 1) % 97)
        l3 = model.apply({"params": params}, enc2, dec)
        assert not jnp.allclose(l1, l3, atol=1e-5)

    def test_enc_mask_hides_padding(self):
        from lzy_tpu.models import unbox
        from lzy_tpu.models.t5 import T5, init_params

        cfg = self._cfg()
        boxed, _ = init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        enc = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 97)
        dec = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 97)
        mask = jnp.array([[True, True, True, True, False, False]])
        model = T5(cfg)
        base = model.apply({"params": params}, enc, dec, mask)
        # mutate only the masked-out positions: logits must be identical
        enc_mut = enc.at[0, 4:].set((enc[0, 4:] + 3) % 97)
        same = model.apply({"params": params}, enc_mut, dec, mask)
        assert jnp.allclose(base, same, atol=1e-6)

    def test_cached_generation_matches_full_forward(self):
        """Greedy decode through the KV cache must reproduce the argmax chain
        of repeated full (non-decode) forwards — the strongest equivalence
        check for the cache."""
        from lzy_tpu.models import unbox
        from lzy_tpu.models.t5 import T5, init_params, t5_generate

        cfg = self._cfg()
        boxed, _ = init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 97)

        gen = t5_generate(cfg, params, enc, max_new_tokens=5)

        model = T5(cfg)
        dec = jnp.full((2, 1), cfg.bos_token, jnp.int32)
        ref = []
        for _ in range(5):
            logits = model.apply({"params": params}, enc, dec)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            ref.append(nxt[:, None])
            dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        assert jnp.array_equal(gen, jnp.concatenate(ref, axis=1))

    def test_shards_on_mesh(self):
        import optax

        from lzy_tpu.models import unbox
        from lzy_tpu.models.t5 import T5Config, init_params, make_loss_fn
        from lzy_tpu.parallel import TrainState, make_train_step, mesh_for

        # every sharded dim must divide the mesh axes (vocab over tp=2 etc.)
        cfg = T5Config.tiny(vocab_size=128)
        boxed, axes = init_params(cfg, jax.random.PRNGKey(0))
        mesh = mesh_for(dp=2, fsdp=2, tp=2)
        step, shard_state, _ = make_train_step(
            make_loss_fn(cfg), optax.adamw(1e-3), mesh=mesh,
            param_logical_axes=axes,
            # a single prefix covers every batch leaf (both are [B, T])
            batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(unbox(boxed), optax.adamw(1e-3)))
        batch = {
            "enc_tokens": jax.random.randint(jax.random.PRNGKey(1), (4, 16),
                                             0, cfg.vocab_size),
            "dec_tokens": jax.random.randint(jax.random.PRNGKey(2), (4, 16),
                                             0, cfg.vocab_size),
        }
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]


class TestSampling:
    def _logits(self):
        # token 3 dominant, then 1, then 0; tokens 2,4 negligible
        return jnp.array([[1.0, 2.0, -5.0, 4.0, -6.0]])

    def test_top_k_restricts_support(self):
        from lzy_tpu.models.generate import sample_token

        seen = set()
        rng = jax.random.PRNGKey(0)
        for _ in range(40):
            tok, rng = sample_token(self._logits(), 1.0, rng, top_k=2)
            seen.add(int(tok[0]))
        assert seen <= {1, 3}
        assert 3 in seen

    def test_top_p_keeps_nucleus_only(self):
        from lzy_tpu.models.generate import sample_token

        # softmax of [1,2,-5,4,-6] ≈ [.045,.122,.0001,.832,...]: p=.9 keeps
        # {3,1}; p tiny keeps only the argmax
        seen = set()
        rng = jax.random.PRNGKey(1)
        for _ in range(40):
            tok, rng = sample_token(self._logits(), 1.0, rng, top_p=0.9)
            seen.add(int(tok[0]))
        assert seen <= {1, 3}
        tok, _ = sample_token(self._logits(), 1.0, jax.random.PRNGKey(2),
                              top_p=0.01)
        assert int(tok[0]) == 3

    def test_greedy_ignores_filters(self):
        from lzy_tpu.models.generate import sample_token

        tok, _ = sample_token(self._logits(), 0.0, jax.random.PRNGKey(0),
                              top_k=1, top_p=0.1)
        assert int(tok[0]) == 3

    def test_generate_accepts_sampling_filters(self):
        from lzy_tpu.models import generate, llama, unbox

        cfg = llama.LlamaConfig.tiny(vocab_size=64)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        out = generate(cfg, params, jnp.array([[3, 5]], jnp.int32),
                       max_new_tokens=3, temperature=0.8, top_k=10,
                       top_p=0.95)
        assert out.shape == (1, 5)

    def test_top_k_zero_is_disabled_not_a_crash(self):
        from lzy_tpu.models.generate import sample_token

        tok, _ = sample_token(self._logits(), 1.0, jax.random.PRNGKey(0),
                              top_k=0)
        assert 0 <= int(tok[0]) < 5


class TestPackedDocuments:
    """Segment-masked attention + per-document positions: a packed row must
    behave exactly like its documents run separately."""

    def test_packed_forward_equals_per_document(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=128),
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        model = llama.Llama(cfg)

        rng = np.random.default_rng(0)
        doc_a = rng.integers(0, 128, 24)
        doc_b = rng.integers(0, 128, 40)
        packed = jnp.asarray(np.concatenate([doc_a, doc_b]))[None, :]
        segments = jnp.asarray(
            np.concatenate([np.zeros(24, np.int32), np.ones(40, np.int32)])
        )[None, :]

        packed_logits = model.apply({"params": params}, packed, None,
                                    segments)
        la = model.apply({"params": params}, jnp.asarray(doc_a)[None, :])
        lb = model.apply({"params": params}, jnp.asarray(doc_b)[None, :])
        np.testing.assert_allclose(
            np.asarray(packed_logits[0, :24]), np.asarray(la[0]),
            atol=2e-4, rtol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(packed_logits[0, 24:]), np.asarray(lb[0]),
            atol=2e-4, rtol=2e-4,
        )

    def test_flash_path_matches_fallback_packed(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=128),
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 128, (2, 256))
        )
        segments = jnp.asarray(
            np.repeat(np.arange(4), 64)[None, :].repeat(2, 0)
        )
        base = llama.Llama(cfg).apply({"params": params}, tokens, None,
                                      segments)
        flash_cfg = dataclasses.replace(cfg, use_flash_kernel=True)
        flashed = llama.Llama(flash_cfg).apply({"params": params}, tokens,
                                               None, segments)
        np.testing.assert_allclose(np.asarray(flashed), np.asarray(base),
                                   atol=2e-4, rtol=2e-4)

    def test_loss_masks_document_boundaries(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64),
                                  dtype=jnp.float32,
                                  param_dtype=jnp.float32)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        loss_fn = llama.make_loss_fn(cfg)
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, 64, (2, 32))
        )
        segments = jnp.zeros((2, 32), jnp.int32).at[:, 16:].set(1)
        # boundary-masked packed loss == mean of the two per-document losses
        # over the same model (manual check: identical token count per doc)
        packed = float(loss_fn(params, {"tokens": tokens,
                                        "segments": segments}))
        explicit_mask = np.ones((2, 32), bool)
        # shifted mask index 15 = full index 16: target token 16 is the
        # first of document 1, predicted from document 0 — the boundary
        explicit_mask[:, 16] = False
        manual = float(loss_fn(params, {
            "tokens": tokens, "segments": segments,
            "mask": jnp.asarray(explicit_mask),
        }))
        assert abs(packed - manual) < 1e-6

    def test_train_step_with_segments_decreases_loss(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64))
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (8, 64))
        )
        segments = jnp.asarray(
            np.repeat(np.arange(2), 32)[None, :].repeat(8, 0)
        )
        mesh = fsdp_mesh()
        losses, _ = _train(
            llama.make_loss_fn(cfg, mesh), params, axes,
            {"tokens": tokens, "segments": segments}, mesh, steps=4,
        )
        assert losses[-1] < losses[0]
