"""Gated 16-device dryrun (VERDICT r3 #10).

The driver may invoke ``dryrun_multichip(16)``; the local tier pins 8
virtual devices (conftest), so this runs the 16-device branch in a
subprocess with its own device count. Slow (several minutes of XLA:CPU
compiles) — gated behind ``LZY_SLOW=1``; executed at least once per
round so the branch the driver may take has run before it matters.
"""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = str(pathlib.Path(__file__).parents[1])


@pytest.mark.skipif(not os.environ.get("LZY_SLOW"),
                    reason="slow 16-device dryrun; set LZY_SLOW=1")
def test_dryrun_multichip_16_devices():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    res = subprocess.run(
        [sys.executable, "__graft_entry__.py", "dryrun", "16"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "dryrun ok: 16 devices" in res.stdout, res.stdout[-1000:]
    # the dryrun's own stderr assertion guards this, but double-check at
    # the 16-device shape too — resharding cliffs often appear only at
    # larger axis products
    assert "Involuntary full rematerialization" not in res.stderr

    from conftest import record_tier_run

    record_tier_run("LZY_SLOW:dryrun16",
                    res.stdout.strip().splitlines()[-1][:200])
