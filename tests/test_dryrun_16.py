"""16-device dryrun coverage (VERDICT r3 #10; weak #5 un-gating).

The driver may invoke ``dryrun_multichip(16)``; the local tier pins 8
virtual devices (conftest), so these run the 16-device branch in a
subprocess with its own device count.

Two tiers: the TRIMMED variant (core sharded train step + ring
attention, ~20 s of XLA:CPU compiles) runs in the DEFAULT suite, so
>=8-device multi-device coverage no longer depends on anyone exporting
``LZY_SLOW``; the full composition sweep (ulysses/moe/hybrid/pipeline —
several minutes) stays behind the gate and is executed at least once per
round.
"""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = str(pathlib.Path(__file__).parents[1])


def test_dryrun_multichip_16_devices_trimmed():
    """Un-gated: the trimmed 16-device dryrun (train step + ring) runs on
    every default-tier invocation — multi-device coverage above the
    conftest's pinned 8 devices must not be skippable-by-default."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    res = subprocess.run(
        [sys.executable, "__graft_entry__.py", "dryrun", "16", "trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "dryrun ok: 16 devices (trimmed)" in res.stdout, \
        res.stdout[-1000:]
    assert "Involuntary full rematerialization" not in res.stderr


@pytest.mark.skipif(not os.environ.get("LZY_SLOW"),
                    reason="slow FULL 16-device dryrun; set LZY_SLOW=1 "
                           "(the trimmed variant above always runs)")
def test_dryrun_multichip_16_devices():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    res = subprocess.run(
        [sys.executable, "__graft_entry__.py", "dryrun", "16"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "dryrun ok: 16 devices" in res.stdout, res.stdout[-1000:]
    # the dryrun's own stderr assertion guards this, but double-check at
    # the 16-device shape too — resharding cliffs often appear only at
    # larger axis products
    assert "Involuntary full rematerialization" not in res.stderr

    from conftest import record_tier_run

    record_tier_run("LZY_SLOW:dryrun16",
                    res.stdout.strip().splitlines()[-1][:200])
