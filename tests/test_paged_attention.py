"""Native paged-attention kernels + int8 KV quantization (PR 9).

Three layers of coverage, matching the module's correctness contract
(``lzy_tpu/ops/paged_attention.py``, docs/serving.md "Native paged
attention & KV quantization"):

- **Op-level bit-exactness sweeps**: the lax gather-attention fallback
  and the Pallas kernel (interpret mode on CPU) must produce EXACTLY the
  same bytes across page sizes, ragged per-row lengths, scratch-block
  idle rows, chunk widths (1-token decode, gamma+1 verify windows,
  prefill chunks), dtypes, and quantization on/off. "Close" is not a
  pass: the serving stack's oracle chain (paged == dense == generate())
  is built on bit-identity, and the native path joins that chain.
- **Model/engine-level oracle tests**: a ``PagedInferenceEngine`` with
  ``native_attention=True`` must be bit-identical to the solo
  ``generate()`` oracle — greedy and sampled, speculation on and off —
  because the lax kernel reproduces the legacy gather math op for op.
- **int8 bounded divergence**: quantized output is intentionally NOT
  bit-identical; what IS asserted: the per-element dequantization error
  bound (one optimal-scale quantization step), kernel-independence of
  quantized output (legacy == lax == pallas on the same int8 pool),
  greedy-match rate against the fp oracle over long continuations, pool
  integrity (no leaked/corrupted blocks under quantization), and the 2x
  block-count win at a fixed pool byte budget.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import decode_config, generate, init_cache
from lzy_tpu.models.llama import Llama, LlamaConfig
from lzy_tpu.ops.paged_attention import (
    DEQUANT_ERROR_EWMA, KVQuant, default_kernel, dequantize_kv,
    note_dequant_error, paged_attention, quantize_kv)
from lzy_tpu.serving import PagedInferenceEngine
from lzy_tpu.serving.kv_cache import (
    blocks_for_bytes, kv_block_bytes, kv_quant_sidecar_bytes)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drive(eng, *reqs, rounds=400):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        eng.step()
    raise AssertionError("requests did not finish")


def _metric_value(metric) -> float:
    """Sum over all label combinations of a process-registry metric."""
    return sum(metric._values.values())


# -- quantizer units ---------------------------------------------------------


class TestQuantizeKV:
    def test_error_bounded_by_one_step(self):
        rng = np.random.default_rng(0)
        for scale_exp in (-3, 0, 4):          # tiny, unit, large ranges
            x = jnp.asarray(
                rng.standard_normal((64, 3, 16)) * 10.0 ** scale_exp,
                jnp.float32)
            q, s, z = quantize_kv(x)
            deq = dequantize_kv(q, s, z, jnp.float32)
            span = (jnp.max(x, -1) - jnp.min(x, -1))[..., None]
            # one exactly-representable step of the OPTIMAL scale (the
            # pow2 rounding costs at most a factor 2 over half a step)
            bound = span / 254.0 + 1e-6
            assert bool(jnp.all(jnp.abs(deq - x) <= bound))

    def test_scales_are_powers_of_two(self):
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((32, 2, 8)),
            jnp.float32)
        _, s, _ = quantize_kv(x)
        log = np.log2(np.asarray(s))
        assert np.allclose(log, np.round(log)), \
            "pow2 scales are what make dequantization FMA-invariant"

    def test_constant_vectors_near_exact(self):
        x = jnp.full((4, 2, 8), 3.25, jnp.float32)
        q, s, z = quantize_kv(x)
        deq = dequantize_kv(q, s, z, jnp.float32)
        assert bool(jnp.all(jnp.abs(deq - x) <= 1e-6))
        assert bool(jnp.all(q == 0))

    def test_ewma_gauge_updates(self):
        v1 = note_dequant_error(0.5)
        v2 = note_dequant_error(0.1)
        assert v2 < v1
        assert _metric_value(DEQUANT_ERROR_EWMA) == pytest.approx(v2)


# -- op-level bit-exactness sweeps -------------------------------------------


def _random_case(rng, *, page, pages, b, t, kv, g, d, dtype, quant):
    """One randomized paged-attention problem with the serving stack's
    real shapes: shuffled block ownership, a row parked on the scratch
    block at position 0 (the idle-slot case), ragged per-row positions,
    and tables whose tail entries are scratch (partially-grown rows)."""
    n = b * pages + 3
    L = pages * page
    q = jnp.asarray(rng.standard_normal((b, t, kv * g, d)), dtype)
    k_pool = jnp.asarray(rng.standard_normal((n, page, kv, d)), dtype)
    v_pool = jnp.asarray(rng.standard_normal((n, page, kv, d)), dtype)
    ids = rng.permutation(np.arange(1, n))[: b * pages]
    pt = ids.reshape(b, pages).astype(np.int32)
    pt[0, pages // 2:] = 0                    # partially-grown row
    starts = rng.integers(0, L - t, size=(b,)).astype(np.int32)
    starts[0] = 0                             # idle row on scratch
    pos = jnp.asarray(starts[:, None] + np.arange(t)[None, :], jnp.int32)
    quant_side = None
    if quant:
        k_pool, ks, kz = quantize_kv(k_pool)
        v_pool, vs, vz = quantize_kv(v_pool)
        quant_side = KVQuant(ks, kz, vs, vz)
    return q, k_pool, v_pool, jnp.asarray(pt), pos, quant_side


class TestKernelBitExactness:
    @pytest.mark.parametrize("page,pages", [(4, 8), (8, 4), (16, 3)])
    @pytest.mark.parametrize("t", [1, 5])
    @pytest.mark.parametrize("quant", [False, True])
    def test_pallas_interpret_equals_lax(self, page, pages, t, quant):
        rng = np.random.default_rng(page * 100 + t)
        for dtype in (jnp.bfloat16, jnp.float32):
            q, kp, vp, pt, pos, side = _random_case(
                rng, page=page, pages=pages, b=3, t=t, kv=2, g=2, d=16,
                dtype=dtype, quant=quant)
            a = paged_attention(q, kp, vp, pt, pos, kernel="lax",
                                dtype=dtype, quant=side)
            p = paged_attention(q, kp, vp, pt, pos, kernel="pallas",
                                dtype=dtype, quant=side, interpret=True)
            assert bool(jnp.array_equal(a, p)), \
                f"pallas != lax at dtype={dtype} quant={quant}"

    def test_exact_under_jit_and_odd_head_dim(self):
        # the engine runs the op inside jitted programs; fusion must not
        # perturb the identity (d=24: a head dim whose softmax scale is
        # not a power of two)
        import functools

        rng = np.random.default_rng(7)
        q, kp, vp, pt, pos, side = _random_case(
            rng, page=8, pages=4, b=2, t=3, kv=2, g=3, d=24,
            dtype=jnp.bfloat16, quant=True)
        f_lax = jax.jit(functools.partial(
            paged_attention, kernel="lax", dtype=jnp.bfloat16, quant=side))
        f_pal = jax.jit(functools.partial(
            paged_attention, kernel="pallas", dtype=jnp.bfloat16,
            quant=side, interpret=True))
        assert bool(jnp.array_equal(f_lax(q, kp, vp, pt, pos),
                                    f_pal(q, kp, vp, pt, pos)))

    def test_pallas_rejects_vmem_oversized_pools(self):
        """An HBM-sized pool must fail the pallas path at TRACE time
        with an actionable error (warmup AOT-compiles, so this lands at
        boot), not as a Mosaic compile failure mid-serving."""
        big = jax.ShapeDtypeStruct((200_000, 64, 2, 128), jnp.bfloat16)
        q = jax.ShapeDtypeStruct((1, 1, 4, 128), jnp.bfloat16)
        pt = jax.ShapeDtypeStruct((1, 16), jnp.int32)
        pos = jax.ShapeDtypeStruct((1, 1), jnp.int32)
        with pytest.raises(ValueError, match="VMEM"):
            jax.eval_shape(
                lambda *a: paged_attention(*a, kernel="pallas",
                                           interpret=False),
                q, big, big, pt, pos)

    def test_unknown_kernel_and_missing_dtype_rejected(self):
        rng = np.random.default_rng(3)
        q, kp, vp, pt, pos, side = _random_case(
            rng, page=4, pages=2, b=1, t=1, kv=1, g=1, d=8,
            dtype=jnp.float32, quant=True)
        with pytest.raises(ValueError, match="unknown"):
            paged_attention(q, kp, vp, pt, pos, kernel="cuda",
                            dtype=jnp.float32, quant=side)
        with pytest.raises(ValueError, match="dtype"):
            paged_attention(q, kp, vp, pt, pos, quant=side)


class TestModelPathBitExactness:
    """The three read paths of ``Attention._decode_step`` — legacy
    gather, native lax, native pallas — through the REAL model forward:
    prefill chunks, 1-token decode, and a gamma+1 verify window over
    interleaved per-row positions."""

    def _run_path(self, tiny_model, **over):
        cfg0, params = tiny_model
        B, page = 3, 8
        pages = cfg0.max_seq_len // page
        n = B * pages + 1
        pt = jnp.arange(1, B * pages + 1, dtype=jnp.int32).reshape(
            B, pages)
        dcfg = dataclasses.replace(
            decode_config(cfg0), decode_paged=True, kv_page_size=page,
            kv_pages=n, **over)
        model = Llama(dcfg)
        cache = init_cache(lambda: model.init(
            jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
            page_table=pt))
        toks = jnp.asarray(np.random.default_rng(0).integers(
            1, cfg0.vocab_size, (B, 6)), jnp.int32)
        outs = []
        # prefill chunk (t=6) → decode step (t=1) → verify window (t=6)
        for chunk in (toks, toks[:, :1], toks):
            logits, upd = model.apply(
                {"params": params, "cache": cache}, chunk,
                page_table=pt, mutable=["cache"])
            cache = upd["cache"]
            outs.append(logits)
        return outs

    def test_native_lax_bit_identical_to_legacy(self, tiny_model):
        legacy = self._run_path(tiny_model)
        native = self._run_path(tiny_model, paged_attention_native=True,
                                paged_kernel="lax")
        for a, b in zip(legacy, native):
            assert bool(jnp.array_equal(a, b))

    def test_native_pallas_bit_identical_to_legacy(self, tiny_model):
        legacy = self._run_path(tiny_model)
        native = self._run_path(tiny_model, paged_attention_native=True,
                                paged_kernel="pallas")
        for a, b in zip(legacy, native):
            assert bool(jnp.array_equal(a, b))

    def test_quantized_output_is_kernel_independent(self, tiny_model):
        """int8 output diverges boundedly from fp but must NOT depend on
        which kernel read the pool — legacy gather+dequant, lax, and
        pallas all dequantize with the same (FMA-invariant) formula."""
        ql = self._run_path(tiny_model, kv_quant="int8")
        qn = self._run_path(tiny_model, kv_quant="int8",
                            paged_attention_native=True,
                            paged_kernel="lax")
        qp = self._run_path(tiny_model, kv_quant="int8",
                            paged_attention_native=True,
                            paged_kernel="pallas")
        for a, b, c in zip(ql, qn, qp):
            assert bool(jnp.array_equal(a, b))
            assert bool(jnp.array_equal(a, c))

    def test_quant_diverges_boundedly_from_fp(self, tiny_model):
        fp = self._run_path(tiny_model)
        q8 = self._run_path(tiny_model, kv_quant="int8")
        worst = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32))))
            for a, b in zip(fp, q8))
        assert 0.0 < worst < 0.5, \
            f"int8 logits should differ from fp, boundedly (got {worst})"

    def test_quant_requires_paged(self, tiny_model):
        cfg0, params = tiny_model
        dcfg = dataclasses.replace(decode_config(cfg0), kv_quant="int8")
        model = Llama(dcfg)
        with pytest.raises(ValueError, match="decode_paged"):
            model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 1), jnp.int32))


# -- engine-level oracle ------------------------------------------------------


class TestNativeEngineOracle:
    PROMPTS = [[5, 9, 3, 11, 7], [2, 4, 2, 4, 2, 4, 2], [31, 9]]
    N = 24

    def test_native_lax_greedy_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        want = [_oracle_tokens(cfg, params, p, self.N)
                for p in self.PROMPTS]
        eng = PagedInferenceEngine(cfg, params, slots=4, page_size=8,
                                   native_attention=True, kernel="lax")
        try:
            reqs = [eng.submit(p, max_new_tokens=self.N)
                    for p in self.PROMPTS]
            _drive(eng, *reqs)
            assert [r.tokens for r in reqs] == want
            assert eng.stats().kernel_path == "lax"
        finally:
            eng.close()

    def test_native_lax_spec_greedy_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        want = [_oracle_tokens(cfg, params, p, self.N)
                for p in self.PROMPTS]
        eng = PagedInferenceEngine(cfg, params, slots=4, page_size=8,
                                   native_attention=True, kernel="lax",
                                   spec_tokens=4)
        try:
            reqs = [eng.submit(p, max_new_tokens=self.N)
                    for p in self.PROMPTS]
            _drive(eng, *reqs)
            assert [r.tokens for r in reqs] == want
        finally:
            eng.close()

    def test_native_pallas_spec_greedy_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        want = [_oracle_tokens(cfg, params, p, 12) for p in self.PROMPTS]
        eng = PagedInferenceEngine(cfg, params, slots=4, page_size=8,
                                   native_attention=True, kernel="pallas",
                                   spec_tokens=3)
        try:
            reqs = [eng.submit(p, max_new_tokens=12)
                    for p in self.PROMPTS]
            _drive(eng, *reqs)
            assert [r.tokens for r in reqs] == want
            assert eng.stats().kernel_path == "pallas"
        finally:
            eng.close()

    def test_native_sampled_matches_legacy_engine(self, tiny_model):
        """Sampled rows share the engine-wide rng stream; the native
        path must not perturb a single draw."""
        cfg, params = tiny_model

        def sample_with(native):
            eng = PagedInferenceEngine(
                cfg, params, slots=3, page_size=8, temperature=0.8,
                seed=11, native_attention=native)
            try:
                reqs = [eng.submit(p, max_new_tokens=10)
                        for p in self.PROMPTS]
                _drive(eng, *reqs)
                return [r.tokens for r in reqs]
            finally:
                eng.close()

        assert sample_with(True) == sample_with(False)

    def test_dispatch_counter_counts_each_prefill_chunk(self, tiny_model):
        """One inc per PROGRAM, on every path: a multi-chunk prefill
        must move the counter by its chunk count, like decode/verify."""
        from lzy_tpu.ops.paged_attention import DISPATCHES

        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=8,
                                   prefill_chunk=4,
                                   native_attention=True)
        try:
            before = _metric_value(DISPATCHES)
            r = eng.submit(list(range(1, 21)), max_new_tokens=3)
            _drive(eng, r)
            # 20-token prompt at chunk 4 = 5 prefill programs, plus the
            # decode steps after it
            assert _metric_value(DISPATCHES) - before >= 5 + 2
        finally:
            eng.close()

    def test_auto_kernel_resolves_by_platform(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=8,
                                   native_attention=True, kernel="auto")
        try:
            assert eng.kernel_path == default_kernel()
        finally:
            eng.close()
        eng = PagedInferenceEngine(cfg, params, slots=1, page_size=8)
        try:
            assert eng.kernel_path == "legacy"
            assert eng.stats().kernel_path == "legacy"
        finally:
            eng.close()

    def test_bad_engine_kwargs_rejected(self, tiny_model):
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="kv_quant"):
            PagedInferenceEngine(cfg, params, kv_quant="fp4")
        with pytest.raises(ValueError, match="kernel"):
            PagedInferenceEngine(cfg, params, kernel="cuda")
        with pytest.raises(ValueError, match="not both"):
            PagedInferenceEngine(cfg, params, kv_blocks=8,
                                 kv_pool_bytes=1 << 20)
        # an explicit kernel the legacy path would silently ignore is a
        # misconfiguration, not a preference
        with pytest.raises(ValueError, match="native_attention"):
            PagedInferenceEngine(cfg, params, kernel="pallas")

    def test_serve_flags_validated(self):
        from lzy_tpu.service.serve import main

        for flags in (["--serve-kernel", "pallas"],
                      ["--serve-kv-quant", "int8"],
                      ["--serve-kv-pool-mb", "64"],
                      ["--serve-paged", "--serve-kernel", "pallas"],
                      ["--serve-paged", "--serve-kv-blocks", "8",
                       "--serve-kv-pool-mb", "64",
                       "--serve-native-attention"]):
            with pytest.raises(SystemExit):
                main(["--storage-uri", "file:///tmp/x",
                      "--serve-model", "tiny"] + flags)


# -- int8 engine: bounded divergence + pool integrity -------------------------


class TestQuantEngine:
    def test_greedy_match_rate_vs_fp_oracle(self, tiny_model):
        """The bounded-divergence regime: int8 greedy decode follows the
        fp oracle's continuation closely over LONG continuations. The
        floor is deliberately below 1.0 — quantized decode is allowed to
        diverge (once the argmax flips, continuations legitimately go
        elsewhere) — but a collapse below it would mean the quantizer is
        destroying the signal, not perturbing it."""
        cfg, params = tiny_model
        prompts = [[5, 9, 3, 11, 7], [2, 4, 2, 4, 2, 4, 2],
                   [31, 9, 17, 1], [8, 8, 40]]
        n = 48
        want = [_oracle_tokens(cfg, params, p, n) for p in prompts]
        eng = PagedInferenceEngine(cfg, params, slots=4, page_size=8,
                                   kv_quant="int8",
                                   native_attention=True)
        try:
            reqs = [eng.submit(p, max_new_tokens=n) for p in prompts]
            _drive(eng, *reqs, rounds=600)
            total = sum(len(w) for w in want)
            matched = sum(
                sum(a == b for a, b in zip(r.tokens, w))
                for r, w in zip(reqs, want))
            rate = matched / total
            assert rate >= 0.8, \
                f"greedy-match rate {rate:.3f} vs fp oracle collapsed"
            st = eng.stats()
            assert st.kv_quant == "int8"
        finally:
            eng.close()

    def test_pool_integrity_under_quantization(self, tiny_model):
        """Quantization must be invisible to the block pool's
        accounting: drive admissions past capacity (evictions), finish
        everything, and assert every non-cached block returned to the
        free list with zero refcounts — int8 payloads and sidecars ride
        the same block ids, so a leak here would mean the quant path
        forked the bookkeeping."""
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=8,
                                   kv_blocks=9, kv_quant="int8",
                                   native_attention=True)
        try:
            prompts = [[i, i + 1, i + 2] * 3 for i in range(1, 11, 2)]
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            _drive(eng, *reqs, rounds=800)
            assert all(r.error is None or "preempted" in r.error
                       for r in reqs)
            pool = eng.kv.pool
            stats = eng.kv.stats()
            assert stats.blocks_free + stats.blocks_cached \
                == stats.blocks_total
            for block in range(pool.n_blocks):
                assert pool.refcount(block) == 0
        finally:
            eng.close()

    def test_quant_prefix_reuse_stays_consistent(self, tiny_model):
        """A second request hitting the radix cache reads blocks the
        FIRST request quantized — the sidecars must describe those
        bytes. Both continuations must equal a fresh quantized run
        (cache reuse can never change quantized output)."""
        cfg, params = tiny_model
        prompt = [7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 7, 3, 5]
        outs = []
        for _ in range(2):
            eng = PagedInferenceEngine(cfg, params, slots=2, page_size=8,
                                       kv_quant="int8",
                                       native_attention=True)
            try:
                r1 = eng.submit(prompt, max_new_tokens=10)
                _drive(eng, r1)
                r2 = eng.submit(prompt, max_new_tokens=10)
                _drive(eng, r2)
                assert eng.kv.stats().prefix_hit_tokens > 0, \
                    "second request should hit the radix cache"
                assert r2.tokens == r1.tokens
                outs.append(r1.tokens)
            finally:
                eng.close()
        assert outs[0] == outs[1]

    def test_pool_bytes_budget_doubles_blocks_under_int8(self, tiny_model):
        """The capacity claim, end to end: at a FIXED payload byte
        budget an int8 engine owns at least 2x the blocks of the bf16
        engine (sidecars are metadata outside the payload budget, like
        page tables — kv_quant_sidecar_bytes reports them)."""
        cfg, params = tiny_model
        budget = 512 * 1024
        sizes = {}
        for quant in (None, "int8"):
            eng = PagedInferenceEngine(cfg, params, slots=2, page_size=8,
                                       kv_pool_bytes=budget,
                                       kv_quant=quant,
                                       native_attention=True)
            try:
                sizes[quant] = eng.stats().kv_blocks_total
            finally:
                eng.close()
        assert sizes["int8"] >= 2 * sizes[None], sizes


class TestBlockBytes:
    def test_int8_halves_payload_and_doubles_blocks(self):
        kw = dict(page_size=16, n_kv_heads=8, head_dim=128, n_layers=32)
        fp = kv_block_bytes(dtype="bfloat16", **kw)
        q8 = kv_block_bytes(dtype="bfloat16", kv_quant="int8", **kw)
        assert q8 * 2 == fp
        budget = 1 << 30
        assert blocks_for_bytes(budget, dtype="bfloat16",
                                kv_quant="int8", **kw) \
            == 2 * blocks_for_bytes(budget, dtype="bfloat16", **kw)

    def test_sidecar_accounting(self):
        kw = dict(page_size=16, n_kv_heads=8, n_layers=32)
        assert kv_quant_sidecar_bytes(**kw) == 0
        side = kv_quant_sidecar_bytes(kv_quant="int8", **kw)
        assert side == 2 * 32 * 16 * 8 * 2 * 4
        # sidecars stay a small fraction of the int8 payload they ride
        payload = kv_block_bytes(head_dim=128, kv_quant="int8",
                                 dtype="bfloat16", **kw)
        assert side / payload < 0.07


class TestQuantMismatchFailsClosed:
    def test_quant_export_into_fp_pool_is_refused(self, tiny_model):
        """A quantized export imported into an fp pool must FAIL CLOSED
        (local re-prefill), never scatter int8 quantization codes into a
        pool that reads them as KV values — the decode replica's output
        must stay the fp oracle's."""
        from lzy_tpu.serving import DecodeEngine, PrefillEngine
        from lzy_tpu.serving.disagg.kv_export import import_kv

        cfg, params = tiny_model
        prompt = list(range(16)) + [40]
        pf = PrefillEngine(cfg, params, slots=1, page_size=8,
                           kv_quant="int8")
        try:
            req = pf.submit(prompt)
            _drive(pf, req)
            export = req.kv_export
        finally:
            pf.close()
        de = DecodeEngine(cfg, params, slots=1, page_size=8)
        try:
            free_before = de.kv.pool.free_count()
            assert import_kv(de, export) == 0
            assert de.kv.match_len(prompt) == 0, \
                "a refused import must not register the prefix"
            assert de.kv.pool.free_count() == free_before
            r = de.submit(prompt, max_new_tokens=8)
            _drive(de, r)
            assert r.tokens == _oracle_tokens(cfg, params, prompt, 8)
        finally:
            de.close()

    def test_fp_export_into_quant_pool_is_refused(self, tiny_model):
        from lzy_tpu.serving import DecodeEngine, PrefillEngine
        from lzy_tpu.serving.disagg.kv_export import import_kv

        cfg, params = tiny_model
        prompt = list(range(16)) + [40]
        pf = PrefillEngine(cfg, params, slots=1, page_size=8)
        try:
            req = pf.submit(prompt)
            _drive(pf, req)
            export = req.kv_export
        finally:
            pf.close()
        de = DecodeEngine(cfg, params, slots=1, page_size=8,
                          kv_quant="int8")
        try:
            assert import_kv(de, export) == 0
            assert de.kv.match_len(prompt) == 0
        finally:
            de.close()

    def test_builders_reject_native_knobs_without_paged(self):
        from lzy_tpu.service.inference import (
            build_gateway_service, build_inference_service)

        for kw in ({"kv_quant": "int8"}, {"native_attention": True},
                   {"kernel": "lax"}):
            with pytest.raises(ValueError, match="paged"):
                build_inference_service("tiny", **kw)
            with pytest.raises(ValueError, match="paged"):
                build_gateway_service("tiny", **kw)

    def test_resident_gauge_sums_engines_and_clears_on_close(
            self, tiny_model):
        from lzy_tpu.ops.paged_attention import QUANT_BLOCKS_RESIDENT

        cfg, params = tiny_model
        base = _metric_value(QUANT_BLOCKS_RESIDENT)
        engines = []
        try:
            for i in range(2):
                eng = PagedInferenceEngine(
                    cfg, params, slots=1, page_size=8, kv_quant="int8")
                engines.append(eng)
                # a 2-block prompt: its full blocks stay radix-cached
                # (resident, unreferenced) after the request finishes
                r = eng.submit(list(range(16)) + [5 + i],
                               max_new_tokens=2)
                _drive(eng, r)
                eng.stats()
            per = [e._quant_resident_seen for e in engines]
            assert all(v > 0 for v in per)
            assert _metric_value(QUANT_BLOCKS_RESIDENT) - base \
                == pytest.approx(sum(per))
        finally:
            for eng in engines:
                eng.close()
        assert _metric_value(QUANT_BLOCKS_RESIDENT) - base \
            == pytest.approx(0)


class TestQuantDisaggTransfer:
    def test_quantized_blocks_travel_export_import(self, tiny_model):
        """Disaggregation moves every cache leaf by name — int8 payloads
        AND their scale/zero-point sidecars must arrive together, and a
        decode continuation over imported quantized blocks must equal
        the monolithic quantized engine's (quantization is deterministic,
        so identical fp inputs produce identical int8 bytes)."""
        from lzy_tpu.serving import DecodeEngine, PrefillEngine
        from lzy_tpu.serving.disagg.kv_export import import_kv

        cfg, params = tiny_model
        prompt = list(range(16)) + [40]      # 2 full blocks at page 8
        kw = dict(page_size=8, kv_quant="int8", native_attention=True)
        pf = PrefillEngine(cfg, params, slots=1, **kw)
        try:
            req = pf.submit(prompt)
            _drive(pf, req)
            assert req.error is None, req.error
            export = req.kv_export
        finally:
            pf.close()
        assert export is not None
        assert any("k_scale" in key for key in export.leaves), \
            "quant sidecars must ride the transfer payload"
        de = DecodeEngine(cfg, params, slots=1, **kw)
        try:
            assert import_kv(de, export) == 2
            r = de.submit(prompt, max_new_tokens=8)
            _drive(de, r)
            assert r.error is None, r.error
            assert de.kv.stats().prefix_hit_tokens >= 16
            got = r.tokens
        finally:
            de.close()
        mono = PagedInferenceEngine(cfg, params, slots=1, **kw)
        try:
            m = mono.submit(prompt, max_new_tokens=8)
            _drive(mono, m)
            assert got == m.tokens
        finally:
            mono.close()


# -- spec draft truncation counter (satellite) --------------------------------


class _WindowProposer:
    """Always proposes a fixed draft — forces spec growth every round."""

    def __init__(self, gamma):
        self.gamma = gamma

    def propose(self, tokens):
        return [3] * self.gamma


class TestSpecDraftTruncation:
    def test_truncation_is_counted(self, tiny_model):
        """A pool with a dry free list truncates drafts instead of
        evicting cached blocks (PR 5's backstop); since PR 9 that event
        is COUNTED — EngineStats.spec_draft_truncated and
        lzy_spec_draft_truncated_total — instead of silently reading as
        a low tokens-per-step."""
        from lzy_tpu.serving.spec import DRAFT_TRUNCATED

        cfg, params = tiny_model
        page = 4
        # prompt fills 2 blocks + growth block; pool sized so that once
        # both slots are resident the free list is EMPTY, so every
        # verify round's _grow_for_spec comes up short
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=page, kv_blocks=7,
            spec_tokens=6, proposer=_WindowProposer(6),
            native_attention=True)
        try:
            before = _metric_value(DRAFT_TRUNCATED)
            reqs = [eng.submit([1 + i, 2, 3, 4, 5, 6, 7], max_new_tokens=12)
                    for i in range(2)]
            _drive(eng, *reqs, rounds=600)
            st = eng.stats()
            assert st.spec_draft_truncated > 0
            assert _metric_value(DRAFT_TRUNCATED) > before
        finally:
            eng.close()
