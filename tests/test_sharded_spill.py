"""Shard-parallel jax.Array channel spill: manifest round-trip, assembly,
and the deserialize-only registry entry (SURVEY §7 "jax.Array channels")."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lzy_tpu.channels.sharded_spill import (
    MANIFEST_FORMAT,
    assemble,
    build_manifest,
    is_global_array,
    spill_local_shards,
)
from lzy_tpu.serialization import default_registry
from lzy_tpu.storage.mem import MemStorageClient


def make_sharded(shape=(8, 4), spec=P("a", "b")):
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("a", "b"))
    data = jnp.arange(float(np.prod(shape))).reshape(shape)
    return jax.device_put(data, NamedSharding(mesh, spec)), data


class TestSpill:
    def test_round_trip(self):
        arr, data = make_sharded()
        assert not is_global_array(arr)   # single process: fully addressable
        client = MemStorageClient()
        keys = spill_local_shards(client, "mem://e/x", arr)
        assert len(keys) == 8             # 4x2 partitioning, replica 0 only
        manifest = json.loads(build_manifest(arr, "mem://e/x"))
        assert manifest["format"] == MANIFEST_FORMAT
        np.testing.assert_array_equal(assemble(manifest, storage=client),
                                      np.asarray(data))

    def test_replicated_axis_dedup(self):
        # replicated over "b": only 4 distinct global shards exist
        arr, data = make_sharded(spec=P("a"))
        client = MemStorageClient()
        keys = spill_local_shards(client, "mem://e/y", arr)
        assert len(keys) == 4
        manifest = json.loads(build_manifest(arr, "mem://e/y"))
        assert len(manifest["shards"]) == 4
        np.testing.assert_array_equal(assemble(manifest, storage=client),
                                      np.asarray(data))

    def test_registry_deserializes_manifest_entries(self):
        import io

        arr, data = make_sharded()
        client = MemStorageClient()
        spill_local_shards(client, "mem://e/z", arr)
        manifest = build_manifest(arr, "mem://e/z")
        ser = default_registry().find_by_format(MANIFEST_FORMAT)
        out = ser.deserialize(io.BytesIO(manifest))
        np.testing.assert_array_equal(out, np.asarray(data))
        with pytest.raises(NotImplementedError):
            ser.serialize(arr, io.BytesIO())
