"""Distributed-mode integration: control plane over gRPC, workers as real OS
processes, SDK through the remote client — the closest local analog of the
reference's deployed topology (gRPC microservices + per-VM worker binaries)."""

import pathlib
import time

import pytest

from lzy_tpu import op
from lzy_tpu.core.workflow import RemoteCallError
from lzy_tpu.runtime.remote import RemoteRuntime
from lzy_tpu.rpc import RpcWorkflowClient
from lzy_tpu.service import InProcessCluster
from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig

TESTS_DIR = str(pathlib.Path(__file__).parent)


# ops at module level: the worker PROCESS imports this module (PYTHONPATH
# includes tests/) and resolves them by reference
@op
def proc_square(x: int) -> int:
    return x * x


@op
def proc_sum(a: int, b: int) -> int:
    return a + b


@op
def proc_fail() -> int:
    raise ValueError("failure in a process worker")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("rpc")
    c = InProcessCluster(
        db_path=str(tmp / "meta.db"),
        storage_uri=f"file://{tmp}/storage",
        worker_mode="process",
        worker_pythonpath=TESTS_DIR,
        poll_period_s=0.1,
    )
    yield c
    c.shutdown()


@pytest.fixture()
def remote_lzy(cluster):
    """SDK wired through the gRPC client — nothing in-process."""
    client = RpcWorkflowClient(cluster.rpc_server.address)
    storage = DefaultStorageRegistry()
    storage.register_storage(
        "default", StorageConfig(uri=cluster.storage_uri), default=True
    )
    from lzy_tpu.core.lzy import Lzy

    yield Lzy(
        runtime=RemoteRuntime(client, poll_period_s=0.1, stream_logs=False,
                              graph_timeout_s=180),
        storage_registry=storage,
    )
    client.close()


def test_graph_across_process_workers(remote_lzy):
    with remote_lzy.workflow("proc-wf"):
        r = proc_sum(proc_square(5), proc_square(3))
        assert int(r) == 34


def test_process_worker_reuse(cluster, remote_lzy):
    """A second barrier in the same workflow (same session) reuses the cached
    worker process instead of booting a new interpreter."""
    with remote_lzy.workflow("proc-wf-2"):
        a = proc_square(7)
        assert int(a) == 49                      # barrier 1 boots a process
        procs = {vm.id for vm in cluster.allocator.vms()}
        assert len(procs) == 1
        b = proc_square(int(a))
        assert int(b) == 49 * 49                 # barrier 2 reuses it
        assert {vm.id for vm in cluster.allocator.vms()} == procs


def test_exception_crosses_process_boundary(remote_lzy):
    with pytest.raises(RemoteCallError) as exc_info:
        with remote_lzy.workflow("proc-fail"):
            r = proc_fail()
            _ = r + 1
    cause = exc_info.value.__cause__
    assert isinstance(cause, ValueError)
    assert "failure in a process worker" in str(cause)
    assert any("remote traceback" in n for n in getattr(cause, "__notes__", []))


def test_worker_exits_when_control_plane_gone():
    """A process worker whose control plane is unreachable must exit on its
    own after bounded heartbeat failures — not leak forever."""
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", "lzy_tpu.rpc.worker_main",
         "--control", "127.0.0.1:1",          # nothing listens here
         "--vm-id", "vm-ghost",
         "--storage-uri", "file:///tmp/lzy-ghost"],
        cwd=str(pathlib.Path(TESTS_DIR).parent),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        # registration fails fast OR heartbeats fail 5x @2s → well under 60s
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("worker did not exit after losing the control plane")


def test_worker_reregisters_with_rebooted_control_plane(tmp_path):
    """Process workers survive a control-plane reboot: heartbeats against the
    new plane get 'no registered agent', the worker re-registers its endpoint,
    and the new plane can reach it again."""
    db = str(tmp_path / "meta.db")
    storage = f"file://{tmp_path}/storage"
    c1 = InProcessCluster(db_path=db, storage_uri=storage,
                          worker_mode="process",
                          worker_pythonpath=TESTS_DIR, poll_period_s=0.1,
                          leader_lease_ttl_s=0.3)
    lzy1 = c1.lzy()
    wf = lzy1.workflow("reboot-wf")
    wf.__enter__()
    try:
        r = proc_square(6)
        assert int(r) == 36                      # worker process is up
        (vm,) = c1.allocator.vms()
        port = c1.rpc_server.port
    finally:
        # kill ONLY the control plane (the workflow/session stays open, the
        # worker process survives); bypass harness.shutdown's VM destruction
        c1.rpc_server.stop()
        c1.executor.shutdown()
        c1._lease_stop.set()            # crash = renewal stops too
        c1.store.close()

    time.sleep(0.4)                      # let the dead plane's lease lapse
    # reboot on the SAME port; the worker's next heartbeats reconnect it
    c2 = InProcessCluster(db_path=db, storage_uri=storage,
                          worker_mode="process",
                          worker_pythonpath=TESTS_DIR, poll_period_s=0.1,
                          rpc_port=port)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                agent = c2.allocator.agent(vm.id)
                break
            except KeyError:
                time.sleep(0.2)
        else:
            pytest.fail("worker never re-registered with the new control plane")
        # the re-registered endpoint is live: dial it — an unknown op id must
        # come back as a clean KeyError FROM THE WORKER, proving the round trip
        with pytest.raises(KeyError):
            agent.status("no-such-op")
    finally:
        # c2's backend never launched the worker process, so it can't reap it;
        # terminate c1's orphan explicitly (kill fallback — this cleanup must
        # never mask the test result or skip the steps below)
        import subprocess

        for proc in list(c1.backend._procs.values()):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        c2.shutdown()
        # the workflow context can't exit cleanly (its control plane died);
        # clear the active slot so later tests can open workflows
        from lzy_tpu.core.workflow import LzyWorkflow

        LzyWorkflow.clear_active()


def test_auth_errors_cross_rpc(cluster):
    """gRPC status codes map back to typed exceptions client-side."""
    client = RpcWorkflowClient(cluster.rpc_server.address)
    try:
        with pytest.raises(RuntimeError, match="unsupported client version"):
            client.start_workflow("u", "wf", cluster.storage_uri,
                                  client_version="0.0.1")
        with pytest.raises(KeyError):
            client.graph_status("no-such-exec", "no-such-graph")
    finally:
        client.close()


@op
def slow_value(x: int) -> int:
    import time as _time

    _time.sleep(12)
    return x * 11


def test_task_survives_control_plane_reboot_mid_execution(tmp_path):
    """The strongest distributed claim: a worker process keeps computing
    through a control-plane outage; the rebooted plane (same port, same
    store) resumes the graph, the reconnected worker reports completion, and
    the task's result lands."""
    import io

    from lzy_tpu.durable import DONE
    from lzy_tpu.serialization import default_registry

    db = str(tmp_path / "meta.db")
    storage = f"file://{tmp_path}/storage"
    c1 = InProcessCluster(db_path=db, storage_uri=storage,
                          worker_mode="process",
                          worker_pythonpath=TESTS_DIR, poll_period_s=0.1,
                          leader_lease_ttl_s=0.3)
    c2 = None
    try:
        lzy1 = c1.lzy()
        wf = lzy1.workflow("mid-exec")
        wf.__enter__()
        proxy = slow_value(4)           # lazy: registers only
        # drive the barrier from a thread so the test can kill the control
        # plane while the op is mid-execution
        import threading as _threading

        state = {}

        def run_barrier():
            try:
                state["value"] = int(proxy)
            except Exception as e:
                state["error"] = e

        t = _threading.Thread(target=run_barrier, daemon=True)
        t.start()
        # wait until the task is actually executing on a worker process
        deadline = time.time() + 60
        while time.time() < deadline and not any(
            r.kind == "exec_task" for r in c1.store.running_ops()
        ):
            time.sleep(0.2)
        time.sleep(3)                    # let the worker enter the op body
        (graph_op_id,) = [r.id for r in c1.store.running_ops()
                          if r.kind == "exec_graph"]
        port = c1.rpc_server.port

        # control plane dies mid-execution (worker processes survive)
        c1.rpc_server.stop()
        c1.executor.shutdown()
        c1._lease_stop.set()            # crash = renewal stops too
        c1.store.close()

        time.sleep(0.4)                  # let the dead plane's lease lapse
        c2 = InProcessCluster(db_path=db, storage_uri=storage,
                              worker_mode="process",
                              worker_pythonpath=TESTS_DIR, poll_period_s=0.1,
                              rpc_port=port)
        assert c2.resume_pending_operations() >= 1
        record = c2.executor.await_op(graph_op_id, timeout_s=60)
        assert record.status == DONE, record.error
        # the op result is durable and correct
        graph = record.state["graph"]
        (task,) = graph["tasks"]
        data = c2.storage_client.read_bytes(task["outputs"][0]["uri"])
        ser = default_registry().find_by_format("primitive")
        assert ser.deserialize(io.BytesIO(data)) == 44
    finally:
        # cleanup covers every exit path from just after c1's creation:
        # reap c1's worker processes, shut whichever clusters exist, and
        # always clear the active-workflow slot for later tests
        import subprocess as _subprocess

        for proc in list(c1.backend._procs.values()):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except _subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        if c2 is not None:
            c2.shutdown()
        from lzy_tpu.core.workflow import LzyWorkflow

        LzyWorkflow.clear_active()


def test_worker_plane_requires_worker_token(tmp_path):
    """ADVICE r1 (medium): with IAM enabled the channel-plane and
    allocator-private RPCs are worker-only — anonymous peers and mere USER
    tokens are rejected, while the real worker (holding its allocation-time
    WORKER token) completes a full graph end to end."""
    from lzy_tpu.iam import AuthError
    from lzy_tpu.rpc.core import JsonRpcClient

    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        worker_mode="process",
        worker_pythonpath=TESTS_DIR,
        poll_period_s=0.1,
        with_iam=True,
    )
    client = RpcWorkflowClient(c.rpc_server.address)
    raw = JsonRpcClient(c.rpc_server.address)
    try:
        user_token = c.iam.create_subject("alice")
        storage = DefaultStorageRegistry()
        storage.register_storage(
            "default", StorageConfig(uri=c.storage_uri), default=True
        )
        from lzy_tpu.core.lzy import Lzy

        lzy = Lzy(
            runtime=RemoteRuntime(client, user="alice", token=user_token,
                                  poll_period_s=0.1, stream_logs=False,
                                  graph_timeout_s=180),
            storage_registry=storage,
        )
        # the full data path works: the worker authenticated every channel
        # bind / publish / complete and its register/heartbeats with its token.
        # The VM-specific probes run INSIDE the workflow: finish_workflow's
        # teardown destroys the session's VMs through an ASYNC durable op,
        # so touching vm records after the block races it (observed as a
        # load-dependent flake in full-suite runs)
        with lzy.workflow("iam-proc-wf"):
            assert int(proc_square(6)) == 36

            # one VM's token cannot heartbeat for another VM
            (vm,) = [v for v in c.allocator.vms()]
            with pytest.raises(AuthError):
                raw.call("Heartbeat", {"vm_id": "some-other-vm",
                                       "token": vm.worker_token})
            # OTT bootstrap: the launch env carried a one-time credential
            # which registration burned — a replayed OTT cannot re-register
            ott = c.allocator.mint_bootstrap_token(vm.id)
            redeemed_token, _ = c.allocator.redeem_bootstrap_token(
                vm.id, ott)
            assert redeemed_token == vm.worker_token
            with pytest.raises(AuthError):
                raw.call("RegisterVm", {"vm_id": vm.id,
                                        "endpoint": "127.0.0.1:1",
                                        "token": ott})
            # an OTT minted for one VM cannot bootstrap another — and the
            # probe must not burn it
            other = c.allocator.mint_bootstrap_token("vm-other")
            with pytest.raises(AuthError, match="not vm"):
                c.allocator.redeem_bootstrap_token(vm.id, other)
            assert c.iam.redeem_ott(other) == "vm/vm-other"  # redeemable

        # anonymous peer cannot touch the channel plane
        with pytest.raises(AuthError):
            raw.call("ChannelFailed", {"entry_id": "x", "error": "evil"})
        # a USER token is not a worker credential
        with pytest.raises(AuthError):
            raw.call("RegisterVm", {"vm_id": "vm-x",
                                    "endpoint": "127.0.0.1:1",
                                    "token": user_token})
    finally:
        raw.close()
        client.close()
        c.shutdown()


@op(tpu="v5e-16")
def spmd_rank_sum() -> float:
    """SPMD body: every gang host joins one jax.distributed runtime and the
    result is a CROSS-PROCESS collective sum of (rank+1) — it can only be
    correct if every rank actually ran the program and joined the mesh."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from lzy_tpu.parallel import initialize_gang

    info = initialize_gang()
    assert info["initialized"], "gang did not initialize jax.distributed"
    assert jax.process_count() == info["size"]
    mesh = Mesh(jax.devices(), ("dp",))
    # one element per LOCAL device (works for any per-host device count),
    # each carrying this rank's contribution; the global sum divided by the
    # per-host device count is sum(rank+1 for all ranks)
    n_local = jax.local_device_count()
    local = jnp.ones((n_local,)) * float(info["rank"] + 1)
    global_arr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp")
    )
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(
        global_arr
    )
    return float(total) / n_local


def test_multihost_spmd_psum_across_worker_processes(tmp_path):
    """The flagship distributed claim, executed for real: a gang of OS
    processes (tpu-v5e-16 pool → 2 hosts), each its own interpreter and JAX
    runtime, jax.distributed.initialize'd into ONE mesh via the gang
    coordinator, computing a cross-host collective whose value the test
    asserts. If any rank skips the collective, the sum is wrong or the gang
    blocks and the graph times out."""
    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        worker_mode="process",
        worker_pythonpath=TESTS_DIR,
        poll_period_s=0.1,
    )
    try:
        lzy = c.lzy()
        with lzy.workflow("spmd-wf"):
            r = spmd_rank_sum()
            # gang size 2: ranks contribute 1.0 + 2.0
            assert float(r) == 3.0
        vms = c.allocator.vms()
        assert len(vms) == 2 and len({v.gang_id for v in vms}) == 1
    finally:
        c.shutdown()


@op(tpu="v5e-16")
def spmd_make_global_array():
    """Returns a GLOBAL sharded array: no single process holds all shards,
    so the value can only reach storage through the gang spill protocol."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, PartitionSpec as P

    from lzy_tpu.parallel import initialize_gang

    info = initialize_gang()
    mesh = Mesh(jax.devices(), ("dp",))
    n_local = jax.local_device_count()
    local = (jnp.arange(n_local, dtype=jnp.float32)
             + info["rank"] * n_local)
    return multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp"))


def test_global_sharded_array_crosses_channel(tmp_path):
    """An SPMD op's global jax.Array output reaches the client: each gang
    process spills its own shards, rank 0 publishes the manifest after the
    barrier, and the SDK reassembles the full value."""
    import numpy as np

    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        worker_mode="process",
        worker_pythonpath=TESTS_DIR,
        poll_period_s=0.1,
    )
    try:
        lzy = c.lzy()
        with lzy.workflow("global-array-wf"):
            r = spmd_make_global_array()
            total = np.asarray(r)
        # 2 processes x local devices each; values encode global positions,
        # so a correct assembly is exact arange
        assert total.ndim == 1 and total.shape[0] >= 2
        np.testing.assert_array_equal(
            total, np.arange(total.shape[0], dtype=np.float32))
    finally:
        c.shutdown()


@op(tpu="v5e-16")
def spmd_pretrain(steps: int) -> float:
    """BASELINE config-3 shape end to end: a gang-scheduled SPMD pretrain
    @op — every host joins one mesh, runs sharded train steps (fsdp over
    all global devices), writes a SHARDED checkpoint (each host uploads its
    own shards), and returns the final global loss."""
    import jax
    import optax

    from lzy_tpu.models import llama, unbox
    from lzy_tpu.parallel import (
        CheckpointManager,
        MeshSpec,
        TrainState,
        initialize_gang,
        make_train_step,
    )
    from lzy_tpu.storage import StorageConfig
    from lzy_tpu.storage.registry import client_for

    info = initialize_gang()
    assert info["initialized"] and jax.process_count() == info["size"]
    mesh = MeshSpec(fsdp=-1).build(jax.devices())

    cfg = llama.LlamaConfig(
        vocab_size=256, d_model=32, n_layers=1, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=64, tie_embeddings=True,
    )
    boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adamw(1e-3)
    step, shard_state, _ = make_train_step(
        llama.make_loss_fn(cfg), tx, mesh=mesh, param_logical_axes=axes,
        batch_logical_axes=("batch", "seq"),
    )
    state = shard_state(TrainState.create(unbox(boxed), tx))
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (16, 32), 0, cfg.vocab_size)}
    loss = None
    for _ in range(steps):
        state, metrics = step(state, batch)
        loss = float(metrics["loss"])

    import os

    storage = client_for(StorageConfig(uri=os.environ["LZY_TEST_CKPT_URI"]))
    mgr = CheckpointManager(storage, os.environ["LZY_TEST_CKPT_URI"], "pre")
    mgr.save_sharded(state.params, steps, metrics={"loss": loss})

    # orbax round-trip leg (VERDICT r4 #9): export from the LIVE
    # multi-process run (rank-0 gather-and-write), re-import with the
    # live shardings, and demand bit-identical local shards on each host
    import numpy as np

    from lzy_tpu.parallel.orbax_interop import export_orbax, import_orbax

    orbax_dir = os.environ["LZY_TEST_ORBAX_DIR"]
    export_orbax(state.params, orbax_dir, force=True)
    template = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state.params)
    shardings = jax.tree_util.tree_map(lambda a: a.sharding, state.params)
    back = import_orbax(orbax_dir, template=template, shardings=shardings)
    for x, y in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(back)):
        for sx, sy in zip(x.addressable_shards, y.addressable_shards):
            np.testing.assert_array_equal(
                np.asarray(sx.data), np.asarray(sy.data))
    return loss


def test_multihost_pretrain_op_with_sharded_checkpoint(tmp_path):
    """The north-star scenario executed for real on a 2-process gang: SPMD
    train steps over one global mesh inside an @op, a sharded checkpoint
    written cooperatively by both hosts, and the loss back at the client."""
    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        worker_mode="process",
        worker_pythonpath=TESTS_DIR,
        poll_period_s=0.1,
    )
    ckpt_uri = f"file://{tmp_path}/ckpt"
    try:
        lzy = c.lzy()
        orbax_dir = str(tmp_path / "orbax-export")
        with lzy.workflow("pretrain-wf"):
            r = spmd_pretrain.with_env_vars(
                {"LZY_TEST_CKPT_URI": ckpt_uri,
                 "LZY_TEST_ORBAX_DIR": orbax_dir})(3)
            loss = float(r)
        assert 0.0 < loss < 20.0

        # the orbax export is a real checkpoint on disk (written by the
        # gang's rank 0), importable OUTSIDE the gang too
        from lzy_tpu.parallel.orbax_interop import import_orbax

        outside = import_orbax(orbax_dir)
        import jax as _jax

        assert len(_jax.tree_util.tree_leaves(outside)) > 0

        # the checkpoint is real and SHARDED: manifest published, and the
        # fsdp axis spans both processes' devices, so shard objects exist
        # beyond what one process could have written
        from lzy_tpu.parallel import CheckpointManager
        from lzy_tpu.storage import StorageConfig
        from lzy_tpu.storage.registry import client_for

        storage = client_for(StorageConfig(uri=ckpt_uri))
        mgr = CheckpointManager(storage, ckpt_uri, "pre")
        assert mgr.latest_step() == 3
        assert mgr.manifest(3)["metrics"]["loss"] == loss
        shard_objs = [u for u in storage.list(ckpt_uri) if "/shards/" in u]
        assert len(shard_objs) >= 16    # many leaves x fsdp shards
    finally:
        c.shutdown()


def test_local_module_ships_to_process_worker(cluster, remote_lzy, tmp_path):
    """The reference's `import` scenario, across a REAL process boundary: the
    op imports a module that exists only on the client machine; the worker
    gets it via content-hashed archive sync (module upload → unpack →
    sys.path), not via a shared pythonpath."""
    import sys as _sys

    from lzy_tpu.env.python_env import ManualPythonEnv

    mod = tmp_path / "shipped_dynamic.py"
    mod.write_text("MAGIC = 'shipped-ok'\n")
    assert str(tmp_path) not in _sys.path  # truly client-local

    @op
    def use_shipped() -> str:
        import shipped_dynamic

        return shipped_dynamic.MAGIC

    penv = ManualPythonEnv(
        python_version="%d.%d" % _sys.version_info[:2],
        packages={},
        local_module_paths=[str(mod)],
    )
    with remote_lzy.workflow("module-ship"):
        r = use_shipped.with_python_env(penv)()
        assert str(r) == "shipped-ok"


def test_debug_surface_gated_and_drives_crash_resume(tmp_path):
    """InjectedFailuresController/DebugActionsController parity over RPC:
    disabled planes reject the debug methods outright; an enabled plane can
    arm a crash point, watch the graph park, and kick durable-op recovery."""
    import threading

    storage = f"file://{tmp_path}/storage"

    # 1) default plane: debug surface absent
    c_prod = InProcessCluster(db_path=str(tmp_path / "prod.db"),
                              storage_uri=storage, worker_mode="process",
                              worker_pythonpath=TESTS_DIR, poll_period_s=0.1)
    client = RpcWorkflowClient(c_prod.rpc_server.address)
    try:
        with pytest.raises(Exception, match="[Mm]ethod not found"):
            client.arm_failure("exec_graph.schedule")
    finally:
        client.close()
        c_prod.shutdown()

    # 2) debug plane: arm → run → parked → resume over RPC → completes
    c = InProcessCluster(db_path=str(tmp_path / "dbg.db"),
                         storage_uri=storage, worker_mode="process",
                         worker_pythonpath=TESTS_DIR, poll_period_s=0.1,
                         debug_rpc=True)
    client = RpcWorkflowClient(c.rpc_server.address)
    lzy = c.lzy()
    done = {}
    try:
        client.arm_failure("exec_graph.schedule")
        assert client.list_failures() == ["exec_graph.schedule"]

        def run():
            with lzy.workflow("dbg-wf"):
                done["result"] = int(proc_square(9))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # deterministic sync: the armed point disarms itself when it fires,
        # so an empty list means the crash happened and the op is parked
        deadline = time.time() + 30
        while client.list_failures() and time.time() < deadline:
            time.sleep(0.1)
        assert client.list_failures() == []   # crash fired
        time.sleep(0.3)                        # let the crashed driver unwind
        assert "result" not in done            # parked by the injected crash
        assert client.resume_ops() >= 1
        t.join(timeout=60)
        assert done.get("result") == 81
    finally:
        client.close()
        c.shutdown()
        from lzy_tpu.durable import InjectedFailures

        InjectedFailures.clear()
