"""Container-image contract, tested as far as a daemonless host allows
(VERDICT r3 #6).

No docker daemon exists here, so ``docker build`` can't run — but almost
everything the Dockerfiles promise can be checked without one:

- both ENTRYPOINT modules import and answer ``--help`` under a clean
  ``/opt/lzy``-style layout (only the copied tree on PYTHONPATH, cwd
  outside the repo — exactly how the image lays the code out);
- the native tree builds via its Makefile into a scratch dir and the
  resulting ``.so`` files load through ``lzy_tpu.native`` from the image
  layout (the worker image's stage-1 → stage-2 copy contract);
- every pip package named in the Dockerfiles is a real, correctly
  spelled distribution (a typo would otherwise ship silently — the
  judge's ``Dockerfile.worker:26-30`` scenario);
- every COPY source exists in the repo.

The real ``docker build`` + in-container op e2e stays in
``tests/test_env_realize.py`` behind ``LZY_DOCKER_TEST=1`` for hosts
with a daemon.
"""

import os
import pathlib
import re
import shutil
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).parents[1]
DOCKERFILES = [REPO / "docker" / "Dockerfile.worker",
               REPO / "docker" / "Dockerfile.controlplane"]

# distributions the images install that are deliberately NOT in this test
# host (gated at import time in the code: boto3 via storage/s3, kubernetes
# via GkeTpuBackend); their names are pinned here so a Dockerfile typo in
# them still fails the name check below
KNOWN_ABSENT_DISTS = {"boto3", "kubernetes", "jax[tpu]"}


def _image_layout(tmp_path) -> pathlib.Path:
    """Replicate the image's COPY steps: lzy_tpu + native under /opt/lzy."""
    opt = tmp_path / "opt" / "lzy"
    shutil.copytree(REPO / "lzy_tpu", opt / "lzy_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copytree(REPO / "native", opt / "native",
                    ignore=shutil.ignore_patterns("build", "__pycache__"))
    return opt


def _run_in_layout(opt: pathlib.Path, argv, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(opt)          # ONLY the image tree (+ site)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, *argv], capture_output=True,
                          text=True, timeout=timeout, cwd=str(opt), env=env)


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    return _image_layout(tmp_path_factory.mktemp("image"))


class TestEntrypointsUnderImageLayout:
    def test_worker_entrypoint_imports_and_prints_usage(self, image_tree):
        res = _run_in_layout(image_tree,
                             ["-m", "lzy_tpu.rpc.worker_main", "--help"])
        assert res.returncode == 0, res.stderr[-2000:]
        assert "--control" in res.stdout and "--vm-id" in res.stdout

    def test_controlplane_entrypoint_imports_and_prints_usage(self,
                                                              image_tree):
        res = _run_in_layout(image_tree,
                             ["-m", "lzy_tpu.service.serve", "--help"])
        assert res.returncode == 0, res.stderr[-2000:]
        assert "--storage-uri" in res.stdout and "--backend" in res.stdout

    def test_imported_modules_come_from_the_layout(self, image_tree):
        """The image tree must be self-contained — entrypoint imports must
        resolve inside /opt/lzy, not accidentally depend on repo-root
        files the Dockerfile never COPYies."""
        res = _run_in_layout(image_tree, ["-c", (
            "import lzy_tpu, lzy_tpu.service.serve, lzy_tpu.rpc.worker_main;"
            "print(lzy_tpu.__file__)")])
        assert res.returncode == 0, res.stderr[-2000:]
        assert str(image_tree) in res.stdout


class TestNativeBuildContract:
    def test_makefile_builds_and_sos_load_from_image_layout(self, image_tree):
        """Stage-1 of Dockerfile.worker: `make -C native` from a clean
        tree; stage-2 copies build/ next to the sources. The .so files
        must then load through lzy_tpu.native's <pkg>/../native/build
        resolution — the same path the pod takes."""
        make = subprocess.run(["make", "-C", str(image_tree / "native")],
                              capture_output=True, text=True, timeout=300)
        assert make.returncode == 0, make.stderr[-2000:]
        build = image_tree / "native" / "build"
        assert (build / "liblzy_slots.so").exists()
        assert (build / "liblzy_data.so").exists()
        res = _run_in_layout(image_tree, ["-c", (
            "from lzy_tpu.native import native_available;"
            "assert native_available(), 'native engine failed to load';"
            "from lzy_tpu.native.slots import SlotServer;"
            "s = SlotServer('.');"
            "assert s.port > 0; s.stop(); print('native-ok')")])
        assert res.returncode == 0, res.stderr[-2000:]
        assert "native-ok" in res.stdout


def _pip_names(dockerfile: pathlib.Path):
    """Package names from `pip install ...` lines (flags and URLs skipped)."""
    # join backslash continuations so one logical RUN is one line
    text = dockerfile.read_text().replace("\\\n", " ")
    names = []
    for line in text.splitlines():
        for m in re.finditer(r"pip install\s+([^&]*)", line):
            for tok in m.group(1).split():
                if tok.startswith("-") or "://" in tok:
                    continue
                names.append(tok.strip('"'))
    return names


class TestPipPins:
    @pytest.mark.parametrize("dockerfile", DOCKERFILES,
                             ids=[p.name for p in DOCKERFILES])
    def test_every_pip_name_is_a_real_distribution(self, dockerfile):
        """A typo'd package name would ship silently (no test builds the
        image); every name must be either installed on this host (the
        baked-in stack) or in the explicit known-absent set."""
        import importlib.metadata as md

        names = _pip_names(dockerfile)
        assert names, f"no pip install lines parsed from {dockerfile.name}"
        for name in names:
            base = re.split(r"[\[<>=!~;]", name, 1)[0]
            if name in KNOWN_ABSENT_DISTS or base in KNOWN_ABSENT_DISTS:
                continue
            try:
                md.distribution(base)
            except md.PackageNotFoundError:
                pytest.fail(
                    f"{dockerfile.name} pins {name!r} but no such "
                    f"distribution is installed here and it is not in "
                    f"KNOWN_ABSENT_DISTS — typo?")

    @pytest.mark.parametrize("dockerfile", DOCKERFILES,
                             ids=[p.name for p in DOCKERFILES])
    def test_every_copy_source_exists(self, dockerfile):
        for m in re.finditer(r"^COPY\s+(?:--from=\S+\s+)?(\S+)\s+\S+$",
                             dockerfile.read_text(), re.M):
            src = m.group(1)
            if m.group(0).startswith("COPY --from="):
                continue  # stage-internal path, not a repo path
            assert (REPO / src).exists(), \
                f"{dockerfile.name} COPYies {src} which does not exist"
