"""Worker-side environment realization: spec diff/validation, pip overlays
built from the captured spec, fail-fast conflicts, and container execution
(reference execution-env parity: ``CondaEnvironment.java:67-125`` installs the
captured env before the op; ``DockerEnvironment.java:40`` runs it in-image)."""

import os
import pathlib
import sys
import zipfile

import pytest

from lzy_tpu import op
from lzy_tpu.core.workflow import RemoteCallError
from lzy_tpu.env import (
    DockerContainer,
    EnvBuildError,
    EnvRealizer,
    LocalProcessRuntime,
    ManualPythonEnv,
)
from lzy_tpu.env.realize import applied_overlay, diff_spec, validate_spec
from lzy_tpu.service import InProcessCluster

TESTS_DIR = str(pathlib.Path(__file__).parent)
PY_VERSION = "%d.%d" % sys.version_info[:2]


def make_wheel(directory, name: str, version: str, body: str,
               requires=()) -> str:
    """Handmade minimal wheel so pip can install fully offline
    (``--no-index --find-links``); ``requires`` become Requires-Dist
    entries (for dependency-closure tests)."""
    mod = name.replace("-", "_")
    path = os.path.join(directory, f"{mod}-{version}-py3-none-any.whl")
    dist_info = f"{mod}-{version}.dist-info"
    requires_lines = "".join(f"Requires-Dist: {r}\n" for r in requires)
    with zipfile.ZipFile(path, "w") as z:
        z.writestr(f"{mod}/__init__.py", body)
        z.writestr(
            f"{dist_info}/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n"
            + requires_lines,
        )
        z.writestr(
            f"{dist_info}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n",
        )
        z.writestr(
            f"{dist_info}/RECORD",
            f"{mod}/__init__.py,,\n{dist_info}/METADATA,,\n"
            f"{dist_info}/WHEEL,,\n{dist_info}/RECORD,,\n",
        )
    return path


class TestSpecDiff:
    def test_matching_env_is_empty_diff(self):
        import pytest as _pytest  # an installed dist we know the version of

        doc = {"python_version": PY_VERSION,
               "packages": [["pytest", _pytest.__version__]]}
        assert diff_spec(doc) == []
        validate_spec(doc)  # no raise

    def test_python_version_conflict_fails_fast(self):
        doc = {"python_version": "2.7", "packages": []}
        with pytest.raises(EnvBuildError, match="requires python 2.7"):
            diff_spec(doc)

    def test_package_mismatch_is_reported_precisely(self):
        doc = {"python_version": PY_VERSION,
               "packages": [["lzy-no-such-pkg", "1.0"]]}
        assert diff_spec(doc) == [("lzy-no-such-pkg", "1.0", None)]
        with pytest.raises(EnvBuildError,
                           match=r"lzy-no-such-pkg==1.0 \(worker has nothing\)"):
            validate_spec(doc)


class TestOverlay:
    def test_realize_installs_into_cached_overlay(self, tmp_path):
        wheels = tmp_path / "wheels"
        wheels.mkdir()
        make_wheel(str(wheels), "lzy-testpkg", "2.0", "VALUE = '2.0'\n")
        realizer = EnvRealizer(
            str(tmp_path / "envs"),
            pip_args=["--no-index", "--find-links", str(wheels)],
        )
        doc = {"python_version": PY_VERSION,
               "packages": [["lzy-testpkg", "2.0"]]}
        overlay = realizer.realize(doc)
        assert overlay and os.path.isdir(os.path.join(overlay, "lzy_testpkg"))
        # cached: second call returns the same dir without re-running pip
        assert realizer.realize(doc) == overlay

        with applied_overlay(overlay):
            import lzy_testpkg

            assert lzy_testpkg.VALUE == "2.0"
        # overlay modules do not leak past the context
        assert "lzy_testpkg" not in sys.modules
        with pytest.raises(ImportError):
            import lzy_testpkg  # noqa: F401, F811

    def test_unbuildable_env_raises(self, tmp_path):
        realizer = EnvRealizer(
            str(tmp_path / "envs"),
            pip_args=["--no-index", "--find-links", str(tmp_path)],
        )
        doc = {"python_version": PY_VERSION,
               "packages": [["lzy-testpkg", "9.9"]]}
        with pytest.raises(EnvBuildError, match="pip could not"):
            realizer.realize(doc)

    def test_overlay_resolves_the_dependency_closure(self, tmp_path):
        """VERDICT r2 #7: a mismatched package whose OWN dependency also
        mismatches must arrive complete — the old --no-deps install dropped
        the dependency and import-errored at op time."""
        wheels = tmp_path / "wheels"
        wheels.mkdir()
        make_wheel(str(wheels), "lzy-deeplib", "1.5", "DEEP = 'deep-1.5'\n")
        make_wheel(
            str(wheels), "lzy-toplib", "2.0",
            "from lzy_deeplib import DEEP\nTOP = 'top-2.0+' + DEEP\n",
            requires=["lzy-deeplib"],
        )
        realizer = EnvRealizer(
            str(tmp_path / "envs"),
            pip_args=["--no-index", "--find-links", str(wheels)],
        )
        # the captured spec mentions only the package the op imported;
        # its dependency must come in through resolution
        doc = {"python_version": PY_VERSION,
               "packages": [["lzy-toplib", "2.0"]]}
        overlay = realizer.realize(doc)
        assert overlay is not None
        assert os.path.isdir(os.path.join(overlay, "lzy_toplib"))
        assert os.path.isdir(os.path.join(overlay, "lzy_deeplib"))
        with applied_overlay(overlay):
            import lzy_toplib

            assert lzy_toplib.TOP == "top-2.0+deep-1.5"
        assert "lzy_toplib" not in sys.modules

    def test_closure_never_overlays_the_accelerator_stack(self, tmp_path):
        """Even when the closure RESOLVES jax (a dependency pin), the
        overlay must not shadow the host's accelerator stack."""
        import jax as host_jax

        wheels = tmp_path / "wheels"
        wheels.mkdir()
        make_wheel(str(wheels), "jax", "0.0.1", "BOGUS = True\n")
        make_wheel(
            str(wheels), "lzy-jaxuser", "1.0", "USES_JAX = True\n",
            requires=["jax"],
        )
        realizer = EnvRealizer(
            str(tmp_path / "envs"),
            pip_args=["--no-index", "--find-links", str(wheels)],
        )
        doc = {"python_version": PY_VERSION,
               "packages": [["lzy-jaxuser", "1.0"]]}
        overlay = realizer.realize(doc)
        assert overlay is not None
        assert os.path.isdir(os.path.join(overlay, "lzy_jaxuser"))
        assert not os.path.isdir(os.path.join(overlay, "jax")), \
            "host jax must never be shadowed by an overlay"
        del host_jax


# module-level ops: worker processes resolve them by reference
@op
def read_testpkg_value() -> str:
    import lzy_testpkg

    return lzy_testpkg.VALUE


@op
def trivial_add(a: int, b: int) -> int:
    return a + b


def _pinned_env(version: str) -> ManualPythonEnv:
    return ManualPythonEnv(python_version=PY_VERSION,
                           packages={"lzy-testpkg": version})


class TestWorkerEnvRealization:
    def test_op_with_differently_pinned_package_passes(self, tmp_path,
                                                       monkeypatch):
        """The op needs lzy-testpkg==2.0, which the control plane does not
        have at all: the isolated worker builds the overlay and the op runs —
        the round-1 gap (captured env was decorative) closed."""
        wheels = tmp_path / "wheels"
        wheels.mkdir()
        make_wheel(str(wheels), "lzy-testpkg", "2.0", "VALUE = '2.0'\n")
        monkeypatch.setenv(
            "LZY_PIP_ARGS", f"--no-index --find-links {wheels}"
        )
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            worker_pythonpath=TESTS_DIR,
            poll_period_s=0.1,
        )
        try:
            lzy = c.lzy()
            with lzy.workflow("env-overlay-wf"):
                r = read_testpkg_value.with_python_env(_pinned_env("2.0"))()
                assert str(r) == "2.0"
        finally:
            c.shutdown()

    def test_env_conflict_fails_at_build_time(self, tmp_path, monkeypatch):
        """An uninstallable pin fails in env assembly with a pip message —
        before inputs are read or the function unpickled."""
        monkeypatch.setenv(
            "LZY_PIP_ARGS", f"--no-index --find-links {tmp_path}"
        )
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            worker_pythonpath=TESTS_DIR,
            poll_period_s=0.1,
        )
        try:
            lzy = c.lzy()
            with pytest.raises(RemoteCallError) as exc_info:
                with lzy.workflow("env-conflict-wf"):
                    r = read_testpkg_value.with_python_env(_pinned_env("9.9"))()
                    _ = str(r)
            # the conflict is caught at closure-resolution time (realize.py
            # resolves the full dependency closure before the overlay install)
            assert "pip could not" in repr(exc_info.value.__cause__)
            assert "testpkg==9.9" in repr(exc_info.value.__cause__)
        finally:
            c.shutdown()

    def test_shared_worker_validates_and_fails_fast(self, tmp_path):
        """Thread (shared-interpreter) workers cannot overlay; a mismatch is
        an immediate, attributable error instead of an unpickle-time one."""
        c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
        try:
            lzy = c.lzy()
            with pytest.raises(RemoteCallError) as exc_info:
                with lzy.workflow("env-validate-wf"):
                    r = trivial_add.with_python_env(_pinned_env("2.0"))(1, 2)
                    _ = int(r)
            assert "does not match the shared worker" in repr(
                exc_info.value.__cause__
            )
        finally:
            c.shutdown()


@op
def containerized_square(x: int) -> int:
    return x * x


class TestContainerExecution:
    def test_docker_argv_construction(self, tmp_path):
        from lzy_tpu.env import DockerRuntime

        calls = []
        rt = DockerRuntime(exec_fn=lambda argv, stdin=None, env=None:
                           calls.append((argv, stdin, env)) or 0)
        spec = DockerContainer(image="tpu-train:1.2", registry="eu.gcr.io/p",
                               pull_policy="always", username="bot",
                               password="hunter2")
        mod_dir = str(tmp_path / "mods")
        plan = rt.plan(spec, str(tmp_path), env={"HF_TOKEN": "secret"},
                       extra_paths=[mod_dir])
        assert plan[0][:2] == ["docker", "login"]
        # login targets the registry HOST (docker keys auth by host, not by
        # the image-path prefix) and the password never hits argv
        assert "eu.gcr.io" in plan[0] and "eu.gcr.io/p" not in plan[0]
        assert "--password-stdin" in plan[0] and "hunter2" not in " ".join(
            plan[0]
        )
        assert plan[1] == ["docker", "pull", "eu.gcr.io/p/tpu-train:1.2"]
        run = plan[2]
        assert run[:3] == ["docker", "run", "--rm"]
        assert f"{os.path.abspath(tmp_path)}:/lzy/exchange" in run
        assert f"{os.path.abspath(mod_dir)}:/lzy/mod0:ro" in run
        assert "PYTHONPATH=/lzy/pkg:/lzy/mod0" in run
        assert "eu.gcr.io/p/tpu-train:1.2" in run
        assert run[-1] == "/lzy/exchange"
        # env var by NAME only: the secret value must never hit argv
        assert "HF_TOKEN" in run and "secret" not in " ".join(run)

        rt.run_exec(spec, str(tmp_path), env={"HF_TOKEN": "secret"})
        assert [c[0][1] for c in calls] == ["login", "pull", "run"]
        assert calls[0][1] == b"hunter2"   # password via stdin, not argv
        assert calls[2][2]["HF_TOKEN"] == "secret"  # value via process env

    def test_op_runs_through_container_boundary(self, tmp_path):
        """End-to-end through the exchange-dir protocol with the local
        process runtime: same boundary as docker, no daemon needed."""
        c = InProcessCluster(db_path=str(tmp_path / "meta.db"),
                             storage_uri=f"file://{tmp_path}/storage",
                             container_runtime=LocalProcessRuntime())
        try:
            lzy = c.lzy()
            with lzy.workflow("container-wf"):
                r = containerized_square.with_container(
                    DockerContainer(image="whatever:latest")
                )(7)
                assert int(r) == 49
        finally:
            c.shutdown()

    def test_container_exception_crosses_boundary(self, tmp_path):
        @op
        def boom() -> int:
            raise ValueError("exploded in container")

        c = InProcessCluster(db_path=str(tmp_path / "meta.db"),
                             storage_uri=f"file://{tmp_path}/storage",
                             container_runtime=LocalProcessRuntime())
        try:
            lzy = c.lzy()
            with pytest.raises(RemoteCallError) as exc_info:
                with lzy.workflow("container-boom-wf"):
                    r = boom.with_container(
                        DockerContainer(image="whatever:latest")
                    )()
                    _ = int(r)
            cause = exc_info.value.__cause__
            assert isinstance(cause, ValueError)
            assert any("container traceback" in n
                       for n in getattr(cause, "__notes__", []))
        finally:
            c.shutdown()

    @pytest.mark.skipif(
        os.environ.get("LZY_DOCKER_TEST") != "1",
        reason="set LZY_DOCKER_TEST=1 on a host with a docker daemon "
               "(build docker/build.sh first, or point "
               "LZY_DOCKER_TEST_IMAGE at any image with python+cloudpickle)",
    )
    def test_op_runs_in_a_real_container(self, tmp_path):
        """The same boundary as the LocalProcessRuntime tests above, but
        executed by a REAL docker daemon with a real image — the e2e proof
        of the docker argv contract (VERDICT r2 weak #2; gated like the
        real-S3 tests in test_transfer.py)."""
        from lzy_tpu.env import DockerRuntime

        if not DockerRuntime.available():
            pytest.skip("no docker CLI on PATH")
        image = os.environ.get("LZY_DOCKER_TEST_IMAGE",
                               "lzy-tpu-worker:latest")
        c = InProcessCluster(db_path=str(tmp_path / "meta.db"),
                             storage_uri=f"file://{tmp_path}/storage",
                             container_runtime=DockerRuntime())
        try:
            lzy = c.lzy()
            with lzy.workflow("real-docker-wf"):
                r = containerized_square.with_container(
                    DockerContainer(image=image)
                )(6)
                assert int(r) == 36

            # exception path through the real container too
            @op
            def docker_boom() -> int:
                raise ValueError("exploded in a real container")

            with pytest.raises(RemoteCallError) as exc_info:
                with lzy.workflow("real-docker-boom"):
                    r = docker_boom.with_container(
                        DockerContainer(image=image)
                    )()
                    _ = int(r)
            assert isinstance(exc_info.value.__cause__, ValueError)

            from conftest import record_tier_run

            record_tier_run("docker:real_container", f"image={image}")
        finally:
            c.shutdown()

    def test_missing_runtime_is_a_clear_error(self, tmp_path):
        c = InProcessCluster(db_path=str(tmp_path / "meta.db"),
                             storage_uri=f"file://{tmp_path}/storage",
                             container_runtime=None)
        try:
            lzy = c.lzy()
            with pytest.raises(RemoteCallError) as exc_info:
                with lzy.workflow("container-none-wf"):
                    r = containerized_square.with_container(
                        DockerContainer(image="whatever:latest")
                    )(3)
                    _ = int(r)
            assert "no container runtime" in repr(exc_info.value.__cause__)
        finally:
            c.shutdown()


class TestHostProvidedAndCredHygiene:
    def test_accelerator_stack_is_never_overlaid(self):
        import jax

        doc = {"python_version": PY_VERSION,
               "packages": [["jax", "0.0.1"], ["jaxlib", "0.0.1"],
                            ["libtpu", "0.0.1"]]}
        # version drift in host-provided packages is ignored, not a conflict
        assert diff_spec(doc) == []
        validate_spec(doc)
        assert jax.__version__ != "0.0.1"  # really would have mismatched

    def test_registry_credentials_never_enter_task_docs(self):
        from lzy_tpu.env.container_runtime import container_to_doc

        doc = container_to_doc(DockerContainer(
            image="x:1", registry="eu.gcr.io/p", username="bot",
            password="hunter2",
        ))
        assert "password" not in doc and "username" not in doc
        assert doc["image"] == "x:1" and doc["registry"] == "eu.gcr.io/p"


class TestCondaRealizer:
    """The consumer of ``to_conda_yaml()`` (VERDICT r3 missing #1): a fake
    conda binary exercises the create-or-update logic on any host; the
    real-conda e2e below is gated on a conda binary existing."""

    def _fake_conda(self, tmp_path, *, fail_create=False,
                    fail_everything=False):
        """A stub 'conda' that records argv and materializes bin/python
        under --prefix, like the real thing would."""
        log = tmp_path / "conda-calls.log"
        script = tmp_path / "conda"
        script.write_text(f"""#!/bin/sh
echo "$@" >> {log}
case "$*" in
  *" create "*|*"env create"*)
    {"exit 1" if (fail_create or fail_everything) else ""}
    ;;
  *" update "*|*"env update"*)
    {"exit 1" if fail_everything else ""}
    ;;
esac
prefix=""
prev=""
for a in "$@"; do
  if [ "$prev" = "--prefix" ]; then prefix="$a"; fi
  prev="$a"
done
if [ -n "$prefix" ]; then
  mkdir -p "$prefix/bin"
  : > "$prefix/bin/python"
  chmod +x "$prefix/bin/python"
fi
exit 0
""")
        script.chmod(0o755)
        return str(script), log

    def test_create_realizes_env_and_returns_interpreter(self, tmp_path):
        from lzy_tpu.env.realize import CondaRealizer

        conda, log = self._fake_conda(tmp_path)
        r = CondaRealizer(str(tmp_path / "envs"), conda_exe=conda)
        doc = {"python_version": "3.9", "packages": [["requests", "2.0.0"]]}
        python = r.realize(doc)
        assert python.endswith("bin/python") and os.path.exists(python)
        calls = log.read_text().splitlines()
        assert len(calls) == 1 and "env create" in calls[0]
        # the yaml it consumed is the captured spec's conda yaml
        name = r.env_name(doc)
        yaml = (tmp_path / "envs" / f"{name}.yaml").read_text()
        assert "python==3.9" in yaml and "requests==2.0.0" in yaml
        # cached: a second realize is a no-op (marker short-circuits)
        assert r.realize(doc) == python
        assert len(log.read_text().splitlines()) == 1

    def test_create_failure_falls_back_to_update(self, tmp_path):
        from lzy_tpu.env.realize import CondaRealizer

        conda, log = self._fake_conda(tmp_path, fail_create=True)
        r = CondaRealizer(str(tmp_path / "envs"), conda_exe=conda)
        python = r.realize({"python_version": "3.9", "packages": []})
        assert os.path.exists(python)
        calls = log.read_text().splitlines()
        assert "env create" in calls[0] and "env update" in calls[1]

    def test_unbuildable_env_fails_fast(self, tmp_path):
        from lzy_tpu.env.realize import CondaRealizer, EnvBuildError

        conda, _ = self._fake_conda(tmp_path, fail_everything=True)
        r = CondaRealizer(str(tmp_path / "envs"), conda_exe=conda)
        with pytest.raises(EnvBuildError, match="conda could not realize"):
            r.realize({"python_version": "3.9", "packages": []})

    def test_no_conda_binary_is_a_clear_error(self, tmp_path, monkeypatch):
        from lzy_tpu.env import realize as mod

        monkeypatch.setattr(mod, "find_conda", lambda: None)
        with pytest.raises(mod.EnvBuildError, match="no conda"):
            mod.CondaRealizer(str(tmp_path / "envs"))

    def test_cli_prints_interpreter_path(self, tmp_path):
        import json as _json
        import subprocess as sp
        import sys as _sys

        conda, _ = self._fake_conda(tmp_path)
        spec = tmp_path / "spec.json"
        spec.write_text(_json.dumps(
            {"python_version": "3.9", "packages": []}))
        proc = sp.run(
            [_sys.executable, "-m", "lzy_tpu.env.realize",
             "--conda-root", str(tmp_path / "envs"),
             "--conda-exe", conda, str(spec)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip().endswith("bin/python")

    @pytest.mark.skipif(
        __import__("lzy_tpu.env.realize", fromlist=["find_conda"])
        .find_conda() is None,
        reason="no conda/mamba/micromamba on this host")
    def test_real_conda_env_create_from_emitted_yaml(self, tmp_path):
        """Real-conda e2e (CondaEnvironment.java:67-125 parity): realize a
        tiny env from the emitted yaml and run its interpreter."""
        import subprocess as sp

        from lzy_tpu.env.realize import CondaRealizer

        r = CondaRealizer(str(tmp_path / "envs"))
        doc = {"python_version": "%d.%d" % __import__("sys").version_info[:2],
               "packages": []}
        python = r.realize(doc)
        out = sp.run([python, "-c", "print('conda-env-ok')"],
                     capture_output=True, text=True, timeout=300)
        assert out.returncode == 0 and "conda-env-ok" in out.stdout

        from conftest import record_tier_run

        record_tier_run("conda:real_env_create", python)
