"""Sharded gang replicas: bit-identity, fence contract, gang failover.

The acceptance bar for ``lzy_tpu/serving/sharded``: a 1×2 CPU-mesh gang
must be indistinguishable from the single-device ``PagedInferenceEngine``
through every contract the serving stack pins —

- **bit-identity** against both the ``generate()`` oracle and a
  single-device engine: greedy, sampled (same rng draw order), spec
  verify under forced full-acceptance/full-rejection, and chunked
  prefill. These strict bitwise tests run with ``dtype=float32``: the
  no-sharded-contractions placement keeps operand order exact, but under
  bf16 compute the differently-partitioned program fuses (and therefore
  rounds) at different points — 1-ULP logit noise that can flip argmax
  on near-ties. bf16 streams are pinned by the fixed-seed determinism
  test instead (see the ``partition`` module docstring);
- **one fence per round**: ``host_fetches`` advances by exactly 1 per
  steady-state decode round and the counting-``np`` shim sees no
  device→host conversion outside ``_fetch`` — the emit matrix is
  replicated before it crosses, so the gang pays the same single sync;
- **sharded pool, shared table**: per-shard occupancy is uniform by
  construction and the skew gauge reads 0;
- **cross-replica KV**: a gang's export stamps its mesh shape, imports
  are geometry-exact (fail closed into a differently-shaped pool),
  unsharded exports still import anywhere;
- **gang failure is whole-gang failure**: one dead host mid-stream fails
  the replica over with fenced tokens kept, through a mixed fleet of one
  gang and one single-device replica.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.gateway import (
    GatewayService, PrefixAffinityRouter, ReplicaFleet, RoundRobinRouter)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import PagedInferenceEngine
from lzy_tpu.serving import engine as engine_mod
from lzy_tpu.serving.disagg.kv_export import export_kv, import_kv
from lzy_tpu.serving.sharded import ShardedPagedInferenceEngine
from lzy_tpu.serving.sharded import metrics as _m

VOCAB = 64
PAGE = 16


PROMPTS = [
    [5, 9, 3, 7, 2],
    [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3, 4],
]


@pytest.fixture(scope="module")
def tiny_model():
    """f32 compute: the strict bitwise fixture (see module docstring).
    param_dtype is float32 either way, so the same param tree also
    drives the bf16-compute determinism test."""
    if len(jax.devices()) < 2:
        pytest.skip("sharded serving needs >= 2 devices")
    cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=VOCAB),
                              dtype=jnp.float32)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drain(engine, reqs, rounds=800):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        engine.step()
    raise AssertionError("engine did not finish its requests")


def _run(engine, prompt, n):
    req = engine.submit(prompt, max_new_tokens=n)
    _drain(engine, [req])
    return req.result()


def _reach_steady_decode(eng, reqs, rounds=200):
    for _ in range(rounds):
        if (not eng._prefill_jobs and eng.queue.depth() == 0
                and sum(r is not None for r in eng._active) == len(reqs)):
            return
        eng.step()
    raise AssertionError("requests never reached steady decode")


class _OracleProposer:
    """Drafts the model's actual greedy continuation: full acceptance."""

    def __init__(self, seqs, gamma):
        self.seqs = [list(map(int, s)) for s in seqs]
        self.gamma = gamma

    def propose(self, tokens):
        t = list(tokens)
        for s in self.seqs:
            if len(s) > len(t) and s[:len(t)] == t:
                return s[len(t):len(t) + self.gamma]
        return []


class _AdversarialProposer(_OracleProposer):
    """Drafts tokens guaranteed wrong: full rejection every round."""

    def propose(self, tokens):
        return [(t + 1) % VOCAB for t in super().propose(tokens)]


class _CountingNp:
    """Transfer shim: counts ``asarray``/``array`` calls whose argument
    is a device array — every device→host conversion in engine code."""

    def __init__(self, real):
        self._real = real
        self.device_fetches = 0

    def __getattr__(self, name):
        return getattr(self._real, name)

    def _counting(self, fn, a, *args, **kw):
        if isinstance(a, jax.Array):
            self.device_fetches += 1
        return fn(a, *args, **kw)

    def asarray(self, a, *args, **kw):
        return self._counting(self._real.asarray, a, *args, **kw)

    def array(self, a, *args, **kw):
        return self._counting(self._real.array, a, *args, **kw)


def _gang(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", PAGE)
    return ShardedPagedInferenceEngine(cfg, params, tp=2, **kw)


@pytest.fixture(scope="module")
def gang(tiny_model):
    """The shared 1×2 gang. prefill_chunk=8 so every prompt here takes
    the chunked-prefill path — chunking must change scheduling only."""
    cfg, params = tiny_model
    eng = _gang(cfg, params, prefill_chunk=8)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def baseline(tiny_model):
    """The single-device twin of ``gang`` (same slots/page/chunking)."""
    cfg, params = tiny_model
    eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                               prefill_chunk=8)
    yield eng
    eng.close()


class TestConstruction:
    def test_tp_divisibility_gate(self, tiny_model):
        # tiny has n_kv_heads=2: a 1×4 gang would need padded kv-head
        # shards, which changes reduction extents — refused up front
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="not divisible by tp=4"):
            ShardedPagedInferenceEngine(cfg, params, tp=4)

    def test_gang_needs_tp_at_least_2(self, tiny_model):
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="tp >= 2"):
            ShardedPagedInferenceEngine(cfg, params, tp=1)

    def test_pallas_kernel_rejected(self, tiny_model):
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="pallas"):
            ShardedPagedInferenceEngine(cfg, params, tp=2,
                                        kernel="pallas")


class TestBitIdentity:
    def test_greedy_matches_oracle_and_single_engine(
            self, tiny_model, gang, baseline):
        cfg, params = tiny_model
        for prompt in PROMPTS:
            exp = _oracle(cfg, params, prompt, 24)
            assert _run(baseline, prompt, 24) == exp
            assert _run(gang, prompt, 24) == exp

    def test_chunked_prefill_long_prompt(self, tiny_model, gang,
                                         baseline):
        # 20 prompt tokens through prefill_chunk=8 → a 3-chunk plan on
        # both engines; the oracle prefills one-shot — all three equal
        cfg, params = tiny_model
        prompt = list(range(1, 21))
        exp = _oracle(cfg, params, prompt, 12)
        assert _run(baseline, prompt, 12) == exp
        assert _run(gang, prompt, 12) == exp

    def test_sampled_rng_draw_order_matches_single_engine(
            self, tiny_model):
        cfg, params = tiny_model
        kw = dict(temperature=0.8, top_k=20, seed=7)
        solo = PagedInferenceEngine(cfg, params, slots=2,
                                    page_size=PAGE, **kw)
        eng = _gang(cfg, params, **kw)
        try:
            for prompt in ([5, 9, 3], [2, 4, 6, 8]):
                assert _run(eng, prompt, 12) == _run(solo, prompt, 12)
        finally:
            solo.close()
            eng.close()

    @pytest.mark.parametrize("accept", [True, False])
    def test_spec_verify_matches_oracle(self, tiny_model, accept):
        cfg, params = tiny_model
        n, gamma = 24, 3
        prompt = PROMPTS[1]
        exp = _oracle(cfg, params, prompt, n)
        cls = _OracleProposer if accept else _AdversarialProposer
        eng = _gang(cfg, params, spec_tokens=gamma,
                    proposer=cls([prompt + exp], gamma))
        try:
            req = eng.submit(prompt, max_new_tokens=n)
            _drain(eng, [req])
            assert req.result() == exp
            s = eng.stats()
            if accept:
                assert s.spec_acceptance_rate == 1.0
                assert eng.decode_steps < n - 1
            else:
                assert s.spec_proposed_tokens > 0
                assert s.spec_accepted_tokens == 0
        finally:
            eng.close()

    def test_bf16_stream_fixed_seed_deterministic(self, tiny_model):
        """The bf16 half of the contract: strict cross-program identity
        is out of reach (fusion-boundary rounding), but one gang's
        stream is deterministic — a re-run of the same prompt (now on
        the radix-cached prefix path) reproduces it bit-for-bit."""
        _, params = tiny_model
        cfg = LlamaConfig.tiny(vocab_size=VOCAB)   # bf16 compute
        eng = _gang(cfg, params)
        try:
            first = _run(eng, PROMPTS[0], 16)
            assert _run(eng, PROMPTS[0], 16) == first
        finally:
            eng.close()


class TestOneFencePerRound:
    def test_one_fetch_per_steady_decode_round(self, gang):
        reqs = [gang.submit(p, max_new_tokens=40) for p in PROMPTS]
        _reach_steady_decode(gang, reqs)
        for _ in range(8):
            before = gang.host_fetches
            assert gang.step()
            assert gang.host_fetches == before + 1
        _drain(gang, reqs)

    def test_shim_sees_no_fetch_outside_the_fence(self, gang,
                                                  monkeypatch):
        reqs = [gang.submit(p, max_new_tokens=40) for p in PROMPTS]
        _reach_steady_decode(gang, reqs)
        shim = _CountingNp(np)
        monkeypatch.setattr(engine_mod, "np", shim)
        rounds = 8
        before = gang.host_fetches
        for _ in range(rounds):
            assert gang.step()
        assert gang.host_fetches - before == rounds
        assert shim.device_fetches == rounds
        monkeypatch.undo()
        _drain(gang, reqs)

    def test_shard_occupancy_uniform_and_skew_zero(self, gang):
        reqs = [gang.submit(p, max_new_tokens=8) for p in PROMPTS]
        _reach_steady_decode(gang, reqs)
        occ = gang.shard_occupancy()
        assert len(occ) == 2
        assert occ[0] == occ[1] > 0
        gang.stats()                       # refreshes the gauges
        key = (("mesh", "1x2"),)
        assert _m.SHARD_SKEW._values[key] == 0.0
        assert _m.SHARD_KV_BLOCKS._values[key + (("shard", "0"),)] \
            == float(occ[0])
        _drain(gang, reqs)


class TestShardedKVTransfer:
    def test_gang_export_is_geometry_stamped_and_exact(
            self, tiny_model, gang, baseline):
        """A gang's KV export names its pool geometry; a same-shape gang
        imports it and serves the continuation bit-identically, while a
        differently-shaped pool fails closed (import skipped, local
        re-prefill — never garbage)."""
        cfg, params = tiny_model
        prompt = [3, 1, 4, 1, 5, 9, 2, 6] * 4          # 2 full pages
        out = _run(gang, prompt, 8)
        export = export_kv(gang, prompt)
        assert export is not None
        assert tuple(export.mesh_shape) == (1, 2)
        assert export.n_blocks == 2

        # geometry-exact import into a fresh 1×2 gang
        sibling = _gang(cfg, params)
        try:
            assert import_kv(sibling, export) == 2
            assert _run(sibling, prompt, 8) == out
        finally:
            sibling.close()

        # fail closed into the single-device pool (mesh (1,2) ≠ none)
        assert import_kv(baseline, export) == 0
        # ...which costs nothing but a local re-prefill
        assert _run(baseline, prompt, 8) == out

    def test_unsharded_export_imports_into_a_gang(self, tiny_model,
                                                  baseline):
        """mesh_shape=None manifests predate gangs and import anywhere:
        the scatter follows the destination pool's placement."""
        cfg, params = tiny_model
        prompt = [7, 7, 2, 9, 1, 8, 3, 5] * 4
        out = _run(baseline, prompt, 8)
        export = export_kv(baseline, prompt)
        assert export is not None and export.mesh_shape is None
        eng = _gang(cfg, params)
        try:
            assert import_kv(eng, export) == 2
            assert _run(eng, prompt, 8) == out
        finally:
            eng.close()


def _mixed_gateway(cfg, params, *, kinds, router=None, **engine_kw):
    """A fleet mixing gang and single-device replicas: ``kinds`` is the
    factory schedule, one entry per ``add_replica`` in order."""
    schedule = iter(kinds)

    def factory():
        if next(schedule) == "gang":
            return _gang(cfg, params, **engine_kw)
        return PagedInferenceEngine(cfg, params, slots=2,
                                    page_size=PAGE, **engine_kw)

    fleet = ReplicaFleet(factory, start_engines=True)
    gw = GatewayService(fleet, router=router or RoundRobinRouter(),
                        model_name="tiny")
    for _ in kinds:
        fleet.add_replica()
    return gw, fleet


class TestMixedFleet:
    def test_routing_across_gang_and_single_device(self, tiny_model):
        """One gang + one single-device replica behind one gateway:
        round-robin routing lands requests on both, and every reply is
        bit-identical to the oracle regardless of which served it."""
        cfg, params = tiny_model
        gw, fleet = _mixed_gateway(cfg, params, kinds=("gang", "single"))
        try:
            gangs = {r.id for r in fleet.replicas()
                     if getattr(r.engine, "gang_size", 1) > 1}
            assert len(gangs) == 1
            served = set()
            for i in range(4):
                prompt = [3 + i, 5, 7]
                res = gw.generate(prompt, max_new_tokens=6,
                                  timeout_s=120)
                assert res["status"] == "ok" and res["failovers"] == 0
                assert res["tokens"] == _oracle(cfg, params, prompt, 6)
                served.add(res["replica"])
            assert len(served) == 2        # both replica kinds served
        finally:
            gw.close()

    def test_gang_host_death_mid_stream_fails_over_whole(
            self, tiny_model):
        """Kill ONE shard host of the gang mid-decode: the whole gang
        dies (no partial-gang mode), the stream fails over to the
        single-device sibling with the fenced tokens kept, and the
        gang-failover counter ticks."""
        cfg, params = tiny_model
        gw, fleet = _mixed_gateway(cfg, params, kinds=("gang", "single"))
        result = {}

        def run():
            try:
                result["res"] = gw.generate([7, 2, 8, 1],
                                            max_new_tokens=24,
                                            timeout_s=120)
            except BaseException as e:
                result["err"] = e

        failovers_before = _m.GANG_FAILOVERS._values.get((), 0.0)
        try:
            t = threading.Thread(target=run)
            t.start()
            victim = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                for replica in fleet.replicas():
                    if getattr(replica.engine, "gang_size", 1) <= 1:
                        continue
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim = replica
                        break
                if victim:
                    break
                time.sleep(0.005)
            assert victim is not None, \
                "request never reached mid-decode on the gang"

            victim.engine.mark_host_dead(0, "host unreachable")
            assert victim.engine.gang_intact is False
            t.join(120)
            assert "err" not in result, result.get("err")
            res = result["res"]
            assert res["tokens"] == _oracle(cfg, params, [7, 2, 8, 1], 24)
            assert res["failovers"] == 1 and res["status"] == "ok"
            # the whole gang retired; only the single-device replica is
            # left routing
            ids = [r.id for r in fleet.replicas()]
            assert victim.id not in ids and len(ids) == 1
            assert _m.GANG_FAILOVERS._values.get((), 0.0) == \
                failovers_before + 1.0
        finally:
            gw.close()
