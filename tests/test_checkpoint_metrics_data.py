"""Checkpointing, metrics, and data-pipeline tests."""

import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lzy_tpu.data import DataPipeline, synthetic_lm_batches
from lzy_tpu.parallel import (
    CheckpointManager,
    TrainState,
    fsdp_mesh,
    make_train_step,
    named_sharding,
)
from lzy_tpu.storage import MemStorageClient
from lzy_tpu.utils.metrics import MetricsRegistry


class TestCheckpoint:
    def _manager(self, **kwargs):
        return CheckpointManager(
            MemStorageClient(), "mem://ckpt", "model", **kwargs
        )

    def _state(self, seed=0):
        params = {
            "w": jnp.full((8, 8), float(seed), jnp.bfloat16),
            "b": jnp.zeros((8,)),
        }
        tx = optax.adam(1e-3)
        return TrainState.create(params, tx)

    def test_save_restore_roundtrip(self):
        mgr = self._manager()
        state = self._state(seed=3)
        mgr.save(state, step=10, metrics={"loss": 1.5})
        assert mgr.latest_step() == 10
        restored = mgr.restore()
        assert restored.params["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"], np.float32),
            np.full((8, 8), 3.0),
        )
        assert int(restored.step) == 0
        assert mgr.manifest(10)["metrics"]["loss"] == 1.5

    def test_restore_with_shardings(self):
        mesh = fsdp_mesh()
        mgr = self._manager()
        mgr.save(self._state(), step=1)
        sh = named_sharding(mesh, None, None)
        restored = mgr.restore(
            shardings=TrainState(
                step=NamedSharding(mesh, P()),
                params={"w": named_sharding(mesh, "embed", None),
                        "b": NamedSharding(mesh, P())},
                opt_state=jax.tree_util.tree_map(
                    lambda _: NamedSharding(mesh, P()),
                    self._state().opt_state,
                ),
            )
        )
        assert restored.params["w"].sharding.spec == P("fsdp", None)

    def test_retention_keeps_last_n(self):
        mgr = self._manager(keep=2)
        for step in (1, 2, 3, 4):
            mgr.save(self._state(), step=step)
        assert mgr.steps() == [3, 4]
        assert mgr.latest_step() == 4
        with pytest.raises(FileNotFoundError):
            mgr.restore(step=1)

    def test_async_save(self):
        mgr = self._manager()
        mgr.save(self._state(), step=5, blocking=False)
        mgr.wait()
        assert mgr.latest_step() == 5

    def test_async_save_failure_surfaces_on_wait(self):
        class BrokenClient(MemStorageClient):
            def write(self, uri, src):
                raise OSError("bucket gone")

        mgr = CheckpointManager(BrokenClient(), "mem://b", "m")
        mgr.save(self._state(), step=1, blocking=False)
        with pytest.raises(RuntimeError, match="async checkpoint save failed"):
            mgr.wait()

    def test_restore_missing_raises(self):
        with pytest.raises(FileNotFoundError, match="no checkpoints"):
            self._manager().restore()

    def test_train_resume_continuity(self):
        """Save mid-training, restore, and continue: the restored run must
        produce the same loss as the uninterrupted one."""
        mesh = fsdp_mesh()

        def loss_fn(p, batch):
            return jnp.mean((batch["x"] @ p["w"]) ** 2)

        tx = optax.sgd(0.1)
        step, shard_state, _ = make_train_step(
            loss_fn, tx, mesh=mesh,
            param_logical_axes={"w": (None, None)},
            batch_logical_axes=("batch",),
        )
        batch = {"x": jnp.ones((8, 4))}
        state = shard_state(TrainState.create({"w": jnp.ones((4, 2))}, tx))
        mgr = self._manager()

        state, _ = step(state, batch)
        mgr.save(state, step=1)
        state, m_direct = step(state, batch)

        restored = shard_state(mgr.restore(step=1))
        _, m_resumed = step(restored, batch)
        np.testing.assert_allclose(
            float(m_direct["loss"]), float(m_resumed["loss"]), rtol=1e-6
        )


class TestMetrics:
    def test_counter_gauge_histogram_exposition(self):
        reg = MetricsRegistry()
        reg.counter("lzy_tasks_total", "tasks").inc(pool="cpu-small")
        reg.counter("lzy_tasks_total").inc(2, pool="tpu-v5e-16")
        reg.gauge("lzy_vms", "live vms").set(3, status="RUNNING")
        reg.histogram("lzy_alloc_seconds", "alloc latency").observe(0.3)
        text = reg.exposition()
        assert 'lzy_tasks_total{pool="cpu-small"} 1.0' in text
        assert 'lzy_tasks_total{pool="tpu-v5e-16"} 2.0' in text
        assert 'lzy_vms{status="RUNNING"} 3' in text
        assert 'lzy_alloc_seconds_bucket{le="0.5"} 1' in text
        assert "lzy_alloc_seconds_count 1" in text

    def test_type_conflict_rejected(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_timer_context(self):
        reg = MetricsRegistry()
        hist = reg.histogram("t", buckets=(0.05, 1.0))
        with hist.time(op="sleep"):
            time.sleep(0.01)
        assert 't_bucket{op="sleep",le="1.0"} 1' in reg.exposition()

    def test_http_exposition(self):
        reg = MetricsRegistry()
        reg.counter("served_total").inc()
        server = reg.serve()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ).read().decode()
            assert "served_total 1.0" in body
        finally:
            server.stop()


class TestDataPipeline:
    def test_batches_sharded_and_ordered(self):
        mesh = fsdp_mesh()
        sharding = named_sharding(mesh, "batch", None)
        source = ({"tokens": np.full((8, 4), i, np.int32)} for i in range(5))
        seen = []
        for batch in DataPipeline(source, sharding, prefetch=2):
            assert batch["tokens"].sharding.spec == P(("dp", "fsdp"), None)
            seen.append(int(batch["tokens"][0, 0]))
        assert seen == [0, 1, 2, 3, 4]

    def test_source_error_propagates(self):
        def bad():
            yield {"x": np.zeros((8,))}
            raise ValueError("source died")

        mesh = fsdp_mesh()
        pipe = DataPipeline(bad(), named_sharding(mesh, "batch"))
        it = iter(pipe)
        next(it)
        with pytest.raises(ValueError, match="source died"):
            next(it)

    def test_early_break_stops_feeder(self):
        """Breaking out of iteration must unblock and stop the feeder thread
        (no leaked threads holding device batches)."""
        mesh = fsdp_mesh()
        before = {t.name for t in threading.enumerate()}
        source = ({"x": np.zeros((8, 4))} for _ in range(1000))
        for i, _ in enumerate(DataPipeline(source, named_sharding(mesh, "batch", None))):
            if i == 1:
                break
        deadline = time.time() + 5
        while time.time() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t.name == "data-pipeline" and t.name not in before]
            if not leaked:
                break
            time.sleep(0.05)
        assert not [t for t in threading.enumerate()
                    if t.name == "data-pipeline"], "feeder thread leaked"

    def test_synthetic_lm_batches_deterministic(self):
        a = list(synthetic_lm_batches(batch_size=2, seq_len=4, vocab_size=10,
                                      n_batches=3, seed=7))
        b = list(synthetic_lm_batches(batch_size=2, seq_len=4, vocab_size=10,
                                      n_batches=3, seed=7))
        assert len(a) == 3
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x["tokens"], y["tokens"])


class TestShardedCheckpoint:
    """Multi-host-correct checkpoints: every GLOBAL shard written exactly
    once by its replica-0 holder, manifest published after a barrier,
    restore reads only the shards the target sharding needs (with a
    full-assembly fallback for resharded restores)."""

    def make_mesh(self, shape, names):
        import numpy as np
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()).reshape(shape), names)

    def test_round_trip_and_reshard(self):
        import numpy as np

        from jax.sharding import NamedSharding, PartitionSpec as P

        from lzy_tpu.parallel.checkpoint import CheckpointManager
        from lzy_tpu.storage.mem import MemStorageClient

        mesh = self.make_mesh((4, 2), ("dp", "tp"))
        sh = NamedSharding(mesh, P("dp", "tp"))
        rep = NamedSharding(mesh, P())
        state = {
            "w": jax.device_put(jnp.arange(64.0).reshape(8, 8), sh),
            "b": jax.device_put(jnp.float32(3.5), rep),
        }
        client = MemStorageClient()
        mgr = CheckpointManager(client, "mem://ck", "m")
        mgr.save_sharded(state, 7, metrics={"loss": 1.0})
        assert mgr.latest_step() == 7
        assert mgr.manifest(7)["sharded"] is True

        # 8 distinct shards for w (4x2 partitioning), ONE object for the
        # replicated scalar — replica dedup wrote each global shard once
        shard_uris = list(client.list("mem://ck/lzy_checkpoints/m/"))
        w_shards = [u for u in shard_uris if "/shards/" in u and "w" in u]
        b_shards = [u for u in shard_uris if "/shards/" in u and "b" in u]
        assert len(w_shards) == 8 and len(b_shards) == 1

        out = mgr.restore_sharded({"w": sh, "b": rep})
        np.testing.assert_array_equal(
            np.asarray(out["w"]), np.arange(64.0).reshape(8, 8))
        assert float(out["b"]) == 3.5 and out["w"].sharding == sh

        # restore under a DIFFERENT layout exercises the assemble fallback
        mesh2 = self.make_mesh((2, 4), ("dp", "tp"))
        sh2 = NamedSharding(mesh2, P("tp", "dp"))
        out2 = mgr.restore_sharded({"w": sh2,
                                    "b": NamedSharding(mesh2, P())})
        np.testing.assert_array_equal(
            np.asarray(out2["w"]), np.arange(64.0).reshape(8, 8))
        assert out2["w"].sharding == sh2


class TestResumableSource:
    def make(self, n=20, bs=4, **kw):
        import numpy as np

        from lzy_tpu.data import array_source

        data = {"x": np.arange(n * 2).reshape(n, 2)}
        return array_source(data, batch_size=bs, seed=7, **kw)

    def test_resume_continues_exactly(self):
        import numpy as np

        src = self.make()
        it = iter(src)
        consumed = [next(it) for _ in range(7)]   # into epoch 2
        resume_state = src.state()

        fresh = self.make(state=resume_state)
        a, b = next(iter(fresh)), next(it)
        np.testing.assert_array_equal(a["x"], b["x"])
        # and the one after that, across the epoch boundary too
        it_fresh = iter(fresh)
        for _ in range(3):
            x, y = next(it_fresh), next(it)
            np.testing.assert_array_equal(x["x"], y["x"])
        assert consumed  # silence linters

    def test_state_points_past_the_held_batch(self):
        """A checkpoint written AFTER training on batch k must resume at
        k+1 — never replay k."""
        import numpy as np

        src = self.make(shuffle=False)
        it = iter(src)
        first = next(it)
        resumed = next(iter(self.make(shuffle=False, state=src.state())))
        assert not np.array_equal(first["x"], resumed["x"])
        np.testing.assert_array_equal(resumed["x"], next(iter(
            self.make(shuffle=False, state={"epoch": 0, "batch": 1,
                                            "seed": 7})))["x"])

    def test_epochs_reshuffle_but_cover_everything(self):
        import numpy as np

        src = self.make(n=16, bs=4, epochs=2)
        seen = [b["x"][:, 0] // 2 for b in src]
        assert len(seen) == 8                      # 4 batches x 2 epochs
        e0, e1 = np.sort(np.concatenate(seen[:4])), np.sort(
            np.concatenate(seen[4:]))
        np.testing.assert_array_equal(e0, np.arange(16))
        np.testing.assert_array_equal(e1, np.arange(16))
        assert not np.array_equal(np.concatenate(seen[:4]),
                                  np.concatenate(seen[4:]))

    def test_host_shards_are_disjoint_and_complete(self):
        import numpy as np

        parts = []
        for rank in range(2):
            src = self.make(n=16, bs=4, epochs=1, shard_index=rank,
                            shard_count=2)
            parts.append(np.concatenate(
                [b["x"][:, 0] // 2 for b in src]))
        allv = np.concatenate(parts)
        assert len(allv) == 16 and len(set(allv.tolist())) == 16

    def test_seed_mismatch_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="seed"):
            self.make(state={"epoch": 0, "batch": 0, "seed": 99})

    def test_data_state_travels_with_checkpoints(self):
        from lzy_tpu.parallel import CheckpointManager
        from lzy_tpu.storage.mem import MemStorageClient

        src = self.make()
        it = iter(src)
        for _ in range(3):
            next(it)
        mgr = CheckpointManager(MemStorageClient(), "mem://dck", "m")
        mgr.save({"w": jnp.ones(4)}, 3, data_state=src.state())
        assert mgr.data_state() == src.state()
        resumed = self.make(state=mgr.data_state())
        import numpy as np

        np.testing.assert_array_equal(next(iter(resumed))["x"],
                                      next(it)["x"])


class TestResumableSourceHardening:
    def test_zero_batch_config_rejected(self):
        import pytest as _pytest

        from lzy_tpu.data import ResumableSource

        with _pytest.raises(ValueError, match="no batches per epoch"):
            ResumableSource(8, lambda idx: idx, batch_size=16,
                            shard_index=0, shard_count=8)

    def test_config_change_rejected_on_restore(self):
        import numpy as np
        import pytest as _pytest

        from lzy_tpu.data import array_source

        data = {"x": np.arange(40).reshape(20, 2)}
        src = array_source(data, batch_size=4, seed=7)
        state = src.state()
        with _pytest.raises(ValueError, match="differently-configured"):
            array_source(data, batch_size=8, seed=7, state=state)
        with _pytest.raises(ValueError, match="differently-configured"):
            array_source(data, batch_size=4, seed=7, shard_index=1,
                         shard_count=2, state=state)

    def test_concurrent_iterators_rejected(self):
        import numpy as np
        import pytest as _pytest

        from lzy_tpu.data import array_source

        src = array_source({"x": np.arange(16)}, batch_size=4)
        a = iter(src)
        next(a)
        b = iter(src)       # takes over
        next(b)
        with _pytest.raises(RuntimeError, match="newer iterator"):
            next(a)

    def test_pipeline_tracks_consumer_not_feeder(self):
        """With prefetch ahead, the checkpointable position must be the
        batch the TRAIN LOOP saw last — not the feeder's lookahead."""
        import numpy as np

        from lzy_tpu.data import DataPipeline, array_source

        n, bs = 32, 4
        data = {"x": np.arange(n)}
        src = array_source(data, batch_size=bs, shuffle=False)
        sharding = jax.devices()[0]
        pipe = DataPipeline(src, sharding, prefetch=4)
        it = iter(pipe)
        seen = [np.asarray(next(it)["x"]) for _ in range(2)]
        import time as _t

        _t.sleep(0.3)       # let the feeder run ahead
        state = pipe.data_state()
        assert state is not None and state["batch"] == 2   # consumer position
        resumed = array_source(data, batch_size=bs, shuffle=False,
                               state=state)
        np.testing.assert_array_equal(next(iter(resumed))["x"],
                                      np.arange(8, 12))
        np.testing.assert_array_equal(seen[0], np.arange(0, 4))


class TestKeepBest:
    def test_best_checkpoint_survives_recency_gc(self, tmp_path):
        """keep=1 recency window + keep_best=1: the lowest-loss step stays
        even after newer (worse) saves age it out of the window."""
        from lzy_tpu.parallel.checkpoint import CheckpointManager
        from lzy_tpu.storage import StorageConfig, client_for

        client = client_for(StorageConfig(uri=f"file://{tmp_path}/s"))
        mgr = CheckpointManager(client, f"file://{tmp_path}/s", "run",
                                keep=1, keep_best=1, best_metric="loss")
        state = {"w": jnp.ones((4,))}
        losses = {10: 3.0, 20: 1.0, 30: 2.5, 40: 2.0}
        for step, loss in sorted(losses.items()):
            mgr.save(state, step, metrics={"loss": loss})
        # recency keeps 40; best keeps 20 (loss 1.0); the rest are reaped
        assert mgr.steps() == [20, 40]
        assert mgr.manifest(20)["metrics"]["loss"] == 1.0
        # and the best one restores
        restored = mgr.restore(step=20)
        assert jnp.allclose(restored["w"], state["w"])

    def test_best_mode_max(self, tmp_path):
        from lzy_tpu.parallel.checkpoint import CheckpointManager
        from lzy_tpu.storage import StorageConfig, client_for

        client = client_for(StorageConfig(uri=f"file://{tmp_path}/s"))
        mgr = CheckpointManager(client, f"file://{tmp_path}/s", "run",
                                keep=1, keep_best=1, best_metric="acc",
                                best_mode="max")
        for step, acc in ((1, 0.5), (2, 0.9), (3, 0.6), (4, 0.7)):
            mgr.save({"w": jnp.zeros(2)}, step, metrics={"acc": acc})
        assert mgr.steps() == [2, 4]

    def test_metricless_saves_never_count_as_best(self, tmp_path):
        from lzy_tpu.parallel.checkpoint import CheckpointManager
        from lzy_tpu.storage import StorageConfig, client_for

        client = client_for(StorageConfig(uri=f"file://{tmp_path}/s"))
        mgr = CheckpointManager(client, f"file://{tmp_path}/s", "run",
                                keep=1, keep_best=2)
        mgr.save({"w": jnp.zeros(2)}, 1)                       # no metrics
        mgr.save({"w": jnp.zeros(2)}, 2, metrics={"loss": 0.5})
        mgr.save({"w": jnp.zeros(2)}, 3)                       # no metrics
        assert mgr.steps() == [2, 3]       # 3 by recency, 2 by best

    def test_nan_and_junk_metrics_never_hold_best_slots(self, tmp_path):
        from lzy_tpu.parallel.checkpoint import CheckpointManager
        from lzy_tpu.storage import StorageConfig, client_for

        client = client_for(StorageConfig(uri=f"file://{tmp_path}/s"))
        mgr = CheckpointManager(client, f"file://{tmp_path}/s", "run",
                                keep=1, keep_best=1)
        mgr.save({"w": jnp.zeros(2)}, 1, metrics={"loss": 0.4})  # true best
        mgr.save({"w": jnp.zeros(2)}, 2, metrics={"loss": float("nan")})
        mgr.save({"w": jnp.zeros(2)}, 3, metrics={"loss": [0.1]})  # junk
        mgr.save({"w": jnp.zeros(2)}, 4, metrics={"loss": 2.0})
        assert mgr.steps() == [1, 4]   # best=1 survives; nan/junk reaped


class TestOrbaxInterop:
    """Checkpoint migration to/from the wider JAX stack (maxtext/t5x
    speak Orbax): a state trained here restores there and vice versa."""

    def test_round_trip_preserves_values_dtypes_and_tree(self, tmp_path):
        import numpy as np

        from lzy_tpu.parallel import export_orbax, import_orbax

        state = {"w": jnp.arange(64.0).reshape(8, 8),
                 "b": jnp.ones((8,), jnp.bfloat16),
                 "nested": {"step": jnp.int32(7)}}
        path = export_orbax(state, str(tmp_path / "ockpt"))
        back = import_orbax(path)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(state["w"]))
        assert back["b"].dtype == jnp.bfloat16
        assert int(back["nested"]["step"]) == 7

    def test_restore_placed_directly_on_the_mesh(self, tmp_path):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from lzy_tpu.parallel import export_orbax, import_orbax, mesh_for

        mesh = mesh_for(8, fsdp=8)
        state = {"w": jnp.arange(64.0).reshape(8, 8)}
        path = export_orbax(state, str(tmp_path / "ockpt"))
        shardings = {"w": NamedSharding(mesh, P("fsdp", None))}
        placed = import_orbax(path, template=state, shardings=shardings)
        assert placed["w"].sharding.spec == P("fsdp", None)
        np.testing.assert_array_equal(np.asarray(placed["w"]),
                                      np.asarray(state["w"]))

    def test_framework_checkpoint_exports_to_orbax(self, tmp_path):
        """A CheckpointManager-saved TrainState migrates out: restore via
        the framework, export via orbax, import back — values equal."""
        import numpy as np
        import optax

        from lzy_tpu.parallel import (
            CheckpointManager, TrainState, export_orbax, import_orbax)
        from lzy_tpu.storage import StorageConfig
        from lzy_tpu.storage.registry import client_for

        params = {"w": jnp.arange(16.0).reshape(4, 4)}
        tx = optax.adam(1e-3)
        state = TrainState.create(params, tx)
        client = client_for(StorageConfig(uri=f"file://{tmp_path}/store"))
        mgr = CheckpointManager(client, f"file://{tmp_path}/store", "run")
        mgr.save(state, step=1)
        mgr.wait()
        restored = mgr.restore(1)
        path = export_orbax(restored.params, str(tmp_path / "ockpt"))
        migrated = import_orbax(path)
        np.testing.assert_array_equal(np.asarray(migrated["w"]),
                                      np.asarray(params["w"]))
