"""Typed wire schemas: round-trips, evolution (unknown fields accepted),
boundary validation → INVALID_ARGUMENT, and sensitive-field masking so
credentials never reach log lines (reference parity: protobuf model
``model/.../operation.proto:12-44`` + ``(validation.sensitive)`` masking in
``util-grpc/.../ProtoPrinter.java``)."""

import logging

import pytest

from lzy_tpu.rpc.schema import (
    GRAPH_DESC,
    MASK,
    REQUESTS,
    TASK_DESC,
    SchemaError,
    mask_request,
    validate_request,
)
from lzy_tpu.service.graph import EntryRef, GraphDesc, TaskDesc


def make_task(tid="t1") -> TaskDesc:
    ref = lambda n: EntryRef(id=f"{tid}-{n}", uri=f"mem://x/{tid}/{n}", name=n)  # noqa: E731
    return TaskDesc(
        id=tid, name="op", func_uri=f"mem://x/{tid}/fn",
        args=[ref("a0")], kwargs={"k": ref("k0")}, outputs=[ref("o0")],
        exception=ref("exc"), pool_label="cpu-small",
        env_vars={"HF_TOKEN": "hf_secret_123"},
    )


class TestRoundTrip:
    def test_task_doc_conforms(self):
        TASK_DESC.validate(make_task().to_doc())

    def test_graph_doc_conforms_and_round_trips(self):
        g = GraphDesc(id="g1", execution_id="e1", storage_uri="mem://x",
                      tasks=[make_task("t1"), make_task("t2")])
        doc = g.to_doc()
        GRAPH_DESC.validate(doc)
        g2 = GraphDesc.from_doc(doc)
        assert g2.to_doc() == doc

    def test_every_rpc_method_has_a_schema(self):
        from lzy_tpu.rpc.control import ControlPlaneServer  # noqa: F401

        for method in ("StartWorkflow", "FinishWorkflow", "AbortWorkflow",
                       "ExecuteGraph", "GraphStatus", "StopGraph",
                       "GetPoolSpecs", "ReadStdLogs", "ChannelBind",
                       "ChannelCompleted", "ChannelFailed",
                       "ChannelPublishPeer", "WaitChannel", "RegisterVm",
                       "Heartbeat", "Init", "Execute", "Status", "Shutdown"):
            assert method in REQUESTS, f"no wire schema for {method}"


class TestEvolution:
    def test_unknown_fields_accepted(self):
        """proto3 rule: a newer peer adding a field must not break an older
        one — unknown fields pass validation and survive masking."""
        doc = make_task().to_doc()
        doc["brand_new_field"] = {"anything": 1}
        TASK_DESC.validate(doc)
        assert TASK_DESC.mask(doc)["brand_new_field"] == {"anything": 1}

    def test_missing_required_rejected(self):
        doc = make_task().to_doc()
        del doc["func_uri"]
        with pytest.raises(SchemaError, match=r"func_uri: required"):
            TASK_DESC.validate(doc)

    def test_wrong_type_rejected_with_path(self):
        doc = make_task().to_doc()
        doc["args"][0]["uri"] = 42
        with pytest.raises(SchemaError, match=r"args\[0\].uri: expected str"):
            TASK_DESC.validate(doc)

    def test_bool_does_not_pass_as_int(self):
        with pytest.raises(SchemaError, match="gang_rank"):
            validate_request("Execute", {
                "task": make_task().to_doc(), "gang_rank": True,
            })

    def test_request_validation_catches_nested_graph(self):
        with pytest.raises(SchemaError, match=r"graph.tasks\[0\]"):
            validate_request("ExecuteGraph", {
                "execution_id": "e", "graph": {
                    "id": "g", "execution_id": "e", "storage_uri": "mem://x",
                    "tasks": [{"id": "t"}],            # missing required
                }})


class TestMasking:
    def test_env_var_values_masked(self):
        masked = TASK_DESC.mask(make_task().to_doc())
        assert masked["env_vars"] == {"HF_TOKEN": MASK}
        assert "hf_secret_123" not in repr(masked)

    def test_tokens_masked_in_requests(self):
        masked = mask_request("Heartbeat", {"vm_id": "vm1",
                                            "token": "vm1:123:0:sig"})
        assert masked == {"vm_id": "vm1", "token": MASK}

    def test_graph_request_masks_task_env_vars(self):
        payload = {"execution_id": "e", "token": "user-token", "graph": {
            "id": "g", "execution_id": "e", "storage_uri": "mem://x",
            "tasks": [make_task().to_doc()],
        }}
        masked = mask_request("ExecuteGraph", payload)
        assert masked["token"] == MASK
        assert masked["graph"]["tasks"][0]["env_vars"] == {"HF_TOKEN": MASK}
        assert "hf_secret_123" not in repr(masked)

    def test_unknown_method_still_scrubs_credential_keys(self):
        masked = mask_request("SomeFutureMethod", {"token": "t", "x": 1})
        assert masked == {"token": MASK, "x": 1}

    def test_mask_never_fails_on_junk(self):
        assert mask_request("Heartbeat", "not-a-dict") == "not-a-dict"
        assert TASK_DESC.mask(None) is None


class TestServerBoundary:
    def test_invalid_payload_maps_to_value_error(self, tmp_path):
        from lzy_tpu.rpc import RpcWorkflowClient
        from lzy_tpu.rpc.core import JsonRpcClient
        from lzy_tpu.service import InProcessCluster

        c = InProcessCluster(db_path=str(tmp_path / "m.db"))
        server = c.serve()
        raw = JsonRpcClient(server.address)
        try:
            with pytest.raises(ValueError, match="required field missing"):
                raw.call("ExecuteGraph", {"graph": {"id": "g"}})
        finally:
            raw.close()
            c.shutdown()

    def test_secrets_never_reach_server_logs(self, tmp_path, caplog):
        """A failing RPC logs the request — the masked form only."""
        from lzy_tpu.rpc.core import JsonRpcClient
        from lzy_tpu.service import InProcessCluster

        c = InProcessCluster(db_path=str(tmp_path / "m.db"))
        server = c.serve()
        raw = JsonRpcClient(server.address)
        try:
            with caplog.at_level(logging.INFO, logger="lzy_tpu.rpc.core"):
                with pytest.raises(Exception):
                    raw.call("FinishWorkflow", {
                        "execution_id": "no-such-execution",
                        "token": "alice:1:0:super-secret-sig",
                    })
            text = "\n".join(r.getMessage() for r in caplog.records)
            assert "rpc FinishWorkflow error" in text
            assert "super-secret-sig" not in text
            assert MASK in text
        finally:
            raw.close()
            c.shutdown()
