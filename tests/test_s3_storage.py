"""Executed coverage for ``storage/s3.py`` (VERDICT missing #5).

The container has no boto3, so these tests install the in-process stub
from ``fake_boto3`` into ``sys.modules`` and run the REAL client code —
construction through the lazy import, every object op, and the manual
multipart path with per-part retries and abort-on-failure. The gated
ImportError contract (no boto3 → clear error at construction) keeps its
own test at the bottom.
"""

import io

import pytest

from fake_boto3 import FakeClientError, install

from lzy_tpu.storage.api import StorageConfig
from lzy_tpu.storage.transfer import TransferConfig, upload_bytes


@pytest.fixture()
def s3(monkeypatch):
    """(client, fake) — a real S3StorageClient over the in-memory S3."""
    fake = install(monkeypatch)
    from lzy_tpu.storage.registry import client_for

    client = client_for(StorageConfig(uri="s3://bucket/prefix",
                                      endpoint="http://fake",
                                      access_key="k", secret_key="s"))
    assert client.scheme == "s3"
    return client, fake


SMALL_CFG = TransferConfig(part_size=64, max_workers=4, retries=3,
                           backoff_s=0.001)


class TestObjectOps:
    def test_write_read_roundtrip_counts_bytes(self, s3):
        client, _ = s3
        payload = b"x" * 1000
        n = client.write("s3://bucket/a/obj", io.BytesIO(payload))
        assert n == 1000
        out = io.BytesIO()
        assert client.read("s3://bucket/a/obj", out) == 1000
        assert out.getvalue() == payload

    def test_read_range(self, s3):
        client, _ = s3
        client.write("s3://bucket/r", io.BytesIO(b"0123456789"))
        assert client.read_range("s3://bucket/r", 2, 3) == b"234"
        assert client.read_range("s3://bucket/r", 7) == b"789"

    def test_exists_size_delete(self, s3):
        client, _ = s3
        assert not client.exists("s3://bucket/missing")
        client.write("s3://bucket/e", io.BytesIO(b"abc"))
        assert client.exists("s3://bucket/e")
        assert client.size("s3://bucket/e") == 3
        client.delete("s3://bucket/e")
        assert not client.exists("s3://bucket/e")

    def test_exists_surfaces_non_404_errors(self, s3):
        """Auth/throttling failures must raise, never read as 'absent' —
        a False here would let cache layers recompute and clobber."""
        client, fake = s3
        fake.fail_next["head_object"] = 1
        with pytest.raises(FakeClientError):
            client.exists("s3://bucket/whatever")

    def test_list_paginates(self, s3):
        client, fake = s3
        keys = [f"s3://bucket/list/{i:02d}" for i in range(5)]
        for uri in keys:
            client.write(uri, io.BytesIO(b"d"))
        client.write("s3://bucket/other", io.BytesIO(b"d"))
        assert list(client.list("s3://bucket/list/")) == keys

    def test_sign_uri(self, s3):
        client, _ = s3
        client.write("s3://bucket/signed", io.BytesIO(b"d"))
        url = client.sign_uri("s3://bucket/signed")
        assert url.startswith("https://") and "signed" in url


class TestMultipart:
    def test_small_payload_uses_single_put(self, s3):
        """multipart_upload's own small-object branch: one retried
        put_object, no multipart ceremony."""
        client, fake = s3
        data = b"s" * SMALL_CFG.part_size          # == part_size: no MPU
        n = client.multipart_upload(
            "s3://bucket/small", size=len(data),
            read_span=lambda off, ln: data[off:off + ln],
            config=SMALL_CFG, advance=lambda n: None)
        assert n == len(data)
        assert fake.calls.get("put_object") == 1
        assert "create_multipart_upload" not in fake.calls
        out = io.BytesIO()
        client.read("s3://bucket/small", out)
        assert out.getvalue() == data

    def test_multipart_assembles_parts_in_order(self, s3):
        client, fake = s3
        data = bytes(range(256)) * 2               # 512 B -> 8 parts of 64
        n = upload_bytes(client, "s3://bucket/big", data, config=SMALL_CFG)
        assert n == len(data)
        assert fake.calls["upload_part"] == 8
        assert fake.calls["complete_multipart_upload"] == 1
        out = io.BytesIO()
        client.read("s3://bucket/big", out)
        assert out.getvalue() == data
        assert fake.dangling_multipart() == 0

    def test_per_part_retry_recovers(self, s3):
        client, fake = s3
        fake.fail_next["upload_part"] = 2           # two throttles, then ok
        data = b"r" * 300
        assert upload_bytes(client, "s3://bucket/retry", data,
                            config=SMALL_CFG) == 300
        assert fake.calls["upload_part"] >= 5 + 2   # 5 parts + 2 retries
        out = io.BytesIO()
        client.read("s3://bucket/retry", out)
        assert out.getvalue() == data

    def test_exhausted_retries_abort_the_upload(self, s3):
        """A dangling multipart upload bills storage forever — on failure
        the client must abort it, and the target key must not appear."""
        client, fake = s3
        fake.fail_next["upload_part"] = 10 * SMALL_CFG.retries
        with pytest.raises(Exception):
            upload_bytes(client, "s3://bucket/doomed", b"d" * 300,
                         config=SMALL_CFG)
        assert fake.aborted, "failed multipart upload was not aborted"
        assert fake.dangling_multipart() == 0
        assert not client.exists("s3://bucket/doomed")


def test_without_boto3_construction_fails_clearly():
    """The gated contract on this image (no boto3): a clear ImportError
    at construction, never at first use."""
    pytest.importorskip  # keep flake quiet about the unused module dance
    try:
        import boto3  # noqa: F401

        pytest.skip("boto3 genuinely installed; gate does not apply")
    except ImportError:
        pass
    from lzy_tpu.storage.s3 import S3StorageClient

    with pytest.raises(ImportError, match="boto3"):
        S3StorageClient(StorageConfig(uri="s3://bucket/prefix"))
