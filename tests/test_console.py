"""Status surfaces: web console (HTML + JSON + metrics), GetStatus RPC, and
the CLI against a remote control plane (reference lzy/site + frontend
parity)."""

import json
import urllib.request

import pytest

from lzy_tpu import op
from lzy_tpu.service import InProcessCluster
from lzy_tpu.service.console import StatusConsole


@op
def console_double(x: int) -> int:
    return x * 2


@pytest.fixture()
def cluster(tmp_path):
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
    lzy = c.lzy()
    with lzy.workflow("console-wf"):
        assert int(console_double(21)) == 42
    yield c
    c.shutdown()


def get(console, path):
    with urllib.request.urlopen(f"http://{console.address}{path}") as resp:
        return resp.status, resp.read().decode()


class TestWebConsole:
    def test_overview_and_json_api(self, cluster):
        console = StatusConsole(cluster.store, bind_host="127.0.0.1")
        try:
            status, home = get(console, "/")
            assert status == 200
            assert "console-wf" in home and "executions" in home

            status, body = get(console, "/api/executions")
            rows = json.loads(body)["executions"]
            assert status == 200 and len(rows) == 1
            assert rows[0]["workflow_name"] == "console-wf"
            assert rows[0]["status"] == "FINISHED"

            _, body = get(console, "/api/graphs")
            g = json.loads(body)["graphs"][0]
            assert g["tasks_done"] == g["tasks_total"] == 1

            status, body = get(console, "/healthz")
            assert (status, body) == (200, "ok")

            status, body = get(console, "/metrics")
            assert status == 200 and "lzy_" in body
        finally:
            console.stop()

    def test_vm_rows_never_carry_tokens(self, tmp_path):
        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        token = c.iam.create_subject("alice")
        lzy = c.lzy(token=token)
        console = StatusConsole(c.store, bind_host="127.0.0.1")
        try:
            # sample while the workflow is open: VMs are alive and their
            # records (with worker_token) sit in the store
            with lzy.workflow("tok-wf"):
                assert int(console_double(2)) == 4
                _, body = get(console, "/api/vms")
                rows = json.loads(body)["vms"]
                assert rows, "expected at least one VM"
                assert all("worker_token" not in r for r in rows)
                vm_tokens = [v.worker_token for v in c.allocator.vms()]
                assert vm_tokens and all(t for t in vm_tokens)
                assert all(t not in body for t in vm_tokens)
                _, home = get(console, "/")
                assert all(t not in home for t in vm_tokens)
        finally:
            console.stop()
            c.shutdown()

    def test_unknown_view_404(self, cluster):
        console = StatusConsole(cluster.store, bind_host="127.0.0.1")
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(console, "/api/nonsense")
            assert e.value.code == 404
        finally:
            console.stop()


class TestRemoteCli:
    def test_cli_against_live_control_plane(self, cluster, capsys):
        from lzy_tpu.__main__ import main

        server = cluster.serve()
        main(["--address", server.address, "executions"])
        out = capsys.readouterr().out
        assert "console-wf" in out and "FINISHED" in out

        main(["--address", server.address, "graphs"])
        out = capsys.readouterr().out
        assert "console-wf" in out and "DONE" in out

    def test_remote_status_requires_token_with_iam(self, tmp_path, capsys):
        from lzy_tpu.iam import AuthError
        from lzy_tpu.__main__ import main

        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        server = c.serve()
        try:
            with pytest.raises(AuthError):
                main(["--address", server.address, "executions"])
            token = c.iam.create_subject("reader", role="READER")
            main(["--address", server.address, "--token", token,
                  "executions"])
            assert "EXECUTION" in capsys.readouterr().out
        finally:
            c.shutdown()

    def test_remote_status_is_scoped_per_user(self, tmp_path, capsys):
        """GetStatus honours the same ownership scoping as the other read
        paths: users see their OWN executions; infrastructure views are
        operator-only; worker tokens see nothing."""
        from lzy_tpu.iam import AuthError, INTERNAL
        from lzy_tpu.__main__ import main

        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        alice = c.iam.create_subject("alice")
        bob = c.iam.create_subject("bob")
        operator = c.iam.create_subject("ops", role=INTERNAL)
        for user, token in (("alice", alice), ("bob", bob)):
            lzy = c.lzy(user=user, token=token)
            with lzy.workflow(f"wf-{user}"):
                assert int(console_double(3)) == 6
        server = c.serve()
        try:
            main(["--address", server.address, "--token", alice,
                  "executions"])
            out = capsys.readouterr().out
            assert "wf-alice" in out and "wf-bob" not in out

            main(["--address", server.address, "--token", operator,
                  "executions"])
            out = capsys.readouterr().out
            assert "wf-alice" in out and "wf-bob" in out

            with pytest.raises(AuthError, match="operator-only"):
                main(["--address", server.address, "--token", alice, "vms"])

            worker_tokens = [v.worker_token for v in c.allocator.vms()]
            if worker_tokens:
                with pytest.raises(AuthError, match="worker credentials"):
                    main(["--address", server.address,
                          "--token", worker_tokens[0], "executions"])
        finally:
            c.shutdown()


def test_disks_view_lists_created_disks(tmp_path, capsys):
    from lzy_tpu.durable import OperationStore, OperationsExecutor
    from lzy_tpu.service.disks import DiskService, DiskSpec, LocalDiskManager
    from lzy_tpu.service.status import collect

    store = OperationStore(str(tmp_path / "m.db"))
    executor = OperationsExecutor(store, workers=1)
    svc = DiskService(store, executor, LocalDiskManager(str(tmp_path / "d")))
    try:
        d = svc.await_disk(svc.create_disk(DiskSpec(name="corpus", size_gb=7)))
        (row,) = collect(store, "disks")
        assert row["id"] == d.id and row["size_gb"] == 7

        import lzy_tpu.__main__ as cli

        cli.main(["--db", str(tmp_path / "m.db"), "disks"])
        out = capsys.readouterr().out
        assert "corpus" in out and "DISK" in out
    finally:
        executor.shutdown()
        store.close()
