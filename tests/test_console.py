"""Status surfaces: web console (HTML + JSON + metrics), GetStatus RPC, and
the CLI against a remote control plane (reference lzy/site + frontend
parity)."""

import json
import urllib.error
import urllib.request

import pytest

from lzy_tpu import op
from lzy_tpu.service import InProcessCluster
from lzy_tpu.service.console import StatusConsole


@op
def console_double(x: int) -> int:
    return x * 2


@pytest.fixture()
def cluster(tmp_path):
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
    lzy = c.lzy()
    with lzy.workflow("console-wf"):
        assert int(console_double(21)) == 42
    yield c
    c.shutdown()


def get(console, path):
    with urllib.request.urlopen(f"http://{console.address}{path}") as resp:
        return resp.status, resp.read().decode()


class TestDotEscaping:
    def test_hostile_task_names_cannot_inject_dot(self):
        """Task/entry names are user input; quotes, backslashes, and
        newlines must come out escaped, not close the dot string."""
        from lzy_tpu.service.graphviz import graph_dot

        evil = 'a"]; evil [label="pwned'
        state = {
            "graph": {"tasks": [
                {"id": 't"1', "name": evil,
                 "outputs": [{"id": "e1", "name": 'x"\ny\\z'}]},
                {"id": "t2", "name": "b\nmultiline",
                 "args": [{"id": "e1"}], "outputs": []},
            ]},
            "tasks": {},
        }
        dot = graph_dot(state)
        # the classic injection — closing the quote to start a new node —
        # must never survive unescaped
        assert 'a"];' not in dot
        assert 'evil [label="pwned' not in dot
        assert '\\"' in dot
        # real newlines in names become literal \n, keeping one statement
        # per line (a raw newline would break the dot grammar mid-string)
        assert not any(l.strip() in ("multiline", "y\\z")
                       for l in dot.splitlines())
        assert '"t2"' in dot and 'x\\"\\ny\\\\z' in dot


class TestWebConsole:
    def test_overview_and_json_api(self, cluster):
        console = StatusConsole(cluster.store, bind_host="127.0.0.1")
        try:
            status, home = get(console, "/")
            assert status == 200
            assert "console-wf" in home and "executions" in home

            status, body = get(console, "/api/executions")
            rows = json.loads(body)["executions"]
            assert status == 200 and len(rows) == 1
            assert rows[0]["workflow_name"] == "console-wf"
            assert rows[0]["status"] == "FINISHED"

            _, body = get(console, "/api/graphs")
            g = json.loads(body)["graphs"][0]
            assert g["tasks_done"] == g["tasks_total"] == 1

            status, body = get(console, "/healthz")
            assert (status, body) == (200, "ok")

            status, body = get(console, "/metrics")
            assert status == 200 and "lzy_" in body
        finally:
            console.stop()

    def test_vm_rows_never_carry_tokens(self, tmp_path):
        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        token = c.iam.create_subject("alice")
        lzy = c.lzy(token=token)
        console = StatusConsole(c.store, bind_host="127.0.0.1")
        try:
            # sample while the workflow is open: VMs are alive and their
            # records (with worker_token) sit in the store
            with lzy.workflow("tok-wf"):
                assert int(console_double(2)) == 4
                _, body = get(console, "/api/vms")
                rows = json.loads(body)["vms"]
                assert rows, "expected at least one VM"
                assert all("worker_token" not in r for r in rows)
                vm_tokens = [v.worker_token for v in c.allocator.vms()]
                assert vm_tokens and all(t for t in vm_tokens)
                assert all(t not in body for t in vm_tokens)
                _, home = get(console, "/")
                assert all(t not in home for t in vm_tokens)
        finally:
            console.stop()
            c.shutdown()

    def test_unknown_view_404(self, cluster):
        console = StatusConsole(cluster.store, bind_host="127.0.0.1")
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                get(console, "/api/nonsense")
            assert e.value.code == 404
        finally:
            console.stop()


class TestRemoteCli:
    def test_cli_against_live_control_plane(self, cluster, capsys):
        from lzy_tpu.__main__ import main

        server = cluster.serve()
        main(["--address", server.address, "executions"])
        out = capsys.readouterr().out
        assert "console-wf" in out and "FINISHED" in out

        main(["--address", server.address, "graphs"])
        out = capsys.readouterr().out
        assert "console-wf" in out and "DONE" in out

    def test_remote_status_requires_token_with_iam(self, tmp_path, capsys):
        from lzy_tpu.iam import AuthError
        from lzy_tpu.__main__ import main

        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        server = c.serve()
        try:
            with pytest.raises(AuthError):
                main(["--address", server.address, "executions"])
            token = c.iam.create_subject("reader", role="READER")
            main(["--address", server.address, "--token", token,
                  "executions"])
            assert "EXECUTION" in capsys.readouterr().out
        finally:
            c.shutdown()

    def test_remote_status_is_scoped_per_user(self, tmp_path, capsys):
        """GetStatus honours the same ownership scoping as the other read
        paths: users see their OWN executions; infrastructure views are
        operator-only; worker tokens see nothing."""
        from lzy_tpu.iam import AuthError, INTERNAL
        from lzy_tpu.__main__ import main

        c = InProcessCluster(db_path=str(tmp_path / "m.db"), with_iam=True)
        alice = c.iam.create_subject("alice")
        bob = c.iam.create_subject("bob")
        operator = c.iam.create_subject("ops", role=INTERNAL)
        for user, token in (("alice", alice), ("bob", bob)):
            lzy = c.lzy(user=user, token=token)
            with lzy.workflow(f"wf-{user}"):
                assert int(console_double(3)) == 6
        server = c.serve()
        try:
            main(["--address", server.address, "--token", alice,
                  "executions"])
            out = capsys.readouterr().out
            assert "wf-alice" in out and "wf-bob" not in out

            main(["--address", server.address, "--token", operator,
                  "executions"])
            out = capsys.readouterr().out
            assert "wf-alice" in out and "wf-bob" in out

            with pytest.raises(AuthError, match="operator-only"):
                main(["--address", server.address, "--token", alice, "vms"])

            worker_tokens = [v.worker_token for v in c.allocator.vms()]
            if worker_tokens:
                with pytest.raises(AuthError, match="worker credentials"):
                    main(["--address", server.address,
                          "--token", worker_tokens[0], "executions"])
        finally:
            c.shutdown()


def test_disks_view_lists_created_disks(tmp_path, capsys):
    from lzy_tpu.durable import OperationStore, OperationsExecutor
    from lzy_tpu.service.disks import DiskService, DiskSpec, LocalDiskManager
    from lzy_tpu.service.status import collect

    store = OperationStore(str(tmp_path / "m.db"))
    executor = OperationsExecutor(store, workers=1)
    svc = DiskService(store, executor, LocalDiskManager(str(tmp_path / "d")))
    try:
        d = svc.await_disk(svc.create_disk(DiskSpec(name="corpus", size_gb=7)))
        (row,) = collect(store, "disks")
        assert row["id"] == d.id and row["size_gb"] == 7

        import lzy_tpu.__main__ as cli

        cli.main(["--db", str(tmp_path / "m.db"), "disks"])
        out = capsys.readouterr().out
        assert "corpus" in out and "DISK" in out
    finally:
        executor.shutdown()
        store.close()


def request(console, method, path, *, token=None, body=None):
    req = urllib.request.Request(
        f"http://{console.address}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class TestKeysAndTasksRoutes:
    """Reference site Auth/Keys/Tasks parity (VERDICT r3 missing #5):
    token-authenticated key management + caller-scoped task listing."""

    @pytest.fixture()
    def plane(self, tmp_path):
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            with_iam=True,
        )
        tokens = {
            "alice": c.iam.create_subject("alice"),
            "bob": c.iam.create_subject("bob"),
            "ops": c.iam.create_subject("ops", role="INTERNAL"),
        }
        lzy = c.lzy(user="alice", token=tokens["alice"])
        with lzy.workflow("alice-wf"):
            assert int(console_double(3)) == 6
        console = StatusConsole(cluster_store(c), iam=c.iam)
        yield c, console, tokens
        console.stop()
        c.shutdown()

    def test_tasks_are_scoped_to_the_caller(self, plane):
        _, console, tokens = plane
        status, doc = request(console, "GET", "/api/tasks",
                              token=tokens["alice"])
        assert status == 200
        assert [e["workflow_name"] for e in doc["executions"]] == ["alice-wf"]
        status, doc = request(console, "GET", "/api/tasks",
                              token=tokens["bob"])
        assert status == 200 and doc["executions"] == []
        # INTERNAL sees everything
        status, doc = request(console, "GET", "/api/tasks",
                              token=tokens["ops"])
        assert len(doc["executions"]) == 1

    def test_tasks_require_a_valid_token(self, plane):
        _, console, _ = plane
        status, doc = request(console, "GET", "/api/tasks")
        assert status == 401
        status, doc = request(console, "GET", "/api/tasks",
                              token="garbage")
        assert status == 401

    def test_keys_listing_is_scoped(self, plane):
        _, console, tokens = plane
        status, doc = request(console, "GET", "/api/keys",
                              token=tokens["alice"])
        assert status == 200
        assert [s["id"] for s in doc["subjects"]] == ["alice"]
        status, doc = request(console, "GET", "/api/keys",
                              token=tokens["ops"])
        assert {s["id"] for s in doc["subjects"]} == {"alice", "bob", "ops"}

    def test_self_service_rotation_invalidates_old_token(self, plane):
        c, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys/rotate",
                              token=tokens["alice"])
        assert status == 200 and doc["subject_id"] == "alice"
        fresh = doc["token"]
        # the old token is dead, the fresh one works
        status, _ = request(console, "GET", "/api/tasks",
                            token=tokens["alice"])
        assert status == 401
        status, doc = request(console, "GET", "/api/tasks", token=fresh)
        assert status == 200 and len(doc["executions"]) == 1

    def test_subject_management_needs_internal(self, plane):
        _, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys",
                              token=tokens["alice"],
                              body={"subject_id": "mallory"})
        assert status == 403
        status, doc = request(console, "DELETE", "/api/keys/bob",
                              token=tokens["alice"])
        assert status == 403

    def test_internal_creates_and_removes_subjects(self, plane):
        c, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys",
                              token=tokens["ops"],
                              body={"subject_id": "carol", "role": "READER"})
        assert status == 201 and doc["token"]
        status, listing = request(console, "GET", "/api/keys",
                                  token=doc["token"])
        assert listing["subjects"][0]["role"] == "READER"
        status, doc = request(console, "DELETE", "/api/keys/carol",
                              token=tokens["ops"])
        assert status == 200
        status, doc = request(console, "DELETE", "/api/keys/carol",
                              token=tokens["ops"])
        assert status == 404


    def test_recreating_a_subject_conflicts(self, plane):
        """POST /api/keys on an existing id must 409, not silently reset
        its token generation (which would re-validate revoked tokens)."""
        _, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys",
                              token=tokens["ops"],
                              body={"subject_id": "alice"})
        assert status == 409 and "already exists" in doc["error"]

    def test_non_object_body_is_a_400(self, plane):
        _, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys",
                              token=tokens["ops"], body="just-a-string")
        assert status == 400

    def test_keys_routes_404_without_iam(self, cluster):
        console = StatusConsole(cluster.store)
        try:
            status, doc = request(console, "GET", "/api/keys", token="x")
            assert status == 404 and "iam not enabled" in doc["error"]
        finally:
            console.stop()


def cluster_store(c):
    return c.store


class TestCsrfAndGraphKill:
    """Round-6 hardening: cookie-authorized mutations need the embedded
    CSRF token (a cross-site form post rides the cookie but cannot read
    the token); Bearer-header API calls are exempt. Plus the graph-kill
    mutating route (cooperative stop flag, owner-scoped)."""

    @pytest.fixture()
    def plane(self, tmp_path):
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            with_iam=True,
        )
        tokens = {
            "alice": c.iam.create_subject("alice"),
            "bob": c.iam.create_subject("bob"),
        }
        lzy = c.lzy(user="alice", token=tokens["alice"])
        with lzy.workflow("alice-wf"):
            assert int(console_double(3)) == 6
        console = StatusConsole(c.store, iam=c.iam)
        yield c, console, tokens
        console.stop()
        c.shutdown()

    @staticmethod
    def _session_cookie(console, token):
        req = urllib.request.Request(
            f"http://{console.address}/login", method="POST",
            data=json.dumps({"token": token}).encode())
        with urllib.request.urlopen(req) as resp:
            return resp.headers["Set-Cookie"].split(";")[0]

    @staticmethod
    def _form_post(console, path, cookie, fields):
        from urllib.parse import urlencode

        req = urllib.request.Request(
            f"http://{console.address}{path}", method="POST",
            data=urlencode(fields).encode(),
            headers={"Cookie": cookie, "Accept": "text/html",
                     "Content-Type": "application/x-www-form-urlencoded"})
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def _csrf_from_keys_page(self, console, cookie):
        import re

        req = urllib.request.Request(f"http://{console.address}/keys",
                                     headers={"Cookie": cookie})
        with urllib.request.urlopen(req) as resp:
            page = resp.read().decode()
        m = re.search(r'name="csrf" value="([0-9a-f]+)"', page)
        assert m, "keys page must embed the CSRF token in its forms"
        return m.group(1)

    def test_cookie_mutation_without_csrf_is_refused(self, plane):
        _, console, tokens = plane
        cookie = self._session_cookie(console, tokens["alice"])
        status, body = self._form_post(console, "/api/keys/rotate",
                                       cookie, {})
        assert status == 403 and "CSRF" in body
        # the credential was NOT rotated: the session still works
        req = urllib.request.Request(f"http://{console.address}/keys",
                                     headers={"Cookie": cookie})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200

    def test_cookie_mutation_with_embedded_csrf_proceeds(self, plane):
        _, console, tokens = plane
        cookie = self._session_cookie(console, tokens["alice"])
        csrf = self._csrf_from_keys_page(console, cookie)
        status, body = self._form_post(console, "/api/keys/rotate",
                                       cookie, {"csrf": csrf})
        assert status == 200 and "credential rotated" in body

    def test_bearer_header_calls_stay_exempt(self, plane):
        # an Authorization header is no ambient credential: JSON API
        # clients keep working without any CSRF dance
        _, console, tokens = plane
        status, doc = request(console, "POST", "/api/keys/rotate",
                              token=tokens["alice"])
        assert status == 200 and doc["token"]

    def test_graph_kill_sets_the_stop_flag_owner_scoped(self, plane):
        c, console, tokens = plane
        graph_id = request(console, "GET", "/api/tasks",
                           token=tokens["alice"])[1]["graphs"][0]["id"]
        # bob cannot kill alice's graph — and cannot tell it exists
        status, doc = request(console, "POST", f"/graph/{graph_id}/kill",
                              token=tokens["bob"])
        assert status == 404
        status2, doc2 = request(console, "POST", "/graph/nope/kill",
                                token=tokens["bob"])
        assert status2 == 404
        assert doc["error"].replace(graph_id, "X") == \
            doc2["error"].replace("nope", "X")
        assert c.store.kv_get("graph_stops", graph_id) is None
        # the owner can
        status, doc = request(console, "POST", f"/graph/{graph_id}/kill",
                              token=tokens["alice"])
        assert status == 200 and doc["stopping"] == graph_id
        assert c.store.kv_get("graph_stops", graph_id) is True

    def test_graph_kill_via_cookie_needs_csrf(self, plane):
        c, console, tokens = plane
        graph_id = request(console, "GET", "/api/tasks",
                           token=tokens["alice"])[1]["graphs"][0]["id"]
        cookie = self._session_cookie(console, tokens["alice"])
        status, body = self._form_post(
            console, f"/graph/{graph_id}/kill", cookie, {})
        assert status == 403 and "CSRF" in body
        csrf = self._csrf_from_keys_page(console, cookie)
        status, page = self._form_post(
            console, f"/graph/{graph_id}/kill", cookie, {"csrf": csrf})
        # urllib follows the 303 back to the graph page
        assert status == 200 and f"graph {graph_id}" in page
        assert c.store.kv_get("graph_stops", graph_id) is True


class TestLoginScopingAndGraphs:
    """Round-5 operator surface (VERDICT r4 missing #4 + ADVICE): session
    login over token exchange, no query-string tokens, the generic
    /api/<view> routes authenticated + scoped, and the dataflow graph
    rendered as dot (DataFlowGraph.java parity) and SVG."""

    @pytest.fixture()
    def plane(self, tmp_path):
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            with_iam=True,
        )
        tokens = {
            "alice": c.iam.create_subject("alice"),
            "bob": c.iam.create_subject("bob"),
            "ops": c.iam.create_subject("ops", role="INTERNAL"),
        }
        lzy = c.lzy(user="alice", token=tokens["alice"])
        with lzy.workflow("alice-wf"):
            assert int(console_double(3)) == 6
        console = StatusConsole(c.store, iam=c.iam)
        yield c, console, tokens
        console.stop()
        c.shutdown()

    def test_api_views_are_scoped_not_bypassable(self, plane):
        """ADVICE r4: /api/executions next to a scoped /api/tasks must not
        return every user's rows unauthenticated."""
        _, console, tokens = plane
        status, _ = request(console, "GET", "/api/executions")
        assert status == 401
        # bob sees no rows of alice's work
        status, doc = request(console, "GET", "/api/executions",
                              token=tokens["bob"])
        assert status == 200 and doc["executions"] == []
        status, doc = request(console, "GET", "/api/executions",
                              token=tokens["alice"])
        assert len(doc["executions"]) == 1
        # infrastructure views need INTERNAL
        status, doc = request(console, "GET", "/api/vms",
                              token=tokens["alice"])
        assert status == 403 and "INTERNAL" in doc["error"]
        status, doc = request(console, "GET", "/api/vms",
                              token=tokens["ops"])
        assert status == 200

    def test_query_string_token_is_rejected(self, plane):
        """ADVICE r4: tokens in URLs leak through logs; header/cookie only."""
        _, console, tokens = plane
        status, _ = request(console, "GET",
                            f"/api/tasks?token={tokens['alice']}")
        assert status == 401

    def test_login_sets_session_cookie_and_serves_home(self, plane):
        _, console, tokens = plane
        req = urllib.request.Request(
            f"http://{console.address}/login", method="POST",
            data=json.dumps({"token": tokens["alice"]}).encode())
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            cookie = resp.headers["Set-Cookie"]
        assert "lzy_session=" in cookie and "HttpOnly" in cookie
        home = urllib.request.Request(f"http://{console.address}/")
        home.add_header("Cookie", cookie.split(";")[0])
        with urllib.request.urlopen(home) as resp:
            page = resp.read().decode()
        assert "alice-wf" in page and "signed in as alice" in page
        # and the home page hides other users' work
        assert "vms" not in page  # USER role sees no infra sections

    def test_bad_login_is_401(self, plane):
        _, console, _ = plane
        status, doc = request(console, "POST", "/login",
                              body={"token": "garbage"})
        assert status == 401

    def test_graph_dot_and_svg(self, plane):
        c, console, tokens = plane
        rows = request(console, "GET", "/api/tasks",
                       token=tokens["alice"])[1]["graphs"]
        graph_id = rows[0]["id"]
        # dot: reference DataFlowGraph parity
        req = urllib.request.Request(
            f"http://{console.address}/graph/{graph_id}.dot")
        req.add_header("Authorization", f"Bearer {tokens['alice']}")
        with urllib.request.urlopen(req) as resp:
            dot = resp.read().decode()
        assert dot.startswith("digraph dataflow")
        assert "console_double" in dot and "COMPLETED" in dot
        # svg page with per-task status
        req = urllib.request.Request(
            f"http://{console.address}/graph/{graph_id}")
        req.add_header("Authorization", f"Bearer {tokens['alice']}")
        with urllib.request.urlopen(req) as resp:
            page = resp.read().decode()
        assert "<svg" in page and "COMPLETED" in page
        # bob may not read alice's graph — and must not be able to TELL
        # it exists: not-owned answers exactly like unknown (a 403 here
        # was a graph-id enumeration oracle)
        status, doc = request(console, "GET", f"/graph/{graph_id}.dot",
                              token=tokens["bob"])
        assert status == 404
        status2, doc2 = request(console, "GET", "/graph/no-such-graph.dot",
                                token=tokens["bob"])
        assert status2 == 404
        assert doc["error"].replace(graph_id, "X") == \
            doc2["error"].replace("no-such-graph", "X")
