# corpus: a justified suppression silences exactly its rule on its
# line (and would cover the line below a standalone comment).
import time  # lzy-lint: disable=clock-raw-time -- corpus fixture: demonstrates the justified-suppression syntax


def nap():
    # lzy-lint: disable=clock-raw-time -- corpus fixture: real wall pause demanded by the scenario
    time.sleep(0.1)
