# corpus: the ISSUE 20 class — a workflow scheduler that releases
# parked conversation KV while still holding its own plane lock. The
# engine-side unpin blocks on the engine acknowledging the release
# (Event.wait) and the lease journal append is storage I/O; every
# dispatch/dedup caller serializes behind the tool-gap cleanup.
import threading


class BadParkPlane:
    def __init__(self, storage):
        self._lock = threading.Lock()
        self._storage = storage
        self._parked = {}
        self._engine_ack = threading.Event()

    def release_expired(self, now):
        with self._lock:
            for session, entry in list(self._parked.items()):
                if entry["expires"] > now:
                    continue
                del self._parked[session]
                # blocking engine handshake UNDER the plane lock: a
                # slow engine round stalls every dispatcher
                self._engine_ack.wait(1.0)
                # and the lease journal append is storage I/O
                self._storage.write_bytes(
                    f"wfsched/released/{session}", b"ttl")
