# corpus: broken chaos contracts — a typed error no degradation path
# catches, a registered point nothing hits, a hit of an unregistered
# (typo'd) name, and a crash_ok point with no death handler in its
# hit module.
from lzy_tpu.chaos.faults import CHAOS, CRASH, DELAY, ERROR, SLOW


class BadCorpusError(RuntimeError):
    pass


_FP_LOOSE = CHAOS.register(
    "corpus.uncaught", error=BadCorpusError,
    doc="declared error is caught nowhere")
_FP_DEAD = CHAOS.register(
    "corpus.dead", error=KeyError,
    doc="registered but never hit")
_FP_CRASHY = CHAOS.register(
    "corpus.crashy", crash_ok=True, modes=(ERROR, DELAY, SLOW, CRASH),
    doc="survivable crash declared, no BaseException handler here")


def boundary(payload):
    CHAOS.hit("corpus.uncaught")
    CHAOS.hit("corpus.typo")             # nobody registers this name
    return payload


def crash_boundary(payload):
    CHAOS.hit("corpus.crashy")
    return payload
