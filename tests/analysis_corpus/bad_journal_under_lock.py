# corpus: the ISSUE 15 class — a crash-recovery journal that performs
# its durable append (storage I/O) while holding the mirror lock. Every
# serving thread advancing a fence serializes behind the disk/DB write,
# and a fault-delayed append parks the whole request path.
import threading


class BadJournal:
    def __init__(self, storage):
        self._lock = threading.Lock()
        self._storage = storage
        self._fences = {}

    def advance_fence(self, request_id, tokens):
        with self._lock:
            self._fences[request_id] = list(tokens)
            # durable append UNDER the mirror lock: the write's latency
            # (or an injected journal.append delay) is now every
            # caller's latency
            self._storage.write_bytes(
                f"gwj/{request_id}", bytes(self._fences[request_id]))

    def load_fence(self, request_id):
        with self._lock:
            return self._storage.read_bytes(f"gwj/{request_id}")
