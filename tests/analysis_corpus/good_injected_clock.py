# corpus: the injectable-clock idiom — components read time only
# through a Clock, so the load plane can drive them virtually.
from lzy_tpu.utils.clock import SYSTEM_CLOCK


class Poller:
    def __init__(self, clock=None):
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._last = self._clock.time()

    def wait_for(self, probe, timeout_s):
        deadline = self._clock.now() + timeout_s
        while self._clock.now() < deadline:
            if probe():
                return True
            self._clock.sleep(0.05)
        return False

    def idle(self):
        SYSTEM_CLOCK.sleep(1.0)
