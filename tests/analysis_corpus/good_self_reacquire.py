# corpus: two good twins of the self-reacquire shape — an RLock is
# reentrant by contract, and the _locked-helper idiom re-enters nothing.
import threading


class ReentrantEngine:
    def __init__(self):
        self._lock = threading.RLock()
        self._queue = []

    def submit(self, item):
        with self._lock:
            self._queue.append(item)
            return self.retry_after_s()

    def retry_after_s(self):
        with self._lock:                 # RLock: re-entry is the contract
            return 0.1 * len(self._queue)


class LockedHelperEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []

    def submit(self, item):
        with self._lock:
            self._queue.append(item)
            return self._retry_after_locked()

    def _retry_after_locked(self):
        # caller holds the lock; this helper never takes it
        return 0.1 * len(self._queue)
