# corpus: the correct journal shape (what gateway/journal.py does) —
# the in-memory mirror updates under the lock, the durable append runs
# OUTSIDE it with the snapshot, so a slow or fault-delayed write never
# serializes the serving path behind the journal.
import threading


class GoodJournal:
    def __init__(self, storage):
        self._lock = threading.Lock()
        self._storage = storage
        self._fences = {}

    def advance_fence(self, request_id, tokens):
        with self._lock:
            self._fences[request_id] = list(tokens)
            snap = list(self._fences[request_id])
        self._storage.write_bytes(f"gwj/{request_id}", bytes(snap))

    def load_fence(self, request_id):
        data = self._storage.read_bytes(f"gwj/{request_id}")
        with self._lock:
            self._fences[request_id] = list(data)
        return data
