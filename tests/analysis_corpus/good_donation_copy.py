# corpus: the fixed shape — jnp.array COPIES, so the donated leaf
# shares no buffer with the retained host mirror, and distinct
# arguments are passed at distinct positions.
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0,))
def step(cache, tokens):
    return cache, tokens


def drive(cache, tokens):
    vals = np.zeros((4,), np.int32)
    leaves = jnp.array(vals)         # copy: safe to donate
    out = step(leaves, tokens)
    ok = step(cache, tokens)
    return out, ok
