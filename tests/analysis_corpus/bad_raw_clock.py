# corpus: raw wall-clock reads and sleeps — the PR 12 injectable-clock
# invariant regressed. Under a VirtualClock fleet these stall at the
# real-time backstop and make every test slow and racy.
import time
from time import sleep


class Poller:
    def __init__(self):
        self._last = time.time()

    def wait_for(self, probe, timeout_s):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if probe():
                return True
            time.sleep(0.05)
        return False

    def idle(self):
        sleep(1.0)
