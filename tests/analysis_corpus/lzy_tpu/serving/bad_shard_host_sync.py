# corpus: gang-replica decode round fetching per SHARD — one host sync
# per device of the mesh instead of one replicated fetch. On a 1xN gang
# this turns the one-fence-per-round contract into N fences, and the
# fence count scales with mesh width instead of staying constant.
import jax
import numpy as np


class GangEngine:
    def decode_step(self, emit_matrix, pool, shards):
        toks = []
        for shard in shards:
            part = np.asarray(                     # sync per shard
                emit_matrix.addressable_shards[shard].data)
            toks.append(part)
        for shard in shards:
            self.host_kv[shard] = jax.device_get(  # transfer per shard
                pool[shard])
        return toks
