# corpus: a host array unchanged between rounds is re-uploaded to the
# device on EVERY iteration of an engine decode loop — each round pays
# a host->device transfer for bytes identical to last round's.
import jax.numpy as jnp


class ReuploadEngine:
    def decode_loop(self, step, params, rounds):
        cur = self.cur
        for _ in range(rounds):
            pos = jnp.asarray(self.positions)      # re-upload per round
            mask = jnp.array(self.greedy_mask)     # re-upload per round
            cur = step(params, cur, pos, mask)
        return cur
