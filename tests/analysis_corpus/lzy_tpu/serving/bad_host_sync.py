# corpus: per-item host-device sync inside an engine decode loop —
# each .item()/np.asarray forces a device round trip per row instead of
# one batched transfer per scheduling round.
import numpy as np


class HotEngine:
    def decode_step(self, logits_rows, slots):
        out = []
        for row in logits_rows:
            tok = row.argmax().item()        # sync per row
            out.append(tok)
        for slot in slots:
            slot.host = np.asarray(slot.dev)  # transfer per slot
        return out
