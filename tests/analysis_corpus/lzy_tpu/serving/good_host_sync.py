# corpus: the correct shape — one batched host transfer per scheduling
# round, outside the per-item loop; the loop touches host data only.
import numpy as np


class BatchedEngine:
    def decode_step(self, logits_batch, slots):
        nxt = np.asarray(logits_batch.argmax(-1))   # ONE transfer
        out = []
        for i, slot in enumerate(slots):
            out.append(int(nxt[i]))                 # host-side indexing
        return out
