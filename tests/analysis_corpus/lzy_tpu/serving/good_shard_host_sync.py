# corpus: the gang-correct shape — the emit matrix is REPLICATED (the
# act_vocab anchor) before it leaves the jit, so ONE np.asarray per
# round carries every shard's answer; the per-shard loop is host-only.
import numpy as np


class GangBatchedEngine:
    def decode_step(self, emit_matrix, shards):
        nxt = np.asarray(emit_matrix)      # ONE fence for the whole gang
        out = []
        for shard in shards:
            out.append(int(nxt[shard]))    # host-side indexing only
        return out
