# corpus: upload-once discipline — device mirrors are built before the
# loop and only rebuilt when the round actually writes the host array,
# so steady-state rounds add zero host->device transfers.
import jax.numpy as jnp


class MirroredEngine:
    def decode_loop(self, step, params, rounds):
        cur = self.cur
        pos_dev = jnp.asarray(self.positions)      # uploaded ONCE
        mask_dev = jnp.array(self.greedy_mask)     # uploaded ONCE
        for r in range(rounds):
            cur = step(params, cur, pos_dev, mask_dev)
            if self.admitted(r):
                # admission dirtied the host positions: rebuilding the
                # mirror is the point, not a blind re-upload
                self.positions[r] = 0
                pos_dev = jnp.asarray(self.positions)
        return cur
