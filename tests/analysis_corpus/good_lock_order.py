# corpus: the same two locks, always acquired in the same order —
# a consistent hierarchy, no cycle.
import threading


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                return 1

    def also_forward(self):
        with self._a:
            with self._b:
                return 2

    def only_b(self):
        with self._b:
            return 3
