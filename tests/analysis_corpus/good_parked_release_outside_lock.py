# corpus: the correct shape (what llm/sched.py + the engine sweep do)
# — expired parked entries are snapshotted and popped under the plane
# lock, then the blocking engine handshake and the lease journal
# append run OUTSIDE it, so tool-gap cleanup never serializes the
# dispatch/dedup path.
import threading


class GoodParkPlane:
    def __init__(self, storage):
        self._lock = threading.Lock()
        self._storage = storage
        self._parked = {}
        self._engine_ack = threading.Event()

    def release_expired(self, now):
        with self._lock:
            expired = [s for s, e in self._parked.items()
                       if e["expires"] <= now]
            for session in expired:
                del self._parked[session]
        for session in expired:
            self._engine_ack.wait(1.0)           # outside the lock
            self._storage.write_bytes(
                f"wfsched/released/{session}", b"ttl")
