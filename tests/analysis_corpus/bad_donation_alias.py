# corpus: the PR 5 segfault shape — jnp.asarray zero-copies host numpy
# memory, then the resulting leaf is donated; XLA may receive the same
# buffer twice (or free memory the host still mirrors).
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0,))
def step(cache, tokens):
    return cache, tokens


def drive(cache, tokens):
    vals = np.zeros((4,), np.int32)
    leaves = jnp.asarray(vals)       # zero-copy view of host memory
    out = step(leaves, tokens)       # ...donated: host mirror aliases it
    dup = step(cache, cache)         # same expression donated AND passed
    return out, dup
