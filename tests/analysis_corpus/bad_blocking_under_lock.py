# corpus: the PR 12 class — blocking/expensive work performed while a
# shared lock is held. Every other thread serializes behind the sleep,
# the storage read, and the event wait.
import threading
import time  # lzy-lint: disable=clock-raw-time -- corpus twin exercises the LOCK rule; the clock rule has its own pair


class Blocky:
    def __init__(self, storage):
        self._lock = threading.Lock()
        self._storage = storage
        self._done = threading.Event()

    def slow_tick(self):
        with self._lock:
            time.sleep(0.05)  # lzy-lint: disable=clock-raw-time -- corpus twin exercises the LOCK rule; the clock rule has its own pair

    def fetch_state(self, uri):
        with self._lock:
            return self._storage.read_bytes(uri)

    def wait_done(self):
        with self._lock:
            return self._done.wait(1.0)
