# corpus: the correct shapes — blocking work happens OUTSIDE the lock
# (snapshot under the lock, I/O after), and a Condition.wait on the
# held condition is exempt (wait releases it).
import threading


class Tidy:
    def __init__(self, storage, clock):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._storage = storage
        self._clock = clock
        self._pending = []

    def slow_tick(self):
        self._clock.sleep(0.05)          # nothing held
        with self._lock:
            self._pending.append(1)

    def fetch_state(self, uri):
        with self._lock:
            pending = list(self._pending)
        data = self._storage.read_bytes(uri)     # outside the lock
        return pending, data

    def wait_work(self):
        with self._cv:
            while not self._pending:
                self._cv.wait(1.0)       # releases the held condition
            return self._pending.pop()
