# corpus: the PR 6 self-deadlock shape — a method holding its own
# non-reentrant Lock calls a helper that re-acquires the same lock.
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []

    def submit(self, item):
        with self._lock:
            self._queue.append(item)
            # computing the backoff hint under our own lock re-enters it
            return self.retry_after_s()

    def retry_after_s(self):
        with self._lock:
            return 0.1 * len(self._queue)
