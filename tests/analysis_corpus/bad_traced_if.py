# corpus: Python control flow on a traced value inside a jitted
# function — a trace-time ConcretizationTypeError at best, silent
# specialization at worst.
import functools

import jax


@functools.partial(jax.jit, donate_argnums=())
def clamp(x, limit):
    if x > limit:            # traced comparison in Python `if`
        return limit
    return x
