# corpus: a disable comment with no justification neither silences the
# finding nor passes suppression hygiene.
import time


def nap():
    time.sleep(0.1)  # lzy-lint: disable=clock-raw-time
