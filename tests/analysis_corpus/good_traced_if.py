# corpus: the good twins — static arguments may branch, and the
# is-None / shape / isinstance / len idioms are trace-time static.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(1,))
def clamp(x, limit):
    if limit > 0:                        # static: fine
        return jnp.minimum(x, limit)
    return x


@jax.jit
def norm(x, scale=None):
    if scale is None:                    # identity check: trace-static
        scale = 1.0
    if x.ndim > 1:                       # shape metadata: trace-static
        x = x.reshape(-1)
    if len(x) == 0:                      # length: trace-static
        return x
    return x * scale
