# corpus: honest chaos contracts — the declared typed error is caught
# on the caller's degradation path, the point is hit, and the crash_ok
# point's module has the death handler its declaration promises.
from lzy_tpu.chaos.faults import CHAOS, CRASH, DELAY, ERROR, SLOW


class GoodCorpusError(RuntimeError):
    pass


_FP_TIGHT = CHAOS.register(
    "corpus.caught", error=GoodCorpusError,
    doc="error caught right below")
_FP_SAFE_CRASH = CHAOS.register(
    "corpus.safe_crash", crash_ok=True, modes=(ERROR, DELAY, SLOW, CRASH),
    doc="loop death handled in this module")


def boundary(payload):
    CHAOS.hit("corpus.caught")
    return payload


def caller(payload):
    try:
        return boundary(payload)
    except GoodCorpusError:
        return None                      # the degradation path


def loop(payload):
    try:
        CHAOS.hit("corpus.safe_crash")
        return payload
    except BaseException:                # noqa: BLE001 — death handler
        return None
