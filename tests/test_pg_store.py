"""PostgresOperationStore specifics: dialect translation and the
DbHelper.withRetries discipline (serialization-failure retry), exercised
through the fake DBAPI driver so they run without a server — and, when a
real driver + ``LZY_PG_DSN`` are present, the SAME suite against a real
PostgreSQL (the gate is inverted: a real driver runs the tests, it does
not skip them; ``fake_pg`` is the always-on fallback)."""

import os

import pytest

from conftest import record_tier_run
from fake_pg import FakePgError, fake_connect

from lzy_tpu.durable.pg_store import (
    PostgresOperationStore,
    store_for,
    translate,
)
from lzy_tpu.durable.store import OperationStore


def _real_driver():
    for mod in ("psycopg2", "pg8000"):
        try:
            __import__(mod)
            return mod
        except ImportError:
            continue
    return None


PG_BACKENDS = [
    "fakepg",
    pytest.param("postgres", marks=pytest.mark.skipif(
        not (_real_driver() and os.environ.get("LZY_PG_DSN")),
        reason="needs a real PG driver AND LZY_PG_DSN=postgresql://... "
               "(the driver alone cannot invent a server to dial)")),
]


@pytest.fixture(params=PG_BACKENDS)
def pg_store(request, tmp_path):
    """A PostgresOperationStore on the fake DBAPI driver (always) or on a
    real server (real driver + LZY_PG_DSN). Real-server runs wipe the
    shared tables first and append tier evidence."""
    if request.param == "fakepg":
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
    else:
        dsn = os.environ["LZY_PG_DSN"]
        s = PostgresOperationStore(dsn)
        with s._lock:
            for table in ("operations", "kv", "leases"):
                s._execute(f"DELETE FROM {table}")
        record_tier_run("postgres:pg_store", dsn.rsplit("@", 1)[-1])
    yield s
    s.close()


class TestPgStoreSuite:
    """The store's operational surface on BOTH drivers: what used to run
    only through ``fake_pg`` now executes against a real server whenever
    one is reachable (VERDICT weak #3 — a real psycopg2 used to SKIP)."""

    def test_kv_roundtrip_and_listing(self, pg_store):
        pg_store.kv_put("ns", "a", {"v": 1})
        pg_store.kv_put("ns", "b", [1, 2, 3])
        pg_store.kv_put("ns", "a", {"v": 2})          # upsert
        assert pg_store.kv_get("ns", "a") == {"v": 2}
        assert pg_store.kv_list("ns") == {"a": {"v": 2}, "b": [1, 2, 3]}
        pg_store.kv_del("ns", "a")
        assert pg_store.kv_get("ns", "a", default="gone") == "gone"

    def test_op_lifecycle_and_idempotency(self, pg_store):
        rec = pg_store.create("op-1", "k", {"x": 1}, idempotency_key="idem")
        dup = pg_store.create("op-2", "k", {"x": 2}, idempotency_key="idem")
        assert dup.id == rec.id == "op-1"
        pg_store.save_progress("op-1", {"x": 3}, step=1)
        pg_store.complete("op-1", result={"ok": True})
        loaded = pg_store.load("op-1")
        assert loaded.done and loaded.result == {"ok": True}
        assert loaded.state == {"x": 3}

    def test_lease_protocol(self, pg_store):
        assert pg_store.try_acquire_lease("gc", "plane-a", ttl_s=30.0)
        assert not pg_store.try_acquire_lease("gc", "plane-b", ttl_s=30.0)
        assert pg_store.renew_lease("gc", "plane-a", ttl_s=30.0)
        assert not pg_store.renew_lease("gc", "plane-b", ttl_s=30.0)
        holder = pg_store.lease_holder("gc")
        assert holder and holder[0] == "plane-a"
        pg_store.release_lease("gc", "plane-a")
        assert pg_store.try_acquire_lease("gc", "plane-b", ttl_s=30.0)


class TestTranslate:
    def test_placeholders(self):
        assert translate("SELECT v FROM kv WHERE ns = ? AND k = ?") == \
            "SELECT v FROM kv WHERE ns = %s AND k = %s"

    def test_null_safe_compare(self):
        assert translate("UPDATE t SET a = ? WHERE deadline IS ?") == \
            "UPDATE t SET a = %s WHERE deadline IS NOT DISTINCT FROM %s"


class TestRetryDiscipline:
    def test_serialization_failure_retried(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s._conn.fail_next_sqlstates = ["40001", "40P01"]  # two, then clean
        s.kv_put("ns", "k", {"v": 1})                     # survives both
        assert s.kv_get("ns", "k") == {"v": 1}

    def test_non_retryable_sqlstate_raises(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s._conn.fail_next_sqlstates = ["23502"]           # NOT NULL violation
        with pytest.raises(FakePgError):
            s.kv_put("ns", "k", 1)

    def test_retries_exhaust(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s.MAX_RETRIES = 3
        s._conn.fail_next_sqlstates = ["40001"] * 10
        with pytest.raises(FakePgError):
            s.kv_put("ns", "k", 1)

    def test_cross_plane_idempotency_race(self, tmp_path):
        """Two planes insert the same idempotency key; the loser's unique
        violation resolves to the winner's record (multi-process PG path —
        the in-process sqlite lock can never hit this)."""
        path = str(tmp_path / "pg.db")
        a = PostgresOperationStore(path, _connect=fake_connect)
        b = PostgresOperationStore(path, _connect=fake_connect)
        rec_a = a.create("op-a", "k", {}, idempotency_key="shared")
        # force plane B's pre-check to miss, as if A's insert landed in
        # the check->insert window: B's INSERT must hit the unique index
        # and resolve to A's record instead of raising
        real_execute = b._execute
        state = {"missed": False}

        def racy_execute(sql, params=()):
            if (not state["missed"]
                    and sql.lstrip().startswith("SELECT id FROM operations")):
                state["missed"] = True

                class _Miss:
                    def fetchone(self):
                        return None

                return _Miss()
            return real_execute(sql, params)

        b._execute = racy_execute
        rec_b = b.create("op-b", "k", {}, idempotency_key="shared")
        assert rec_a.id == rec_b.id == "op-a"
        assert state["missed"], "the race path was not exercised"


def test_store_for_dispatch(tmp_path):
    """Inverted gate (VERDICT weak #3): a real driver used to SKIP this
    test wholesale. Now a path dispatches to sqlite everywhere; a DSN
    dispatches to a REAL PostgresOperationStore when a driver + server
    exist (executed, with a round-trip), and to a clear ImportError when
    no driver does. Only the driver-without-server combination skips —
    there is nothing to dial."""
    s = store_for(str(tmp_path / "x.db"))
    assert type(s) is OperationStore
    s.close()
    if _real_driver() is None:
        with pytest.raises(ImportError, match="psycopg2 or pg8000"):
            store_for("postgresql://u@h/db")
        return
    dsn = os.environ.get("LZY_PG_DSN")
    if not dsn:
        pytest.skip(f"{_real_driver()} is installed but LZY_PG_DSN is "
                    f"unset; a made-up DSN would dial out")
    pg = store_for(dsn)
    assert type(pg) is PostgresOperationStore
    try:
        pg.kv_put("dispatch", "probe", {"ok": True})
        assert pg.kv_get("dispatch", "probe") == {"ok": True}
        pg.kv_del("dispatch", "probe")
        record_tier_run("postgres:store_for", dsn.rsplit("@", 1)[-1])
    finally:
        pg.close()
