"""PostgresOperationStore specifics: dialect translation and the
DbHelper.withRetries discipline (serialization-failure retry), exercised
through the fake DBAPI driver so they run without a server."""

import pytest

from fake_pg import FakePgError, fake_connect

from lzy_tpu.durable.pg_store import (
    PostgresOperationStore,
    store_for,
    translate,
)
from lzy_tpu.durable.store import OperationStore


class TestTranslate:
    def test_placeholders(self):
        assert translate("SELECT v FROM kv WHERE ns = ? AND k = ?") == \
            "SELECT v FROM kv WHERE ns = %s AND k = %s"

    def test_null_safe_compare(self):
        assert translate("UPDATE t SET a = ? WHERE deadline IS ?") == \
            "UPDATE t SET a = %s WHERE deadline IS NOT DISTINCT FROM %s"


class TestRetryDiscipline:
    def test_serialization_failure_retried(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s._conn.fail_next_sqlstates = ["40001", "40P01"]  # two, then clean
        s.kv_put("ns", "k", {"v": 1})                     # survives both
        assert s.kv_get("ns", "k") == {"v": 1}

    def test_non_retryable_sqlstate_raises(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s._conn.fail_next_sqlstates = ["23502"]           # NOT NULL violation
        with pytest.raises(FakePgError):
            s.kv_put("ns", "k", 1)

    def test_retries_exhaust(self, tmp_path):
        s = PostgresOperationStore(str(tmp_path / "pg.db"),
                                   _connect=fake_connect)
        s.MAX_RETRIES = 3
        s._conn.fail_next_sqlstates = ["40001"] * 10
        with pytest.raises(FakePgError):
            s.kv_put("ns", "k", 1)

    def test_cross_plane_idempotency_race(self, tmp_path):
        """Two planes insert the same idempotency key; the loser's unique
        violation resolves to the winner's record (multi-process PG path —
        the in-process sqlite lock can never hit this)."""
        path = str(tmp_path / "pg.db")
        a = PostgresOperationStore(path, _connect=fake_connect)
        b = PostgresOperationStore(path, _connect=fake_connect)
        rec_a = a.create("op-a", "k", {}, idempotency_key="shared")
        # force plane B's pre-check to miss, as if A's insert landed in
        # the check->insert window: B's INSERT must hit the unique index
        # and resolve to A's record instead of raising
        real_execute = b._execute
        state = {"missed": False}

        def racy_execute(sql, params=()):
            if (not state["missed"]
                    and sql.lstrip().startswith("SELECT id FROM operations")):
                state["missed"] = True

                class _Miss:
                    def fetchone(self):
                        return None

                return _Miss()
            return real_execute(sql, params)

        b._execute = racy_execute
        rec_b = b.create("op-b", "k", {}, idempotency_key="shared")
        assert rec_a.id == rec_b.id == "op-a"
        assert state["missed"], "the race path was not exercised"


def test_store_for_dispatch(tmp_path):
    s = store_for(str(tmp_path / "x.db"))
    assert type(s) is OperationStore
    try:
        import psycopg2  # noqa: F401

        have_driver = True
    except ImportError:
        try:
            import pg8000  # noqa: F401

            have_driver = True
        except ImportError:
            have_driver = False
    if have_driver:
        pytest.skip("a real PG driver is installed; the DSN would dial out")
    with pytest.raises(ImportError, match="psycopg2 or pg8000"):
        store_for("postgresql://u@h/db")
