"""bench.py supervisor: the CPU-fallback path must produce a RESULT row.

BENCH_r01–r05 are all error rows because the TPU relay has been absent
every round; PR 5 taught ``supervise()`` to fall back to a
``JAX_PLATFORMS=cpu`` child when the relay is *definitively* absent
(TCP preflight refused), so a round records a real serving-path
trajectory tagged ``cpu_fallback: true`` instead of an error-only JSON.
No round had actually exercised that path until BENCH_r06; this test
pins the supervisor's control flow fast (the subprocess hop is faked —
the real end-to-end run is the committed BENCH_r06.json).
"""

import json
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parents[1]))

import bench  # noqa: E402


@pytest.fixture()
def _absent_relay(monkeypatch):
    monkeypatch.setattr(
        bench, "tcp_preflight",
        lambda: "relay not listening on 127.0.0.1:8083 (test)")
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda preflight_err=None: "probe 1: hung (test)")


def _fake_child(metric_obj):
    """Stand-in for the ``bench.py --run`` subprocess: emits one metric
    line on stdout like a healthy CPU child would."""
    class Proc:
        returncode = 0
        stdout = (json.dumps(metric_obj) + "\n").encode()

    calls = []

    def run(cmd, **kw):
        calls.append((list(cmd), kw))
        return Proc()

    return run, calls


def test_supervise_emits_cpu_fallback_row_when_relay_absent(
        _absent_relay, monkeypatch, capsys):
    """Relay definitively absent (preflight refused + probe failed):
    supervise() must run ONE JAX_PLATFORMS=cpu child and print its
    metric line tagged cpu_fallback:true + relay_error — NOT an
    error-only row."""
    run, calls = _fake_child({
        "metric": bench.METRIC, "value": 0.0123,
        "unit": "mfu_fraction", "vs_baseline": 0.03,
        "detail": {"platform": "cpu"}})
    monkeypatch.setattr(bench.subprocess, "run", run)
    bench.supervise()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    obj = json.loads(line)
    assert obj["cpu_fallback"] is True
    assert "error" not in obj                      # a RESULT, not an error
    assert obj["relay_error"].startswith("probe 1")
    assert obj["value"] == 0.0123
    # exactly one child, on the CPU backend, running the real body
    assert len(calls) == 1
    cmd, kw = calls[0]
    assert cmd[-1] == "--run"
    assert kw["env"]["JAX_PLATFORMS"] == "cpu"


def test_supervise_still_emits_error_row_when_cpu_child_fails(
        _absent_relay, monkeypatch, capsys):
    """If even the CPU child self-diagnoses, the round keeps the
    error-only contract (never a fabricated result)."""
    run, _ = _fake_child({
        "metric": bench.METRIC, "value": 0.0,
        "unit": "mfu_fraction", "vs_baseline": 0.0,
        "error": "backend never initialized (test)"})
    monkeypatch.setattr(bench.subprocess, "run", run)
    bench.supervise()
    obj = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert obj["error"]
    assert "cpu_fallback" not in obj
