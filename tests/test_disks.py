"""Disk/volume subsystem: durable create/clone/delete, PVC realization, and
dynamic mounts onto running workers (reference: allocator DiskService +
Yc*DiskAction durable ops, KuberVolumeManager PVCs, MountDynamicDiskAction +
KuberMountHolderManager)."""

import time

import pytest

from lzy_tpu import op
from lzy_tpu.durable import InjectedFailures, OperationStore, OperationsExecutor
from lzy_tpu.service import InProcessCluster
from lzy_tpu.service.disks import (
    Disk,
    DiskMeta,
    DiskMount,
    DiskService,
    DiskSpec,
    DiskType,
    LocalDiskManager,
    PvcDiskManager,
)
from lzy_tpu.service.kube import FakeKubeApi


@pytest.fixture()
def svc(tmp_path):
    store = OperationStore(str(tmp_path / "meta.db"))
    executor = OperationsExecutor(store, workers=2)
    service = DiskService(store, executor,
                          LocalDiskManager(str(tmp_path / "disks")))
    yield service
    InjectedFailures.clear()
    executor.shutdown()
    store.close()


class TestDiskService:
    def test_create_get_list_delete(self, svc):
        d = svc.await_disk(svc.create_disk(
            DiskSpec(name="scratch", type=DiskType.SSD, size_gb=5),
            DiskMeta(user="alice")))
        assert svc.get(d.id).spec.name == "scratch"
        assert svc.manager.exists(d.id)
        assert [x.id for x in svc.list(user="alice")] == [d.id]
        assert svc.list(user="bob") == []

        svc._executor.await_op(svc.delete_disk(d.id))
        with pytest.raises(KeyError):
            svc.get(d.id)
        assert not svc.manager.exists(d.id)

    def test_clone_copies_content(self, svc):
        src = svc.await_disk(svc.create_disk(DiskSpec(name="base")))
        path = svc.manager.local_path(src.id)
        with open(f"{path}/corpus.txt", "w") as f:
            f.write("tokenized data")

        clone = svc.await_disk(svc.clone_disk(
            src.id, DiskSpec(name="base-copy"), DiskMeta(user="bob")))
        assert clone.id != src.id
        with open(f"{svc.manager.local_path(clone.id)}/corpus.txt") as f:
            assert f.read() == "tokenized data"
        # and the source is untouched
        assert svc.get(src.id).spec.name == "base"

    def test_clone_unknown_source_fails_fast(self, svc):
        with pytest.raises(KeyError):
            svc.clone_disk("disk-nope", DiskSpec(name="x"))

    def test_create_survives_crash_between_steps(self, svc):
        """Crash after the backend create but before registration; resume
        completes registration without creating a second volume."""
        InjectedFailures.arm("create_disk.register")  # after create persisted
        op_id = svc.create_disk(DiskSpec(name="crashy"))
        time.sleep(0.5)
        with pytest.raises(TimeoutError):
            svc._executor.await_op(op_id, timeout_s=0.5)  # parked RUNNING
        assert svc._executor.restore() >= 1
        disk = svc.await_disk(op_id)
        assert svc.get(disk.id).spec.name == "crashy"
        assert svc.manager.exists(disk.id)

    def test_failed_create_compensates(self, svc, monkeypatch):
        """A terminally-failing create must not leave an unregistered backend
        volume behind."""
        created = {}
        real_create = svc.manager.create

        def failing_create(disk_id, spec, meta):
            real_create(disk_id, spec, meta)
            created["id"] = disk_id
            raise RuntimeError("provisioner quota exceeded")

        monkeypatch.setattr(svc.manager, "create", failing_create)
        op_id = svc.create_disk(DiskSpec(name="doomed"))
        record = svc._executor.await_op(op_id)
        assert record.status == "FAILED"
        assert not svc.manager.exists(created["id"])


class TestPvcManager:
    def test_create_maps_type_to_storage_class(self):
        api = FakeKubeApi()
        mgr = PvcDiskManager(api, namespace="ns")
        mgr.create("disk-1", DiskSpec(name="d", type=DiskType.HDD, size_gb=20),
                   DiskMeta())
        (pvc,) = api.list_pvcs("ns")
        assert pvc["spec"]["storageClassName"] == "standard-rwo"
        assert pvc["spec"]["resources"]["requests"]["storage"] == "20Gi"
        assert mgr.exists("disk-1")
        # idempotent resume: second create is a tolerated conflict
        mgr.create("disk-1", DiskSpec(name="d", type=DiskType.HDD, size_gb=20),
                   DiskMeta())
        assert len(api.list_pvcs("ns")) == 1

    def test_clone_uses_csi_datasource(self):
        api = FakeKubeApi()
        mgr = PvcDiskManager(api, namespace="ns")
        spec = DiskSpec(name="d", type=DiskType.SSD, size_gb=8)
        mgr.create("disk-src", spec, DiskMeta())
        src = Disk(id="disk-src", spec=spec, meta=DiskMeta())
        mgr.clone(src, "disk-dst", spec, DiskMeta())
        (clone,) = api.list_pvcs("ns", label_selector="lzy-disk-id=disk-dst")
        assert clone["spec"]["dataSource"] == {
            "kind": "PersistentVolumeClaim",
            "name": PvcDiskManager.claim_name("disk-src"),
        }

    def test_delete_tolerates_absent(self):
        mgr = PvcDiskManager(FakeKubeApi(), namespace="ns")
        mgr.delete("disk-ghost")  # no raise
        assert not mgr.exists("disk-ghost")

    def test_pvc_disks_have_no_local_path(self):
        assert PvcDiskManager(FakeKubeApi()).local_path("disk-1") is None


@op
def read_mounted(mount_name: str, filename: str) -> str:
    from lzy_tpu.service.worker import current_mounts

    mounts = current_mounts()
    if mount_name not in mounts:
        return "<not mounted>"
    with open(f"{mounts[mount_name]['path']}/{filename}") as f:
        return f.read()


class TestDynamicMounts:
    @pytest.fixture()
    def cluster(self):
        c = InProcessCluster(storage_uri="mem://disk-mounts")
        yield c
        c.shutdown()

    def test_mount_then_op_reads_unmount_then_not(self, cluster):
        lzy = cluster.lzy()
        disk = cluster.disks.await_disk(
            cluster.disks.create_disk(DiskSpec(name="data")))
        with open(f"{cluster.disks.manager.local_path(disk.id)}/f.txt",
                  "w") as f:
            f.write("mounted bytes")

        with lzy.workflow("mnt-wf"):
            # first barrier allocates the VM; before the mount the op must
            # not see the disk
            assert str(read_mounted("data", "f.txt")) == "<not mounted>"
            (vm,) = cluster.allocator.vms()
            cluster.executor.await_op(
                cluster.allocator.mount_disk(vm.id, disk.id, "data"))
            assert cluster.allocator.vm_mounts(vm.id)["data"]["disk_id"] == disk.id
            assert str(read_mounted("data", "f.txt")) == "mounted bytes"

            cluster.executor.await_op(
                cluster.allocator.unmount_disk(vm.id, "data"))
            assert cluster.allocator.vm_mounts(vm.id) == {}
            assert str(read_mounted("data", "f.txt")) == "<not mounted>"

    def test_mount_unknown_disk_or_vm_fails_fast(self, cluster):
        with pytest.raises(KeyError):
            cluster.allocator.mount_disk("vm-ghost", "disk-ghost", "x")


class TestGkeMounts:
    def _backend(self):
        from lzy_tpu.service.backends import GkeTpuBackend

        api = FakeKubeApi()
        backend = GkeTpuBackend(control_address="cp:18700",
                                storage_uri="s3://bucket/root",
                                image="gcr.io/p/lzy-worker:1", api=api)
        return api, backend

    def _vm(self):
        from lzy_tpu.service.allocator import RUNNING, Vm

        return Vm(id="vm-1", session_id="s", pool_label="tpu-v5e-8",
                  status=RUNNING, gang_id="g", host_index=0, gang_size=1)

    def test_worker_pod_exposes_dynamic_mount_dir(self):
        from lzy_tpu.service.harness import DEFAULT_POOLS

        api, backend = self._backend()
        pool = next(p for p in DEFAULT_POOLS if p.label == "tpu-v5e-8")
        manifest = backend.build_pod_manifest(self._vm(), pool)
        (vol,) = [v for v in manifest["spec"]["volumes"]
                  if v["name"] == "lzy-dyn-mounts"]
        assert vol["hostPath"]["path"].endswith("/vm-1")
        (vm_mount,) = manifest["spec"]["containers"][0]["volumeMounts"]
        assert vm_mount["mountPath"] == backend.WORKER_MOUNT_DIR
        assert vm_mount["mountPropagation"] == "HostToContainer"

    def test_mount_creates_holder_pod_and_unmount_removes(self):
        api, backend = self._backend()
        vm = self._vm()
        disk = Disk(id="disk-9", spec=DiskSpec(name="d"), meta=DiskMeta())
        path = backend.mount(vm, disk, DiskMount("disk-9", "corpus"))
        assert path == f"{backend.WORKER_MOUNT_DIR}/corpus"
        (holder,) = api.list_pods(backend._namespace,
                                  label_selector="lzy/role=mount-holder")
        claim_vols = [v for v in holder["spec"]["volumes"]
                      if "persistentVolumeClaim" in v]
        assert claim_vols[0]["persistentVolumeClaim"]["claimName"] == \
            PvcDiskManager.claim_name("disk-9")
        # scheduled next to the worker pod
        affinity = holder["spec"]["affinity"]["podAffinity"]
        rule = affinity["requiredDuringSchedulingIgnoredDuringExecution"][0]
        assert rule["labelSelector"]["matchLabels"] == {"lzy/vm-id": "vm-1"}
        # idempotent re-mount (durable resume)
        backend.mount(vm, disk, DiskMount("disk-9", "corpus"))
        assert len(api.list_pods(backend._namespace,
                                 label_selector="lzy/role=mount-holder")) == 1

        backend.unmount(vm, "corpus")
        assert api.list_pods(backend._namespace,
                             label_selector="lzy/role=mount-holder") == []

    def test_destroy_reaps_holder_pods(self):
        from lzy_tpu.service.harness import DEFAULT_POOLS

        api, backend = self._backend()
        vm = self._vm()
        pool = next(p for p in DEFAULT_POOLS if p.label == "tpu-v5e-8")
        backend.launch(vm, pool)
        disk = Disk(id="disk-9", spec=DiskSpec(name="d"), meta=DiskMeta())
        backend.mount(vm, disk, DiskMount("disk-9", "corpus"))
        backend.destroy(vm)
        assert api.list_pods(backend._namespace) == []


class TestMountNameValidation:
    def test_hostile_names_rejected(self, tmp_path):
        from lzy_tpu.service.disks import validate_mount_name

        for bad in ("x; touch /pwned", "a/b", "UPPER", "under_score", "",
                    "-leading", "a" * 64):
            with pytest.raises(ValueError):
                validate_mount_name(bad)
        with pytest.raises(ValueError):
            DiskMount("disk-1", "bad name")
        assert validate_mount_name("data-v2") == "data-v2"
