"""Control-plane crash recovery: the gateway that can die.

THE acceptance property (ISSUE 15): a gateway process death mid-stream
— greedy AND sampled rows in flight — followed by a restart yields
byte-identical output via the ORIGINAL resume token, with adopted (not
re-leased) replicas, zero failed requests in the chaos soak at
``gateway.crash`` rate 1.0, and every journaled live request accounted
for by the recovery auditor (re-attached, re-submitted-at-fence, or
terminally failed with a typed status — never silently dropped).

The journal's degradation contract rides along: a failing durable
append (``journal.append`` chaos at rate 1.0) is a counted warning and
a memory-only record, never a failed request.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.chaos.faults import CHAOS, CRASH, ERROR, FaultPlan
from lzy_tpu.chaos.invariants import (
    FenceAuditor, InvariantViolation, audit_recovery)
from lzy_tpu.durable.failures import InjectedCrash
from lzy_tpu.durable.store import OperationStore
from lzy_tpu.gateway import (
    GatewayJournal, GatewayService, PrefixAffinityRouter, ReplicaFleet,
    recover_gateway, simulate_gateway_death)
from lzy_tpu.gateway.journal import ORPHANED
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import InferenceEngine, PagedInferenceEngine

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _make_ctx(cfg, params, *, replicas=2, slots=2, paged=False,
              sharded=False, allocator=None, store=None, **engine_kw):
    """A journal-backed gateway fleet plus everything a successor needs
    (the factory, the shared store, the fence auditor)."""
    store = store if store is not None else OperationStore(":memory:")
    journal = GatewayJournal(store)

    def factory():
        if sharded:
            from lzy_tpu.serving.sharded import ShardedPagedInferenceEngine

            return ShardedPagedInferenceEngine(cfg, params, slots=slots,
                                               page_size=PAGE, tp=2,
                                               **engine_kw)
        if paged:
            return PagedInferenceEngine(cfg, params, slots=slots,
                                        page_size=PAGE, **engine_kw)
        return InferenceEngine(cfg, params, slots=slots, **engine_kw)

    fleet = ReplicaFleet(factory, allocator=allocator)
    auditor = FenceAuditor()
    gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                        model_name="tiny", journal=journal)
    gw.fence_auditor = auditor
    for _ in range(replicas):
        fleet.add_replica()
    return {
        "gw": gw, "journal": journal, "factory": factory,
        "auditor": auditor, "allocator": allocator,
        "recoveries": 0, "reports": [],
    }


def _kill_and_recover(ctx, *, dead_replicas=(), engine_source=None):
    """Simulate the gateway process death, then build + recover a
    successor sharing the journal. ``dead_replicas`` close those
    engines first (a lease that died WITH the process). Runs the
    recovery auditor against the pre-death live snapshot."""
    old = ctx["gw"]
    pre_live = ctx["journal"].live_requests()
    engines = {}
    from lzy_tpu.gateway.fleet import DRAINING

    for replica in (old.fleet.replicas()
                    + old.fleet.replicas(state=DRAINING)):
        engines[replica.id] = replica.engine
    for rid in dead_replicas:
        engines[rid].close()
    simulate_gateway_death(old)
    fleet2 = ReplicaFleet(ctx["factory"], allocator=ctx["allocator"])
    gw2 = GatewayService(fleet2, router=PrefixAffinityRouter(PAGE),
                         model_name="tiny", journal=ctx["journal"],
                         kv_index=old.kv_index)
    gw2.fence_auditor = ctx["auditor"]
    src = engine_source if engine_source is not None \
        else (lambda rid, vms: engines.get(rid))
    report = recover_gateway(gw2, engine_source=src,
                             allocator=ctx["allocator"])
    audit_recovery(ctx["journal"], gw2, pre_live)
    ctx["gw"] = gw2
    ctx["recoveries"] += 1
    ctx["reports"].append(report)
    return report, engines


def _poll_until(gw, rid, pos, *, min_tokens=1, budget_s=60.0):
    """Poll one stream until at least ``min_tokens`` NEW tokens arrived
    (or done); returns (new_tokens, new_pos, last_frame)."""
    out = []
    deadline = time.monotonic() + budget_s
    frame = None
    while len(out) < min_tokens and time.monotonic() < deadline:
        frame = gw.streams.poll(rid, pos, wait_s=1.0)
        out.extend(frame["tokens"])
        pos += len(frame["tokens"])
        if frame["done"]:
            break
    assert frame is not None and (len(out) >= min_tokens
                                  or frame["done"]), \
        f"stream {rid} produced {len(out)} tokens in {budget_s}s"
    return out, pos, frame


def _drain(gw, rid, pos, *, budget_s=120.0):
    """Poll to the done frame; returns (tokens_from_pos, final_frame)."""
    out = []
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        frame = gw.streams.poll(rid, pos, wait_s=2.0)
        out.extend(frame["tokens"])
        pos += len(frame["tokens"])
        if frame["done"]:
            return out, frame
    raise AssertionError(f"stream {rid} not done within {budget_s}s")


class TestJournalDegrade:
    """journal.append failure = degraded-to-memory with a counted
    warning, NEVER a failed request."""

    def test_appends_degrade_to_memory_under_chaos(self, tiny_model):
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw, journal = ctx["gw"], ctx["journal"]
        plan = FaultPlan(991, rate=1.0, modes=(ERROR,),
                         points=("journal.append",))
        CHAOS.arm(plan)
        try:
            res = gw.generate([5, 9, 3], max_new_tokens=4, timeout_s=120)
            assert res["status"] == "ok"
            assert res["tokens"] == _oracle_tokens(cfg, params,
                                                   [5, 9, 3], 4)
        finally:
            CHAOS.disarm()
            gw.close()
        assert journal.degraded >= 2          # birth + finish at least
        # the in-memory mirror still carries the (settled) record
        docs = journal.requests()
        assert any(d.get("status") == "terminal" for d in docs.values())

    def test_unit_roundtrip(self):
        journal = GatewayJournal(OperationStore(":memory:"))
        rid = journal.record_birth(prompt=[1, 2], max_new_tokens=8,
                                   streamed=True, tenant="t0",
                                   session="conv-1")
        journal.record_attempt(rid, "replica-1")
        journal.advance_fence(rid, 0, [4, 5])
        journal.advance_fence(rid, 0, [4])    # covered range = no-op
        journal.advance_fence(rid, 5, [9])    # gap = refused
        journal.advance_fence(rid, 1, [7, 8])  # diverging overlap = drop
        doc = journal.live_requests()[rid]
        assert doc["fence"] == [4, 5] and doc["routed"] == ["replica-1"]
        journal.finish(rid, "ok", fence=[4, 5, 6], reply={"replica": "r"})
        doc = journal.requests()[rid]
        assert doc["status"] == "terminal" and doc["terminal"] == "ok"
        assert doc["fence"] == [4, 5, 6]
        journal.forget(rid)
        assert rid not in journal.requests()

    def test_fence_delta_parts_reassemble_across_processes(self):
        """Fence advances journal O(frame) DELTA parts; a successor
        journal (fresh instance, same store — the cross-process path)
        reassembles the full fence from them."""
        store = OperationStore(":memory:")
        a = GatewayJournal(store)
        rid = a.record_birth(prompt=[9], max_new_tokens=16,
                             streamed=True)
        a.advance_fence(rid, 0, [1, 2])
        a.advance_fence(rid, 2, [3, 4, 5])
        # an overlapping frame (a re-polled range + new tail) appends
        # only the genuinely-new suffix
        a.advance_fence(rid, 3, [4, 5, 6])
        b = GatewayJournal(store)             # the successor's view
        doc = b.live_requests()[rid]
        assert doc["fence"] == [1, 2, 3, 4, 5, 6]
        # forget drops the parts too
        b.forget(rid)
        assert rid not in b.requests()
        c = GatewayJournal(store)
        assert c._assembled_fences() == {}

    def test_lease_roundtrip_with_pool_tag(self):
        journal = GatewayJournal(OperationStore(":memory:"))
        journal.record_lease("decode-1", ["vm-1", "vm-2"], "sess-9",
                             pool="decode")
        doc = journal.leases()["decode-1"]
        assert doc["vm_ids"] == ["vm-1", "vm-2"]
        assert doc["pool"] == "decode"
        journal.forget_lease("decode-1")
        assert journal.leases() == {}


class TestKillTheGateway:
    """THE acceptance test: death mid-stream, greedy and sampled rows
    in flight, byte-identical resume via the ORIGINAL tokens."""

    def test_mid_stream_death_greedy_and_sampled(self, tiny_model):
        cfg, params = tiny_model
        # a sampling fleet with a per-request greedy override: exactly
        # the mixed traffic the soak runs
        ctx = _make_ctx(cfg, params, replicas=2,
                        temperature=0.8, top_k=20, seed=7)
        gw = ctx["gw"]
        n = 20
        g_prompt, s_prompt = [7, 2, 8, 1], [5, 9, 3, 4]
        g_open = gw.streams.open(g_prompt, max_new_tokens=n,
                                 timeout_s=120, greedy=True)
        s_open = gw.streams.open(s_prompt, max_new_tokens=n,
                                 timeout_s=120)
        g_rid, s_rid = g_open["request_id"], s_open["request_id"]
        g_seen, g_pos, _ = _poll_until(gw, g_rid, 0, min_tokens=4)
        s_seen, s_pos, _ = _poll_until(gw, s_rid, 0, min_tokens=4)

        old_ids = sorted(r.id for r in gw.fleet.replicas())
        report, engines = _kill_and_recover(ctx)
        gw2 = ctx["gw"]
        try:
            # adopted, not re-leased: same ids, same ENGINE OBJECTS
            assert sorted(report.adopted) == old_ids
            assert not report.dropped_leases
            for replica in gw2.fleet.replicas():
                assert replica.engine is engines[replica.id]
            assert sorted(report.resubmitted) == sorted([g_rid, s_rid])

            # the ORIGINAL resume tokens, from the clients' positions
            g_rest, g_frame = _drain(gw2, g_rid, g_pos)
            s_rest, s_frame = _drain(gw2, s_rid, s_pos)
            g_final = g_seen + g_rest
            s_final = s_seen + s_rest
            assert g_frame["status"] == "ok" and s_frame["status"] == "ok"
            # greedy: byte-identical to an uninterrupted generate()
            assert g_final == _oracle_tokens(cfg, params, g_prompt, n)
            # sampled: the fence never repeats or drops a token and the
            # stream completes to the full budget
            assert s_final[:len(s_seen)] == s_seen
            assert len(s_final) == n
            assert g_frame["resumptions"] >= 1
            # re-polling position 0 on the SUCCESSOR replays the whole
            # stream byte-identically (idempotent frames survive death)
            replay, _ = _drain(gw2, g_rid, 0)
            assert replay == g_final
        finally:
            gw2.close()

    def test_adoption_preserves_leases(self, tiny_model):
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.allocator import RUNNING

        cfg, params = tiny_model
        cluster = InProcessCluster()
        ctx = _make_ctx(cfg, params, replicas=2,
                        allocator=cluster.allocator)
        gw = ctx["gw"]
        try:
            lease_by_id = {r.id: list(r.vm_ids)
                           for r in gw.fleet.replicas()}
            assert all(lease_by_id.values())
            vms_before = sorted(v.id for v in cluster.allocator.vms())
            report, _ = _kill_and_recover(ctx)
            gw2 = ctx["gw"]
            # no new VMs were allocated and every adopted replica holds
            # its ORIGINAL gang, still RUNNING
            assert sorted(v.id for v in cluster.allocator.vms()) == \
                vms_before
            for replica in gw2.fleet.replicas():
                assert list(replica.vm_ids) == lease_by_id[replica.id]
                for vm_id in replica.vm_ids:
                    assert cluster.allocator.vm(vm_id).status == RUNNING
            res = gw2.generate([5, 9, 3], max_new_tokens=3,
                               timeout_s=120)
            assert res["status"] == "ok"
        finally:
            ctx["gw"].close()
            cluster.shutdown()

    def test_dead_lease_dropped_and_freed(self, tiny_model):
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.allocator import IDLE

        cfg, params = tiny_model
        cluster = InProcessCluster()
        ctx = _make_ctx(cfg, params, replicas=2,
                        allocator=cluster.allocator)
        gw = ctx["gw"]
        try:
            victim = gw.fleet.replicas()[0]
            report, _ = _kill_and_recover(ctx,
                                          dead_replicas=(victim.id,))
            gw2 = ctx["gw"]
            assert victim.id in report.dropped_leases
            assert victim.id not in [r.id for r in gw2.fleet.replicas()]
            assert victim.id not in ctx["journal"].leases()
            # the dead replica's gang went back to the session cache
            for vm_id in victim.vm_ids:
                assert cluster.allocator.vm(vm_id).status == IDLE
        finally:
            ctx["gw"].close()
            cluster.shutdown()

    def test_boot_recovery_never_drops_the_live_fleets_leases(
            self, tiny_model):
        """The serve.py boot path recovers AFTER the builders populated
        a fresh fleet (whose add_replica just journaled its own leases
        under the same ids a predecessor used): recovery must skip
        those rows — dropping them would forget the journal AND free
        RUNNING gangs the live fleet is using."""
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.allocator import RUNNING

        cfg, params = tiny_model
        cluster = InProcessCluster()
        ctx = _make_ctx(cfg, params, replicas=2,
                        allocator=cluster.allocator)
        gw = ctx["gw"]
        try:
            report = recover_gateway(gw, engine_source=None,
                                     allocator=cluster.allocator)
            assert report.dropped_leases == []
            assert report.adopted == []
            assert sorted(ctx["journal"].leases()) == \
                sorted(r.id for r in gw.fleet.replicas())
            for replica in gw.fleet.replicas():
                for vm_id in replica.vm_ids:
                    assert cluster.allocator.vm(vm_id).status == RUNNING
            res = gw.generate([5, 9, 3], max_new_tokens=3,
                              timeout_s=120)
            assert res["status"] == "ok"
        finally:
            gw.close()
            cluster.shutdown()

    def test_lost_final_frame_window(self, tiny_model):
        """The predecessor FINISHED the generation but died before the
        client read the done frame: the successor rehydrates the
        terminal session and the old resume token reads the tail."""
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw = ctx["gw"]
        n = 8
        opened = gw.streams.open([7, 2, 8, 1], max_new_tokens=n,
                                 timeout_s=120)
        rid = opened["request_id"]
        sess = gw.streams._get(rid)
        assert sess.finished.wait(60.0)       # server-side complete
        seen, pos, _ = _poll_until(gw, rid, 0, min_tokens=2)
        report, _ = _kill_and_recover(ctx)
        gw2 = ctx["gw"]
        try:
            assert rid in report.rehydrated_terminal
            rest, frame = _drain(gw2, rid, pos)
            assert frame["status"] == "ok"
            assert seen + rest == _oracle_tokens(cfg, params,
                                                 [7, 2, 8, 1], n)
            assert frame["reply"].get("status") == "ok"
        finally:
            gw2.close()

    def test_unary_request_orphaned_with_typed_status(self, tiny_model):
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw = ctx["gw"]
        done = {}

        def run():
            try:
                done["res"] = gw.generate([6, 1, 2], max_new_tokens=48,
                                          timeout_s=120)
            except BaseException as e:  # noqa: BLE001
                done["err"] = e

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 60
        journal = ctx["journal"]
        while time.monotonic() < deadline and not journal.live_requests():
            time.sleep(0.005)
        assert journal.live_requests(), "unary birth never journaled"
        report, _ = _kill_and_recover(ctx)
        gw2 = ctx["gw"]
        try:
            assert len(report.orphaned) == 1
            rid = report.orphaned[0]
            doc = journal.requests()[rid]
            assert doc["status"] == "terminal"
            assert doc["terminal"] == ORPHANED
            t.join(120)
        finally:
            gw2.close()

    def test_successor_with_fresh_journal_instance_keeps_journaling(
            self, tiny_model):
        """The REAL cross-process shape: the successor constructs its
        OWN GatewayJournal over the same store. Recovery must hydrate
        the new journal's mirror, or every later fence advance and the
        terminal settle would no-op and the store record would stay
        live-with-a-stale-fence — resubmitting an already-finished
        request on the NEXT death."""
        cfg, params = tiny_model
        store = OperationStore(":memory:")
        ctx = _make_ctx(cfg, params, replicas=1, store=store)
        gw = ctx["gw"]
        n = 12
        prompt = [7, 2, 8, 1]
        opened = gw.streams.open(prompt, max_new_tokens=n, timeout_s=120)
        rid = opened["request_id"]
        seen, pos, _ = _poll_until(gw, rid, 0, min_tokens=3)
        engines = {r.id: r.engine for r in gw.fleet.replicas()}
        simulate_gateway_death(gw)
        journal2 = GatewayJournal(store)       # FRESH instance
        fleet2 = ReplicaFleet(ctx["factory"])
        gw2 = GatewayService(fleet2, router=PrefixAffinityRouter(PAGE),
                             model_name="tiny", journal=journal2)
        report = recover_gateway(
            gw2, engine_source=lambda r, vms: engines.get(r))
        try:
            assert rid in report.resubmitted
            rest, frame = _drain(gw2, rid, pos)
            assert frame["status"] == "ok"
            final = seen + rest
            assert final == _oracle_tokens(cfg, params, prompt, n)
            sess = gw2.streams._get(rid)
            assert sess.finished.wait(30.0)
            # a THIRD journal instance (the next process) must see the
            # record settled with the full fence — proof the successor
            # kept journaling through its fresh instance
            journal3 = GatewayJournal(store)
            doc = journal3.requests()[rid]
            assert doc["status"] == "terminal"
            assert doc["terminal"] == "ok"
            assert doc["fence"] == final
        finally:
            gw2.close()

    def test_malformed_prompt_does_not_leak_a_session(self, tiny_model):
        """A prompt the journal birth cannot serialize must unwind the
        registered session (a leak would count toward max_sessions
        forever) and surface the typed bad-prompt error."""
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw = ctx["gw"]
        try:
            for _ in range(3):
                with pytest.raises((ValueError, TypeError)):
                    gw.streams.open(["not-a-token"], max_new_tokens=4)
            assert gw.streams.sessions() == []
            assert ctx["journal"].live_requests() == {}
        finally:
            gw.close()

    def test_auditor_catches_a_silent_drop(self, tiny_model):
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw, journal = ctx["gw"], ctx["journal"]
        try:
            rid = journal.record_birth(prompt=[1, 2], max_new_tokens=4,
                                       streamed=True)
            pre_live = journal.live_requests()
            # a "recovery" that neither re-attaches nor settles
            with pytest.raises(InvariantViolation, match="silently"):
                audit_recovery(journal, gw, pre_live)
        finally:
            gw.close()


class TestGangRecovery:
    """Sharded gang replicas recover ALL-OR-NOTHING: a journaled lease
    whose gang lost even one shard host while the gateway was down is
    never re-adopted — the SPMD programs span every shard, so a partial
    gang has no degraded mode. The lease is dropped whole (journal row
    forgotten, engine closed); intact gangs adopt exactly like
    single-device replicas."""

    def test_gang_with_dead_host_dropped_whole_intact_gang_adopted(
            self, tiny_model):
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=2, sharded=True)
        gw = ctx["gw"]
        engines = {}
        try:
            # both gangs serve before the crash
            res = gw.generate([5, 9, 3], max_new_tokens=3, timeout_s=120)
            assert res["status"] == "ok"
            victim, survivor = gw.fleet.replicas()
            engines.update({r.id: r.engine for r in gw.fleet.replicas()})

            def src(rid, vms):
                eng = engines.get(rid)
                if rid == victim.id and eng is not None:
                    # one shard host died WITH the gateway: the recovering
                    # successor must see gang_intact False and refuse the
                    # whole lease, not adopt a 1-of-2 gang
                    eng.mark_host_dead(1, "host lost in the outage")
                return eng

            report, _ = _kill_and_recover(ctx, engine_source=src)
            gw2 = ctx["gw"]
            assert victim.id in report.dropped_leases
            assert victim.id not in ctx["journal"].leases()
            ids = [r.id for r in gw2.fleet.replicas()]
            assert victim.id not in ids
            # the intact gang was ADOPTED (same engine object, no
            # rebuild) and still serves bit-identically
            assert survivor.id in ids
            adopted = next(r for r in gw2.fleet.replicas()
                           if r.id == survivor.id)
            assert adopted.engine is engines[survivor.id]
            assert adopted.engine.gang_size == 2
            res = gw2.generate([5, 9, 3], max_new_tokens=3,
                               timeout_s=120)
            assert res["status"] == "ok"
        finally:
            ctx["gw"].close()
            for eng in engines.values():
                if not getattr(eng, "closed", False):
                    eng.close()


class TestDisaggRecovery:
    """A disagg gateway journals BOTH pools: recovery adopts each lease
    into its own fleet and each fleet back onto its OWN allocator
    session (decode vs prefill sessions must never cross — freeing a
    gang into the wrong pool's cache or double-deleting one session on
    shutdown)."""

    def test_adopts_each_pool_onto_its_own_session(self):
        from lzy_tpu.gateway import DisaggGatewayService
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.inference import build_disagg_gateway_service

        cluster = InProcessCluster()
        store = OperationStore(":memory:")
        journal = GatewayJournal(store)
        svc = build_disagg_gateway_service(
            "tiny", prefill_replicas=1, decode_replicas=1, slots=2,
            start=False, journal=journal, allocator=cluster.allocator)
        try:
            decode_sess = svc.fleet._session_id
            prefill_sess = svc.prefill_fleet._session_id
            assert decode_sess and prefill_sess
            assert decode_sess != prefill_sess
            engines = {r.id: r.engine for r in svc.fleet.replicas()}
            engines.update({r.id: r.engine
                            for r in svc.prefill_fleet.replicas()})
            simulate_gateway_death(svc)

            d2 = ReplicaFleet(lambda: None,
                              allocator=cluster.allocator,
                              session_owner="disagg-decode",
                              replica_prefix="decode")
            p2 = ReplicaFleet(lambda: None,
                              allocator=cluster.allocator,
                              session_owner="disagg-prefill",
                              replica_prefix="prefill")
            gw2 = DisaggGatewayService(d2, p2, page_size=16,
                                       model_name="tiny",
                                       journal=GatewayJournal(store))
            report = recover_gateway(
                gw2, engine_source=lambda r, vms: engines.get(r),
                allocator=cluster.allocator)
            try:
                assert sorted(report.adopted) == ["decode-1",
                                                 "prefill-1"]
                assert [r.id for r in d2.replicas()] == ["decode-1"]
                assert [r.id for r in p2.replicas()] == ["prefill-1"]
                # each pool re-adopted ITS OWN allocator session
                assert d2._session_id == decode_sess
                assert p2._session_id == prefill_sess
                res = gw2.generate([5, 9, 3], max_new_tokens=3,
                                   timeout_s=120)
                assert res["status"] == "ok"
            finally:
                gw2.close()
        finally:
            cluster.shutdown()


class TestKvIndexRecovery:
    """Satellite: the fleet-global prefix index is force-refreshed from
    every adopted replica BEFORE the first routed request, and rows of
    leases that died with the old process are forgotten."""

    def test_index_repopulated_before_first_routed_request(self,
                                                           tiny_model):
        from lzy_tpu.gateway.kv_index import GlobalKVIndex

        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=2, paged=True,
                        kv_host_tier_bytes=1 << 20)
        gw = ctx["gw"]
        gw.kv_index = GlobalKVIndex(PAGE)
        prompt = list(range(2 * PAGE)) + [3]
        res = gw.generate(prompt, max_new_tokens=2, timeout_s=120)
        assert res["status"] == "ok"
        warm = res["replica"]
        gw.tick()
        assert gw.kv_index.stats()["replicas_advertising"] >= 1

        report, _ = _kill_and_recover(ctx)
        gw2 = ctx["gw"]
        try:
            # BEFORE any tick or request on the successor: the index is
            # already whole (recovery force-refreshed it), and the
            # flag re-asserts the refresh on the first tick
            stats = gw2.kv_index.stats()
            assert warm in stats["indexed_chains"]
            assert stats["indexed_chains"][warm] >= 2
            assert gw2._kv_force_refresh is True
            gw2.tick()
            assert gw2._kv_force_refresh is False
        finally:
            gw2.close()

    def test_dead_lease_rows_forgotten(self, tiny_model):
        from lzy_tpu.gateway.kv_index import GlobalKVIndex

        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=2, paged=True,
                        kv_host_tier_bytes=1 << 20)
        gw = ctx["gw"]
        gw.kv_index = GlobalKVIndex(PAGE)
        prompt = list(range(2 * PAGE)) + [3]
        # warm BOTH replicas' caches so both advertise
        for replica in gw.fleet.replicas():
            req = replica.engine.submit(prompt, max_new_tokens=2)
            assert req.result(timeout=120) is not None
        gw.tick()
        assert gw.kv_index.stats()["replicas_advertising"] == 2
        victim = gw.fleet.replicas()[0].id
        report, _ = _kill_and_recover(ctx, dead_replicas=(victim,))
        gw2 = ctx["gw"]
        try:
            assert victim in report.dropped_leases
            stats = gw2.kv_index.stats()
            assert victim not in stats["indexed_chains"]
            assert stats["replicas_advertising"] == 1
        finally:
            gw2.close()


def _run_with_recovery(ctx, prompt, n, *, greedy):
    """Drive one streamed request to completion, treating every
    injected gateway.crash — surfaced as an InjectedCrash from
    open/poll or as an error frame naming the injected crash — as a
    process death: kill, recover, resume at the SAME (request_id,
    position). Returns the full token list."""
    pos, out, rid = 0, [], None
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        gw = ctx["gw"]
        try:
            if rid is None:
                opened = gw.streams.open(prompt, max_new_tokens=n,
                                         timeout_s=120, greedy=greedy)
                rid = opened["request_id"]
            frame = gw.streams.poll(rid, pos, wait_s=2.0)
        except InjectedCrash:
            _kill_and_recover(ctx)
            continue
        if frame["done"] and frame.get("status") == "error":
            err = frame.get("error") or ""
            assert "injected crash" in err, \
                f"unexpected stream failure: {err}"
            _kill_and_recover(ctx)
            continue
        out.extend(frame["tokens"])
        pos += len(frame["tokens"])
        if frame["done"]:
            assert frame["status"] == "ok", frame
            return out
    raise AssertionError("request did not finish under chaos")


@pytest.mark.chaos
class TestGatewayCrashSoak:
    """gateway.crash at rate 1.0: every hit on the journal-backed
    request path dies until max_faults runs out — zero failed requests,
    greedy rows byte-identical to the oracle, recovery audited after
    every death."""

    def test_fixed_seed_crash_soak(self, tiny_model):
        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=2,
                        temperature=0.8, top_k=20, seed=11)
        n = 10
        rows = [([7, 2, 8, 1], True), ([5, 9, 3], False),
                ([9, 1, 4, 6], True), ([3, 3, 8], False)]
        plan = FaultPlan(1234, rate=1.0, modes=(CRASH,),
                         points=("gateway.crash",), max_faults=4)
        CHAOS.arm(plan)
        try:
            results = [
                _run_with_recovery(ctx, p, n, greedy=g)
                for p, g in rows
            ]
        finally:
            CHAOS.disarm()
            ctx["gw"].close()
        assert plan.fired >= 1, "the crash point never fired"
        assert ctx["recoveries"] >= 1
        for (prompt, greedy), tokens in zip(rows, results):
            assert len(tokens) == n
            if greedy:
                assert tokens == _oracle_tokens(cfg, params, prompt, n)

    @pytest.mark.skipif(
        not __import__("os").environ.get("LZY_SLOW"),
        reason="multi-seed gateway-death soak: set LZY_SLOW=1")
    def test_slow_multi_seed_soak(self, tiny_model):
        cfg, params = tiny_model
        for seed in (1, 2, 3):
            ctx = _make_ctx(cfg, params, replicas=2,
                            temperature=0.8, top_k=20, seed=seed)
            n = 12
            rows = [([7 + seed, 2, 8, 1], True), ([5, 9, 3 + seed], False),
                    ([2, 4, 6, 8], True), ([1, 1, 2 + seed], False),
                    ([6, 5, 4], True), ([8, 8, 1], False)]
            plan = FaultPlan(seed * 101, rate=1.0, modes=(CRASH,),
                             points=("gateway.crash",), max_faults=6)
            CHAOS.arm(plan)
            try:
                results = [
                    _run_with_recovery(ctx, p, n, greedy=g)
                    for p, g in rows
                ]
            finally:
                CHAOS.disarm()
                ctx["gw"].close()
            for (prompt, greedy), tokens in zip(rows, results):
                assert len(tokens) == n
                if greedy:
                    assert tokens == _oracle_tokens(cfg, params,
                                                    prompt, n)
            assert ctx["auditor"].completions_seen >= 1


class _FakeClock:
    """Recording clock for the reconnect-ladder test: time advances a
    bit per read so deadlines move; sleeps are recorded, not slept."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def time(self):
        self.t += 0.001
        return self.t

    def now(self):
        return self.time()

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


class _FlakyRpc:
    """JsonRpcClient stand-in routing stream methods at a live
    StreamSessionManager, with a connection-refused window (the gateway
    restart) injected per call."""

    def __init__(self, manager):
        self.manager = manager
        self.fail_next = 0
        self.failures_seen = 0

    def call(self, method, payload=None, timeout_s=None, *,
             retry=False, idempotency_key=None):
        from lzy_tpu.rpc.core import Unavailable

        payload = payload or {}
        if self.fail_next > 0:
            self.fail_next -= 1
            self.failures_seen += 1
            raise Unavailable("connection refused (gateway restarting)")
        if method == "InferStream":
            return self.manager.open(
                payload["prompt"],
                max_new_tokens=payload["max_new_tokens"],
                timeout_s=payload.get("timeout_s"),
                greedy=payload.get("greedy"))
        if method == "InferStreamPoll":
            return self.manager.poll(
                payload["request_id"], payload.get("position", 0),
                wait_s=payload.get("wait_s", 1.0))
        if method == "InferCancel":
            return self.manager.cancel(payload["request_id"])
        raise KeyError(method)

    def close(self):
        pass


class TestReconnectLadder:
    """Satellite: connection refused during the restart → backoff →
    resume at the fence on the successor, with a resume token minted by
    the PREDECESSOR process."""

    def test_ladder_resumes_at_fence_on_successor(self, tiny_model):
        from lzy_tpu.rpc.control import RpcInferenceClient
        from lzy_tpu.utils.backoff import RetryPolicy

        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=2)
        gw = ctx["gw"]
        rpc = _FlakyRpc(gw.streams)
        clock = _FakeClock()
        client = RpcInferenceClient(
            client=rpc, clock=clock,
            reconnect=RetryPolicy(attempts=8, base_s=0.05, cap_s=0.2,
                                  jitter=False))
        n = 16
        prompt = [7, 2, 8, 1]
        opened = client.stream_open(prompt, max_new_tokens=n)
        rid = opened["request_id"]
        tokens = []
        frames = client.iter_stream(rid, 0, wait_s=1.0,
                                    deadline_s=3600.0)
        restarted = False
        try:
            for frame in frames:
                tokens.extend(frame.get("tokens", ()))
                if not restarted and len(tokens) >= 3:
                    # the restart window: the next polls are refused,
                    # the successor recovers the journal, and the SAME
                    # iterator (the predecessor's resume token) rides
                    # the ladder onto the new process
                    _kill_and_recover(ctx)
                    rpc.manager = ctx["gw"].streams
                    rpc.fail_next = 3
                    restarted = True
                if frame.get("done"):
                    assert frame["status"] == "ok"
                    break
        finally:
            ctx["gw"].close()
        assert restarted
        assert rpc.failures_seen == 3
        # the ladder actually backed off between refused polls
        assert len(clock.sleeps) >= 3
        assert all(s > 0 for s in clock.sleeps[:3])
        assert tokens == _oracle_tokens(cfg, params, prompt, n)

    def test_ladder_gives_up_past_budget(self, tiny_model):
        from lzy_tpu.rpc.control import RpcInferenceClient
        from lzy_tpu.rpc.core import Unavailable
        from lzy_tpu.utils.backoff import RetryPolicy

        cfg, params = tiny_model
        ctx = _make_ctx(cfg, params, replicas=1)
        gw = ctx["gw"]
        rpc = _FlakyRpc(gw.streams)
        client = RpcInferenceClient(
            client=rpc, clock=_FakeClock(),
            reconnect=RetryPolicy(attempts=3, base_s=0.01, cap_s=0.01,
                                  jitter=False))
        try:
            opened = client.stream_open([5, 9, 3], max_new_tokens=4)
            rpc.fail_next = 99                # the gateway never returns
            with pytest.raises(Unavailable):
                for _ in client.iter_stream(opened["request_id"], 0,
                                            wait_s=0.5):
                    pass
            assert rpc.failures_seen == 4     # 1 + the 3-attempt ladder
        finally:
            gw.close()
