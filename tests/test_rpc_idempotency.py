"""Client retry + idempotency keys on mutating RPCs (VERDICT r2 #4).

The reference retries transient statuses everywhere
(``pylzy/lzy/utils/grpc.py:240``) and dedups server-side
(``IdempotencyUtils.java``). The critical case: the server COMMITS a
mutation but the reply is lost — the client's retry must not double-apply.
"""

import threading
import time
import types

import pytest

from lzy_tpu.rpc.control import ControlPlaneServer, RpcWorkflowClient
from lzy_tpu.rpc.core import JsonRpcClient, JsonRpcServer, Unavailable
from lzy_tpu.service import InProcessCluster


class ReplyLoss:
    """Service proxy: named methods COMMIT, then the reply is dropped
    (UNAVAILABLE) for the first ``n`` calls — the lost-reply window."""

    def __init__(self, target, methods, n=1):
        self._target = target
        self._drop = {m: n for m in methods}
        self.calls = {m: 0 for m in methods}

    def __getattr__(self, name):
        attr = getattr(self._target, name)
        if name not in self._drop or not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            self.calls[name] += 1
            result = attr(*args, **kwargs)
            if self._drop[name] > 0:
                self._drop[name] -= 1
                raise Unavailable("injected reply loss after commit")
            return result

        return wrapped


@pytest.fixture()
def cluster(tmp_path):
    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        poll_period_s=0.05,
    )
    yield c
    c.shutdown()


@pytest.fixture()
def flaky_plane(cluster):
    """Control plane whose workflow service commits, then loses the first
    reply of each listed mutation."""
    flaky = ReplyLoss(cluster.workflow_service,
                      ["start_workflow", "finish_workflow"])
    ns = types.SimpleNamespace(
        workflow_service=flaky,
        channels=cluster.channels,
        allocator=cluster.allocator,
        iam=cluster.iam,
        store=cluster.store,
    )
    server = ControlPlaneServer(ns)
    client = RpcWorkflowClient(server.address)
    yield cluster, flaky, client
    client.close()
    server.stop()


class TestExactlyOnce:
    def test_lost_reply_does_not_double_start(self, flaky_plane):
        cluster, flaky, client = flaky_plane
        execution_id = client.start_workflow(
            "user", "wf", cluster.storage_uri,
            client_version="99.0.0",
        )
        # the server ran the mutation twice over the wire, but the second
        # call replayed the first outcome: one execution, one session
        assert flaky.calls["start_workflow"] == 2
        executions = cluster.store.kv_list("executions")
        assert list(executions) == [execution_id]
        sessions = cluster.store.kv_list("sessions")
        assert len(sessions) == 1

        # finish: same lost-reply window; teardown must run exactly once
        client.finish_workflow(execution_id)
        assert flaky.calls["finish_workflow"] == 2
        doc = cluster.store.kv_get("executions", execution_id)
        assert doc["status"] == "FINISHED"
        assert cluster.store.kv_list("sessions") == {}

    def test_failures_replay_not_rerun(self, cluster):
        svc = cluster.workflow_service
        runs = {"n": 0}
        orig = svc._start_workflow

        def counting(*args, **kwargs):
            runs["n"] += 1
            return orig(*args, **kwargs)

        # occupy an execution id so the keyed attempt fails INSIDE the
        # deduped fn (authz/version failures happen before dedup by design
        # — see start_workflow — so they are re-checked, not replayed)
        taken = svc.start_workflow("u", "wf", cluster.storage_uri,
                                   execution_id="exec-taken",
                                   client_version="0.1.0")
        svc._start_workflow = counting
        try:
            with pytest.raises(RuntimeError, match="already exists"):
                svc.start_workflow("u", "wf", cluster.storage_uri,
                                   execution_id="exec-taken",
                                   client_version="0.1.0",
                                   idempotency_key="k-fail")
            # the retry with the same key replays the recorded error without
            # re-executing (exactly-once also for failed outcomes)
            with pytest.raises(RuntimeError, match="already exists"):
                svc.start_workflow("u", "wf", cluster.storage_uri,
                                   execution_id="exec-taken",
                                   client_version="0.1.0",
                                   idempotency_key="k-fail")
        finally:
            svc._start_workflow = orig
        assert runs["n"] == 1
        assert list(cluster.store.kv_list("executions")) == [taken]

    def test_version_gate_rechecked_not_replayed(self, cluster):
        """Authz + version gating run BEFORE the idempotent wrapper
        (ADVICE r3): a duplicate carrying a known key must not bypass
        them, and a gate failure is re-checked fresh on every attempt."""
        svc = cluster.workflow_service
        with pytest.raises(RuntimeError, match="unsupported client"):
            svc.start_workflow("u", "wf", cluster.storage_uri,
                               client_version="0.0.1",
                               idempotency_key="k-gate")
        # same key, fixed client: the gate passes and the call EXECUTES
        # (the failed attempt never reached the dedup record)
        execution_id = svc.start_workflow("u", "wf", cluster.storage_uri,
                                          client_version="0.1.0",
                                          idempotency_key="k-gate")
        assert execution_id in cluster.store.kv_list("executions")

    def test_cross_subject_key_does_not_replay(self, tmp_path):
        """Idempotency records are scoped per authenticated subject: B
        presenting A's key must run B's own mutation, not silently replay
        (and leak) A's recorded execution id (confused-deputy guard)."""
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            with_iam=True,
        )
        try:
            svc = c.workflow_service
            tok_a = c.iam.create_subject("alice")
            tok_b = c.iam.create_subject("bob")
            exec_a = svc.start_workflow(
                "alice", "wf", c.storage_uri, token=tok_a,
                client_version="0.1.0", idempotency_key="shared-key")
            exec_b = svc.start_workflow(
                "bob", "wf", c.storage_uri, token=tok_b,
                client_version="0.1.0", idempotency_key="shared-key")
            assert exec_a != exec_b
            owners = {k: v["user"]
                      for k, v in c.store.kv_list("executions").items()}
            assert owners[exec_a] == "alice" and owners[exec_b] == "bob"
            # while A's own retry still replays
            again = svc.start_workflow(
                "alice", "wf", c.storage_uri, token=tok_a,
                client_version="0.1.0", idempotency_key="shared-key")
            assert again == exec_a
        finally:
            c.shutdown()

    def test_pre_scoping_record_still_replays(self, tmp_path):
        """Upgrade bridge (ADVICE r4): records persisted before keys were
        subject-scoped live under the bare key; a retry that spans the
        upgrade (now authenticated, hence scoped) must replay that
        outcome instead of re-executing the mutation."""
        c = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            with_iam=True,
        )
        monkeypatch = pytest.MonkeyPatch()
        monkeypatch.setenv("LZY_IDEM_LEGACY_BRIDGE", "1")
        try:
            # simulate the pre-upgrade deployment: a settled record under
            # the unscoped key, as the old code would have written it
            c.store.create("op-legacy", "idem.start_workflow", {},
                           idempotency_key="legacy-key")
            c.store.complete("op-legacy", "exec-from-before-the-upgrade")
            tok = c.iam.create_subject("alice")
            replayed = c.workflow_service.start_workflow(
                "alice", "wf", c.storage_uri, token=tok,
                client_version="0.1.0", idempotency_key="legacy-key")
            assert replayed == "exec-from-before-the-upgrade"
            # nothing re-executed: no new execution row appeared
            assert replayed not in c.store.kv_list("executions")
        finally:
            monkeypatch.undo()
            c.shutdown()

    def test_replayed_error_keeps_its_type(self, cluster):
        svc = cluster.workflow_service
        # KeyError (NOT_FOUND over the wire) must replay as KeyError, not a
        # generic RuntimeError that would surface as INTERNAL
        with pytest.raises(KeyError):
            svc.finish_workflow("no-such-exec", idempotency_key="k-nf")
        with pytest.raises(KeyError):
            svc.finish_workflow("no-such-exec", idempotency_key="k-nf")

    def test_key_reuse_across_methods_rejected(self, cluster):
        svc = cluster.workflow_service
        execution_id = svc.start_workflow(
            "u", "wf", cluster.storage_uri, client_version="99.0.0",
            idempotency_key="k-reuse")
        with pytest.raises(ValueError, match="already used"):
            svc.finish_workflow(execution_id, idempotency_key="k-reuse")

    def test_concurrent_duplicate_waits_for_first(self, cluster):
        svc = cluster.workflow_service
        release = threading.Event()
        results = []

        def slow():
            release.wait(5.0)
            return "slow-result"

        t = threading.Thread(
            target=lambda: results.append(
                svc._idempotent("k-conc", "probe", slow)),
            daemon=True,
        )
        t.start()
        time.sleep(0.1)
        # duplicate arrives while the first is in flight: it must wait and
        # then replay the first result, not run `slow` again
        dup = threading.Thread(
            target=lambda: results.append(
                svc._idempotent("k-conc", "probe", lambda: "dup-ran")),
            daemon=True,
        )
        dup.start()
        time.sleep(0.1)
        release.set()
        t.join(5.0)
        dup.join(5.0)
        assert results == ["slow-result", "slow-result"]

    def test_slow_mutation_heartbeats_past_the_ttl(self, cluster):
        """A mutation still executing past IDEM_INFLIGHT_TTL_S in a LIVE
        process is slow, not crashed: the executor heartbeats the record's
        deadline while fn runs, so a concurrent retry waits and replays
        instead of reclaiming and double-applying (ADVICE r3)."""
        svc = cluster.workflow_service
        svc.IDEM_INFLIGHT_TTL_S = 0.3          # heartbeat every 0.1 s
        runs = {"n": 0}
        results = []

        def slow():
            runs["n"] += 1
            time.sleep(1.0)                    # 3x the TTL
            return "slow-result"

        t = threading.Thread(
            target=lambda: results.append(
                svc._idempotent("k-slow", "probe", slow)),
            daemon=True,
        )
        t.start()
        time.sleep(0.15)
        # the duplicate outlives several TTL windows; without the heartbeat
        # it would reclaim the "orphan" and run `slow` a second time
        dup = svc._idempotent("k-slow", "probe", slow, wait_s=5.0)
        t.join(5.0)
        assert dup == "slow-result"
        assert results == ["slow-result"]
        assert runs["n"] == 1


class TestReclaimedWhileRunning:
    def test_displaced_executor_does_not_overwrite_new_owner(self, cluster):
        """If another plane reclaims our record mid-run (our heartbeat
        stalled past the TTL), settling must CAS on the owned deadline and
        lose: the record now belongs to the re-execution, and recording our
        outcome over it would let one key replay two different results."""
        svc = cluster.workflow_service
        stolen = {}

        def fn_that_gets_robbed():
            rec = [r for r in cluster.store.running_ops()
                   if r.idempotency_key == "k-steal"][0]
            # simulate the other plane's takeover: deadline CAS succeeds
            assert cluster.store.reclaim(rec.id, rec.deadline,
                                         time.time() + 999)
            stolen["id"] = rec.id
            return "displaced-result"

        result = svc._idempotent("k-steal", "probe", fn_that_gets_robbed)
        # the displaced caller still gets its own outcome (its side effects
        # did run) ...
        assert result == "displaced-result"
        # ... but the record stays RUNNING under the new owner's deadline,
        # for the new owner to settle
        rec = cluster.store.load(stolen["id"])
        assert rec.status == "RUNNING"


class TestOrphanedRecords:
    def test_crash_orphaned_running_record_is_taken_over(self, cluster):
        """A record left RUNNING by a control-plane crash (created, never
        completed) must not wedge its key forever: once its in-flight
        deadline passes, the retry takes it over and executes."""
        import time as _time

        svc = cluster.workflow_service
        # simulate the crash: record exists, RUNNING, deadline already past
        cluster.store.create("idem-crashed", "idem.probe", {},
                             idempotency_key="k-orphan",
                             deadline=_time.time() - 1.0)
        result = svc._idempotent("k-orphan", "probe", lambda: "recovered")
        assert result == "recovered"
        rec = cluster.store.load("idem-crashed")
        assert rec.status == "DONE" and rec.result == "recovered"

    def test_settled_idem_rows_are_gc_reaped(self, cluster):
        svc = cluster.workflow_service
        svc._idempotent("k-old", "probe", lambda: "x")
        assert cluster.store.load is not None
        # young rows survive, old rows go
        assert svc.gc_tick(idem_ttl_s=3600.0) == []
        rows = [r for r in cluster.store._conn.execute(
            "SELECT id FROM operations WHERE kind LIKE 'idem.%'")]
        assert len(rows) == 1
        svc.gc_tick(idem_ttl_s=0.0)
        rows = [r for r in cluster.store._conn.execute(
            "SELECT id FROM operations WHERE kind LIKE 'idem.%'")]
        assert rows == []


class TestTransportRetry:
    def test_reads_retry_transient_then_succeed(self):
        hits = {"n": 0}

        def handler(p):
            hits["n"] += 1
            if hits["n"] < 3:
                raise Unavailable("backend hiccup")
            return {"ok": True}

        server = JsonRpcServer({"Probe": handler})
        client = JsonRpcClient(server.address, backoff_base_s=0.01)
        try:
            assert client.call("Probe", retry=True) == {"ok": True}
            assert hits["n"] == 3
        finally:
            client.close()
            server.stop()

    def test_mutations_without_key_do_not_retry(self):
        hits = {"n": 0}

        def handler(p):
            hits["n"] += 1
            raise Unavailable("down")

        server = JsonRpcServer({"Mutate": handler})
        client = JsonRpcClient(server.address, backoff_base_s=0.01)
        try:
            with pytest.raises(Unavailable):
                client.call("Mutate")
            assert hits["n"] == 1
        finally:
            client.close()
            server.stop()

    def test_idempotency_key_rides_the_payload(self):
        seen = []

        def handler(p):
            seen.append(p.get("idempotency_key"))
            if len(seen) == 1:
                raise Unavailable("reply lost")
            return {}

        server = JsonRpcServer({"Mutate": handler})
        client = JsonRpcClient(server.address, backoff_base_s=0.01)
        try:
            client.call("Mutate", idempotency_key="stable-key")
            assert seen == ["stable-key", "stable-key"]
        finally:
            client.close()
            server.stop()
