"""Deviceless TPU AOT compiles: the dryrun's warning assert, promoted to
the real target (VERDICT r4 #1).

The CPU dryrun proves the sharded step executes; these tests prove the
*TPU* compiler (same libtpu the chip uses, via
``jax.experimental.topologies``) schedules it without collective
pathologies: a single-chip module must contain no collectives at all,
and an fsdp module's all-gather traffic must stay within the expected
parameter-gathering budget — an activation resharding cliff blows
straight through that bound. ``tools/aot_analysis.py`` runs the same
machinery at flagship size and commits the evidence artifact
(``tpu_evidence/AOT_ANALYSIS.*``).
"""

import jax
import jax.numpy as jnp
import pytest

from lzy_tpu.models import count_params, llama, unbox
from lzy_tpu.models.common import param_logical_axes


def _topo(name, **kw):
    import time

    from jax.experimental import topologies

    last = None
    for _ in range(6):
        try:
            return topologies.get_topology_desc(
                platform="tpu", topology_name=name, **kw)
        except Exception as e:  # noqa: BLE001 — no libtpu on this host
            last = e
            # libtpu is single-process (one /tmp/libtpu_lockfile): another
            # compile (tools/aot_analysis.py, the probe loop's bench) may
            # hold it right now — that's contention, not absence
            if "lockfile" not in str(e):
                break
            time.sleep(10)
    pytest.skip(f"deviceless TPU topology unavailable: {last}")


def _small_cfg():
    # small-but-not-tiny: at toy sizes the partitioner makes degenerate
    # choices that would make the traffic bound meaningless
    return llama.LlamaConfig(
        vocab_size=4096, d_model=256, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=512, max_seq_len=256, remat=False, tie_embeddings=True,
    )


def _compile(cfg, devices, mesh_axes, batch_shape):
    import optax

    from lzy_tpu.parallel import MeshSpec, TrainState, make_train_step

    mesh = MeshSpec(**mesh_axes).build(devices)
    boxed = jax.eval_shape(
        lambda k: llama.init_params(cfg, k)[0], jax.random.PRNGKey(0))
    params = unbox(boxed)
    tx = optax.adamw(3e-4)
    state = jax.eval_shape(lambda p: TrainState.create(p, tx), params)
    step, _, batch_sharding = make_train_step(
        llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
        param_logical_axes=param_logical_axes(boxed),
        batch_logical_axes=("batch", "seq"))
    batch = {"tokens": jax.ShapeDtypeStruct(
        batch_shape, jnp.int32, sharding=batch_sharding)}

    from tools.aot_analysis import StderrCapture, collective_census

    with StderrCapture() as scan:
        compiled = step.lower(state, batch).compile()
    return compiled, collective_census(compiled.as_text()), scan.text()


def test_single_chip_module_has_no_collectives():
    topo = _topo("v5e:1x1x1", chips_per_host_bounds=(1, 1, 1))
    cfg = _small_cfg()
    compiled, census, stderr = _compile(
        cfg, list(topo.devices), {"fsdp": -1}, (4, 256))
    assert census == {}, f"single-chip module emits collectives: {census}"
    assert "Involuntary full rematerialization" not in stderr
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    assert ca.get("flops", 0) > 0


def test_fsdp_module_collectives_are_the_expected_ones():
    topo = _topo("v5e:2x2")
    cfg = _small_cfg()
    compiled, census, stderr = _compile(
        cfg, list(topo.devices), {"fsdp": -1}, (8, 256))
    assert "Involuntary full rematerialization" not in stderr

    # fsdp's legal collective set: param all-gathers (fwd + bwd), grad
    # reduction (all-reduce or reduce-scatter), scalar metric reductions.
    # An all-to-all means the partitioner invented a resharding nobody
    # asked for.
    assert "all-to-all" not in census, census
    assert "all-gather" in census, "fsdp must gather params"
    assert ("all-reduce" in census) or ("reduce-scatter" in census), (
        "fsdp must reduce grads")

    # traffic budget: fsdp gathers each param in bf16 for fwd, bwd, and a
    # few extra uses (the tied embedding feeds embed + head + both
    # backwards) — a handful of full-tree equivalents. Before the
    # activation anchors (models/llama.py _anchor) the partitioner
    # batch-all-gathered [B,T,V] masks instead: 1459 MB here, 164x the
    # tree — this bound pins that class of regression with huge margin.
    boxed = jax.eval_shape(
        lambda k: llama.init_params(cfg, k)[0], jax.random.PRNGKey(0))
    param_bytes = count_params(unbox(boxed)) * 4  # f32 master params
    ag_bytes = census["all-gather"]["bytes"]
    assert ag_bytes <= 6 * param_bytes, (
        f"all-gather traffic {ag_bytes/1e6:.1f} MB exceeds 6x param bytes "
        f"{6*param_bytes/1e6:.1f} MB — unexpected gathers beyond fsdp's "
        f"param fwd+bwd budget")
