"""Speculative decoding tests (serving/spec.py + engine verify path).

The load-bearing guarantee is bit-identity: speculation may only change
HOW FAST tokens appear, never WHICH tokens appear. Greedy output with
speculation on must equal the solo ``generate()`` oracle and the
non-speculative engines, dense and paged; sampled rows sharing a batch
with speculating greedy rows must be bit-identical to a spec-off run
(same rng draw order). Acceptance itself is made deterministic where a
test needs it by injecting a proposer: an ORACLE proposer (drafts the
model's actual continuation — every token accepted) and an ADVERSARIAL
one (drafts tokens guaranteed wrong — every token rejected, exercising
the rollback path), so the accept and reject machinery are each pinned
down exactly, not sampled by luck of the n-gram matcher.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import (
    InferenceEngine, NgramProposer, PagedInferenceEngine)

VOCAB = 64


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=VOCAB)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle(cfg, params, prompt_ids, n):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drain(engine, reqs, rounds=800):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        engine.step()
    raise AssertionError("engine did not finish its requests")


class _OracleProposer:
    """Drafts the model's actual greedy continuation: full acceptance."""

    def __init__(self, seqs, gamma):
        self.seqs = [list(map(int, s)) for s in seqs]
        self.gamma = gamma

    def propose(self, tokens):
        t = list(tokens)
        for s in self.seqs:
            if len(s) > len(t) and s[:len(t)] == t:
                return s[len(t):len(t) + self.gamma]
        return []


class _AdversarialProposer(_OracleProposer):
    """Drafts tokens guaranteed to differ from the argmax: every
    proposal fully rejected, every verify round rolled back."""

    def propose(self, tokens):
        return [(t + 1) % VOCAB for t in super().propose(tokens)]


PROMPTS = [
    [5, 9, 3, 7, 2],
    [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3, 4],   # repetitive: n-gram hits
    [40, 41, 42],
]


class TestNgramProposer:
    def test_longest_suffix_match_wins(self):
        p = NgramProposer(max_ngram=3, gamma=4)
        # suffix [7,8] recurs (followed by 9,1); 1-gram [8] also recurs
        # with a different continuation — the longer match must win
        assert p.propose([7, 8, 9, 1, 8, 4, 7, 8]) == [9, 1, 8, 4]

    def test_full_window_preferred_on_runs(self):
        # the NEAREST occurrence of the suffix of a constant run offers a
        # 1-token window; an earlier one offers the whole gamma
        p = NgramProposer(max_ngram=3, gamma=4)
        assert p.propose([6] * 12) == [6, 6, 6, 6]

    def test_no_match_proposes_nothing(self):
        p = NgramProposer(max_ngram=3, gamma=4)
        assert p.propose([1, 2, 3, 4, 5, 6]) == []
        assert p.propose([9]) == []

    def test_gamma_truncation(self):
        p = NgramProposer(max_ngram=2, gamma=2)
        assert p.propose([5, 6, 7, 8, 5, 6]) == [7, 8]

    def test_validation(self):
        with pytest.raises(ValueError, match="gamma"):
            NgramProposer(gamma=0)
        with pytest.raises(ValueError, match="min_ngram"):
            NgramProposer(max_ngram=2, min_ngram=3)


class TestGreedyBitIdentical:
    @pytest.mark.parametrize("paged", [False, True])
    def test_spec_on_matches_oracle_and_spec_off(self, tiny_model, paged):
        cfg, params = tiny_model
        n = 20
        expected = [_oracle(cfg, params, p, n) for p in PROMPTS]

        def build(spec):
            if paged:
                return PagedInferenceEngine(
                    cfg, params, slots=2, page_size=16, spec_tokens=spec)
            return InferenceEngine(cfg, params, slots=2, spec_tokens=spec)

        for spec in (0, 4):
            eng = build(spec)
            reqs = [eng.submit(p, max_new_tokens=n) for p in PROMPTS]
            _drain(eng, reqs)
            for r, exp in zip(reqs, expected):
                assert r.result() == exp
            eng.close()

    def test_full_acceptance_emits_oracle_tokens_faster(self, tiny_model):
        cfg, params = tiny_model
        n, gamma = 16, 4
        prompt = PROMPTS[0]
        exp = _oracle(cfg, params, prompt, n)
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=16, spec_tokens=gamma,
            proposer=_OracleProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [req])
        assert req.result() == exp
        s = eng.stats()
        assert s.spec_acceptance_rate == 1.0
        assert s.spec_proposed_tokens == s.spec_accepted_tokens > 0
        # gamma+1 tokens per verify round: far fewer rounds than tokens
        assert eng.decode_steps < n - 1
        assert s.spec_tokens_per_step > 2.0
        eng.close()

    def test_full_rejection_still_bit_identical(self, tiny_model):
        cfg, params = tiny_model
        n, gamma = 12, 3
        prompt = PROMPTS[1]
        exp = _oracle(cfg, params, prompt, n)
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=16, spec_tokens=gamma,
            proposer=_AdversarialProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [req])
        assert req.result() == exp
        s = eng.stats()
        assert s.spec_proposed_tokens > 0
        assert s.spec_accepted_tokens == 0
        assert s.spec_acceptance_rate == 0.0
        eng.close()


class TestPagedRollbackIntegrity:
    def test_forced_full_rejection_never_corrupts_the_pool(
            self, tiny_model):
        """Adversarial drafts force a rollback every verify round while a
        radix-cached prefix is pinned by refcount; afterwards the pool
        must balance exactly and the cached prefix must still decode
        bit-identically (a rollback that freed or scribbled on a
        resident/refcounted block would break one of the two)."""
        cfg, params = tiny_model
        n, gamma, page = 12, 3, 4
        prompt = [11, 12, 13, 14, 15, 16, 17, 18, 19, 20]   # 2 full blocks
        exp = _oracle(cfg, params, prompt, n)
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=page, kv_blocks=40,
            spec_tokens=gamma,
            proposer=_AdversarialProposer([prompt + exp], gamma))
        # request 1 caches the prompt's full blocks in the radix tree
        r1 = eng.submit(prompt, max_new_tokens=4)
        _drain(eng, [r1])
        cached = set(eng.kv._node_of)
        assert cached, "prompt blocks should be tree-resident"
        # request 2 pins the cached prefix and speculates (all rejected)
        r2 = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [r2])
        assert r2.result() == exp
        assert eng.stats().spec_accepted_tokens == 0
        # pool balances: every block is free or cached-unreferenced
        ks = eng.kv.stats()
        assert ks.blocks_free + ks.blocks_cached == ks.blocks_total
        for b in eng.kv._node_of:
            assert eng.kv.pool.refcount(b) == 0
        # tree-resident prefix blocks survived every rollback
        assert cached <= set(eng.kv._node_of)
        # and their contents are untouched: a third request reuses the
        # cached prefix and must still match the oracle exactly
        r3 = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [r3])
        assert r3.result() == exp
        assert eng.kv.stats().prefix_hit_tokens > 0
        eng.close()


class TestMixedBatch:
    def test_sampled_rows_bit_identical_with_spec_on(self, tiny_model):
        """A sampling engine with one greedy=True (speculating) row and
        one sampled row: the sampled row's tokens must not move when
        speculation is enabled (same rng draw order), and the greedy row
        must match the greedy oracle."""
        cfg, params = tiny_model
        n = 10
        greedy_prompt, sampled_prompt = PROMPTS[1], PROMPTS[0]
        exp_greedy = _oracle(cfg, params, greedy_prompt, n)
        outs = {}
        for spec in (0, 4):
            eng = InferenceEngine(
                cfg, params, slots=2, temperature=0.8, top_k=20, seed=7,
                spec_tokens=spec)
            r_sampled = eng.submit(sampled_prompt, max_new_tokens=n)
            r_greedy = eng.submit(greedy_prompt, max_new_tokens=n,
                                  greedy=True)
            _drain(eng, [r_sampled, r_greedy])
            outs[spec] = (r_sampled.result(), r_greedy.result())
            eng.close()
        assert outs[0][0] == outs[4][0], "sampled row moved under spec"
        assert outs[0][1] == outs[4][1] == exp_greedy
        # ... and the sampled row really did sample (not argmax)
        assert outs[0][0] != _oracle(cfg, params, sampled_prompt, n)


class TestEosAndLimits:
    def test_eos_inside_accepted_window_truncates(self, tiny_model):
        cfg, params = tiny_model
        gamma = 4
        prompt = PROMPTS[0]
        exp = _oracle(cfg, params, prompt, 12)
        # an eos whose FIRST occurrence is mid-stream (an earlier
        # duplicate would legitimately end the request sooner)
        j = next(i for i in range(1, len(exp)) if exp[i] not in exp[:i])
        eos = exp[j]
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=16, spec_tokens=gamma,
            eos_token=eos,
            proposer=_OracleProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=12)
        _drain(eng, [req])
        # emission stops AT the eos even though the accepted window went
        # past it; nothing after the eos leaks out
        assert req.result() == exp[:j + 1]
        assert eng.stats().busy == 0       # slot freed
        eng.close()

    def test_max_new_tokens_exact_under_full_acceptance(self, tiny_model):
        cfg, params = tiny_model
        gamma = 4
        prompt = PROMPTS[1]
        exp = _oracle(cfg, params, prompt, 16)
        eng = InferenceEngine(
            cfg, params, slots=1, spec_tokens=gamma,
            proposer=_OracleProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=7)
        _drain(eng, [req])
        assert req.result() == exp[:7]     # never a token beyond the cap
        eng.close()


class TestStatsAndWarmup:
    def test_counters_sum_and_surface(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(
            cfg, params, slots=2, page_size=16, spec_tokens=3)
        reqs = [eng.submit(p, max_new_tokens=12) for p in PROMPTS[:2]]
        _drain(eng, reqs)
        s = eng.stats()
        assert s.spec_tokens == 3
        assert 0 <= s.spec_accepted_tokens <= s.spec_proposed_tokens
        assert s.spec_verify_steps == eng.spec_steps
        if s.spec_proposed_tokens:
            assert s.spec_acceptance_rate == pytest.approx(
                s.spec_accepted_tokens / s.spec_proposed_tokens, abs=1e-3)
        doc = s.doc()
        for key in ("spec_tokens", "spec_proposed_tokens",
                    "spec_accepted_tokens", "spec_acceptance_rate",
                    "spec_verify_steps", "spec_tokens_per_step"):
            assert key in doc
        # emitted decode tokens reconcile with the per-round accounting
        assert eng.decode_tokens <= eng.decode_rows * (3 + 1)
        eng.close()

    def test_spec_off_omits_spec_fields(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        doc = eng.stats().doc()
        assert "spec_tokens" not in doc
        assert "spec_acceptance_rate" not in doc
        eng.close()

    @pytest.mark.parametrize("paged", [False, True])
    def test_warmup_does_not_perturb_decode(self, tiny_model, paged):
        cfg, params = tiny_model
        n = 10
        exp = _oracle(cfg, params, PROMPTS[1], n)
        if paged:
            eng = PagedInferenceEngine(
                cfg, params, slots=2, page_size=16, spec_tokens=3)
        else:
            eng = InferenceEngine(cfg, params, slots=2, spec_tokens=3)
        eng.warmup()
        req = eng.submit(PROMPTS[1], max_new_tokens=n)
        _drain(eng, [req])
        assert req.result() == exp
        eng.close()

    def test_spec_tokens_validation(self, tiny_model):
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="spec_tokens"):
            InferenceEngine(cfg, params, spec_tokens=-1)
        with pytest.raises(ValueError, match="spec_tokens"):
            InferenceEngine(cfg, params,
                            spec_tokens=cfg.max_seq_len)


class TestServiceSurface:
    def test_flags_thread_through_the_service_builder(self):
        """The serve.py path: build_inference_service(spec_tokens=...,
        warm_start=True) produces a speculating, pre-warmed engine, and
        the per-request greedy override reaches it through
        InferenceService.generate — output still equals the oracle."""
        from lzy_tpu.service.inference import build_inference_service

        svc = build_inference_service(
            "tiny", slots=2, paged=True, page_size=16,
            spec_tokens=3, warm_start=True)
        try:
            assert svc.engine.spec_tokens == 3
            scfg = svc.engine.cfg
            prompt = PROMPTS[1]
            out = svc.generate(prompt, max_new_tokens=8, greedy=True,
                               timeout_s=60)
            assert out["status"] == "ok"
            exp = _oracle(scfg, svc.engine.params, prompt, 8)
            assert out["tokens"] == exp
            stats = svc.stats()
            assert stats["spec_tokens"] == 3
            assert "spec_acceptance_rate" in stats
        finally:
            svc.close()
