"""The quickstart's code blocks must actually run (reference tutorial
parity: the reference's docs/tutorials are what its scenario tier mirrors;
stale docs are the first thing a switching user hits).

Each ```python block from docs/quickstart.md executes in ONE shared
namespace, in order (later blocks build on earlier ones, like a reader
following along). Blocks that are deliberately illustrative fragments
(ellipses, undefined cloud endpoints) are skipped by marker.
"""

import pathlib
import re

import pytest

DOC = pathlib.Path(__file__).parents[1] / "docs" / "quickstart.md"


def _blocks():
    src = DOC.read_text()
    return re.findall(r"```python\n(.*?)```", src, re.S)


def _runnable(block: str) -> bool:
    # `<placeholder>` tokens or an explicit illustration marker mean
    # "not meant to execute standalone"; a bare `...` is valid python
    # (Ellipsis function bodies in the docs) and ordinary `<`
    # comparisons must NOT exclude a block
    return (re.search(r"<[a-z][a-z0-9_-]*>", block, re.I) is None
            and "# illustration" not in block)


def test_quickstart_blocks_execute_in_order(tmp_path):
    blocks = _blocks()
    assert len(blocks) >= 5, "quickstart lost its code blocks?"
    ns: dict = {}
    ran = 0
    for i, block in enumerate(blocks):
        if not _runnable(block):
            continue
        # environment-specific install paths → this test's sandbox (the
        # reader is told to create /var/lzy; CI must not write there)
        block = block.replace("/var/lzy", str(tmp_path))
        try:
            exec(compile(block, f"quickstart-block-{i}", "exec"), ns)  # noqa: S102
        except Exception as e:  # noqa: BLE001 — surface which block broke
            pytest.fail(f"quickstart block {i} failed: {type(e).__name__}: "
                        f"{e}\n---\n{block}")
        ran += 1
    assert ran >= 5, f"only {ran} quickstart blocks were runnable"
    # the serving block must EXECUTE (not get skipped as an illustration):
    # it is the doc surface of the inference engine (docs/serving.md)
    assert "InferenceEngine" in ns, "quickstart serving block did not run"
    assert ns["req"].done
    cluster = ns.get("cluster")
    if cluster is not None:
        cluster.shutdown()
