"""Chaos harness: seeded fault injection, invariant auditors, degradation.

Acceptance criterion (ISSUE 6): a multi-seed soak runs mixed
greedy+sampled traffic through a disaggregated gateway with faults armed
at every registered serving point; every invariant auditor stays clean
and greedy output is bit-identical to the uninterrupted ``generate()``
oracle. Any failing seed replays deterministically: the failure message
prints the seed and the fired schedule
(``LZY_CHAOS_SEED=<seed> pytest tests/test_chaos.py -k soak``).

Unit layers underneath: fault-plan determinism, the unified backoff
policy, the circuit breaker (flapping replicas stop being routed before
the streak verdict fires), load shedding with retry-after, graceful
drain, the invariant auditors themselves, and remaining-deadline
threading across failover and disagg staging.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.chaos import (
    CHAOS, FaultPlan, FenceAuditor, InvariantViolation, audit_engine,
    audit_fleet_leases, audit_pool, audit_radix)
from lzy_tpu.chaos.faults import CRASH, DELAY, ERROR, FaultPoint, SLOW
from lzy_tpu.gateway import (
    Autoscaler, DisaggGatewayService, GatewayService, HealthPolicy,
    HealthTracker, PrefixAffinityRouter, ReplicaFleet)
from lzy_tpu.gateway.health import BreakerPolicy, CircuitBreaker
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.rpc.core import Unavailable
from lzy_tpu.serving import (
    AdmissionError, DecodeEngine, InferenceEngine, PagedInferenceEngine,
    PrefillEngine, QuotaExceeded, RadixCache, SloLimiter, TenantPolicy,
    TenantTable)
from lzy_tpu.serving.scheduler import RequestQueue
from lzy_tpu.utils.backoff import RetryPolicy

pytestmark = pytest.mark.chaos

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with no plan armed, whatever broke."""
    CHAOS.disarm()
    yield
    CHAOS.disarm()


def _oracle_tokens(cfg, params, prompt_ids, n):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


# ---------------------------------------------------------------------------
# fault plan


class TestFaultPlan:
    def _decisions(self, seed, n=64, **kw):
        plan = FaultPlan(seed, **kw)
        point = FaultPoint("x", crash_ok=True,
                           modes=(ERROR, DELAY, SLOW, CRASH))
        return [plan.decide(point) for _ in range(n)]

    def test_same_seed_same_schedule(self):
        a = self._decisions(7, rate=0.3)
        b = self._decisions(7, rate=0.3)
        assert a == b
        assert any(d is not None for d in a)

    def test_seeds_diverge(self):
        assert self._decisions(1, rate=0.3) != self._decisions(2, rate=0.3)

    def test_per_point_streams_are_independent(self):
        """A point's decision stream depends only on (seed, its own hit
        count) — interleaving hits of OTHER points must not perturb it
        (the replayability argument)."""
        p1 = FaultPoint("one")
        p2 = FaultPoint("two")
        solo = FaultPlan(5, rate=0.5)
        solo_stream = [solo.decide(p1) for _ in range(32)]
        mixed = FaultPlan(5, rate=0.5)
        mixed_stream = []
        for i in range(32):
            mixed.decide(p2)            # interleaved traffic on point two
            mixed_stream.append(mixed.decide(p1))
        assert mixed_stream == solo_stream

    def test_max_faults_bounds_each_point(self):
        plan = FaultPlan(3, rate=1.0, modes=(ERROR,), max_faults=4)
        point = FaultPoint("x")
        fired = [plan.decide(point) for _ in range(32)]
        assert sum(d is not None for d in fired) == 4
        assert plan.fired == 4 and len(plan.schedule) == 4
        # the cap is PER POINT (a global budget would let thread
        # interleaving across points decide who gets the last slot,
        # breaking seed replay): a second point still fires
        assert plan.decide(FaultPoint("y")) is not None

    def test_disallowed_mode_never_fires(self):
        # crash on a point without crash_ok is silently withheld
        plan = FaultPlan(3, rate=1.0, modes=(CRASH,))
        assert all(plan.decide(FaultPoint("x")) is None for _ in range(16))

    def test_point_allowlist(self):
        plan = FaultPlan(3, rate=1.0, modes=(ERROR,), points=("a",))
        assert plan.decide(FaultPoint("b")) is None
        assert plan.decide(FaultPoint("a")) is not None

    def test_arm_rejects_unknown_points_and_double_arm(self):
        with pytest.raises(KeyError):
            CHAOS.arm(FaultPlan(1, points=("no.such.point",)))
        CHAOS.arm(FaultPlan(1, points=("engine.admit",)))
        try:
            with pytest.raises(RuntimeError):
                CHAOS.arm(FaultPlan(2))
        finally:
            CHAOS.disarm()

    def test_error_mode_raises_the_registered_type(self):
        """The admission boundary degrades via AdmissionError — the
        injected fault must be that exact type, or the degradation path
        under test would not be the production one."""
        CHAOS.arm(FaultPlan(1, rate=1.0, modes=(ERROR,),
                            points=("engine.admit",)))
        q = RequestQueue(max_depth=4)
        from lzy_tpu.serving.scheduler import Request

        with pytest.raises(AdmissionError, match="injected fault"):
            q.submit(Request([1], 1))
        CHAOS.disarm()
        q.submit(Request([1], 1))       # disarmed: admission works

    def test_describe_names_seed_and_fired_schedule(self):
        plan = FaultPlan(42, rate=1.0, modes=(ERROR,))
        plan.decide(FaultPoint("x"))
        text = plan.describe()
        assert "seed=42" in text and "x hit=1 -> error" in text


# ---------------------------------------------------------------------------
# unified backoff policy


class TestRetryPolicy:
    def test_attempt_count_and_terminal_error(self):
        calls = []

        def boom():
            calls.append(1)
            raise IOError("nope")

        with pytest.raises(IOError):
            RetryPolicy(attempts=3, base_s=0.0).call(boom)
        assert len(calls) == 3

    def test_retry_if_gates_retries(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("fatal")

        with pytest.raises(ValueError):
            RetryPolicy(attempts=5, base_s=0.0).call(
                boom, retry_if=lambda e: isinstance(e, IOError))
        assert len(calls) == 1

    def test_full_jitter_bounds_and_determinism(self):
        import random

        policy = RetryPolicy(attempts=8, base_s=0.5, cap_s=2.0)
        a = [policy.delay_s(k, random.Random(9)) for k in range(1, 8)]
        b = [policy.delay_s(k, random.Random(9)) for k in range(1, 8)]
        assert a == b                       # injected rng => deterministic
        for k, d in enumerate(a, start=1):
            assert 0.0 <= d <= min(2.0, 0.5 * 2 ** (k - 1))

    def test_unjittered_doubles_to_cap(self):
        policy = RetryPolicy(attempts=8, base_s=0.5, cap_s=2.0,
                             jitter=False)
        assert [policy.delay_s(k) for k in (1, 2, 3, 4)] == \
            [0.5, 1.0, 2.0, 2.0]

    def test_transfer_config_preserves_per_part_retry_counts(self):
        from lzy_tpu.storage.transfer import TransferConfig

        cfg = TransferConfig(retries=3, backoff_s=0.01)
        assert cfg.retry_policy.attempts == 3
        assert cfg.retry_policy.base_s == 0.01

    def test_success_after_failures_returns_value(self):
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise IOError("blip")
            return "ok"

        assert RetryPolicy(attempts=4, base_s=0.0).call(flaky) == "ok"


# ---------------------------------------------------------------------------
# circuit breaker


class TestCircuitBreaker:
    def test_flapping_opens_before_the_streak_verdict(self):
        """fail/success alternation never builds a 3-streak (the health
        verdict stays None) but crosses the windowed threshold — the
        breaker must stop routing while the verdict keeps the lease."""
        tracker = HealthTracker(
            HealthPolicy(max_consecutive_failures=3),
            breaker=BreakerPolicy(failure_threshold=3, window_s=10.0,
                                  open_s=5.0))
        t = 0.0
        for i in range(3):
            tracker.breaker.record_failure("r", now=t + i)
            if i < 2:
                tracker.record_success("r")
        assert tracker.verdict("r") is None      # streak never accrued
        assert not tracker.routable("r", now=t + 3)

    def test_half_open_probe_closes_or_reopens(self):
        br = CircuitBreaker(BreakerPolicy(failure_threshold=2,
                                          window_s=10.0, open_s=5.0))
        br.record_failure("r", now=0.0)
        br.record_failure("r", now=1.0)
        assert not br.routable("r", now=2.0)
        assert br.retry_after_s("r", now=2.0) == pytest.approx(4.0)
        # past open_s: half-open lets EXACTLY ONE dispatched probe
        # through — a burst must not pile onto a possibly-still-broken
        # replica. routable() (the listing gate) never claims; only
        # try_route() (the dispatch gate) does.
        assert br.routable("r", now=6.4)         # listable...
        assert br.routable("r", now=6.45)        # ...without consuming
        assert br.try_route("r", now=6.5)        # dispatch claims it
        assert not br.try_route("r", now=6.55)   # probe already claimed
        assert not br.routable("r", now=6.55)    # claim visible to lists
        br.record_failure("r", now=6.6)          # probe failed: re-open
        assert not br.try_route("r", now=7.0)
        assert br.try_route("r", now=12.0)       # half-open again
        br.record_success("r")                   # probe succeeded
        assert br.routable("r", now=12.1)
        assert br.try_route("r", now=12.1)       # closed: no claiming
        assert br.try_route("r", now=12.15)
        assert br.state("r", now=12.15) == "closed"

    def test_release_probe_unblocks_an_undispatched_claim(self):
        """A try_route claim whose request is then refused admission
        must be released, or the recovered replica sits probe-blocked
        for another open_s with no probe in flight."""
        br = CircuitBreaker(BreakerPolicy(failure_threshold=1,
                                          window_s=10.0, open_s=5.0))
        br.record_failure("r", now=0.0)
        assert br.try_route("r", now=6.0)        # half-open: claims
        assert not br.try_route("r", now=6.1)
        br.release_probe("r")                    # dispatch refused
        assert br.try_route("r", now=6.2)        # next caller re-probes

    def test_open_breaker_withholds_replica_from_routing(self):
        class _FakeEngine:
            closed = False

            def stats(self):
                from lzy_tpu.serving.engine import EngineStats

                return EngineStats(slots=1, busy=0, queue_depth=0,
                                   requests_finished=0, tokens_generated=0)

            def close(self):
                pass

        tracker = HealthTracker(
            breaker=BreakerPolicy(failure_threshold=2, window_s=30.0,
                                  open_s=60.0))
        fleet = ReplicaFleet(_FakeEngine, start_engines=False,
                             health=tracker)
        a = fleet.add_replica()
        b = fleet.add_replica()
        assert set(fleet.loads()) == {a.id, b.id}
        tracker.record_failure(a.id)
        tracker.record_failure(a.id)
        assert set(fleet.loads()) == {b.id}      # open breaker: withheld
        assert fleet.breaker_retry_after_s() is not None
        tracker.forget(a.id)
        assert set(fleet.loads()) == {a.id, b.id}


# ---------------------------------------------------------------------------
# load shedding


class TestLoadShedding:
    def test_full_queue_sheds_with_retry_after(self):
        from lzy_tpu.serving.scheduler import Request

        q = RequestQueue(max_depth=1)
        q.submit(Request([1], 1))
        with pytest.raises(AdmissionError) as err:
            q.submit(Request([2], 1))
        assert err.value.retry_after_s is not None
        assert 0.05 <= err.value.retry_after_s <= 10.0

    def test_gateway_shed_counts_and_hints(self, tiny_model):
        cfg, params = tiny_model
        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=1, max_queue=1),
            start_engines=False)
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny")
        try:
            replica = fleet.add_replica()
            # fill slot-less queue: engine not stepping, so both park
            replica.engine.submit([1, 2], max_new_tokens=2)
            with pytest.raises(Unavailable) as err:
                gw.generate([3, 4], max_new_tokens=2)
            assert getattr(err.value, "retry_after_s", None) is not None
            assert "retry_after_s" in str(err.value)
            assert gw.stats()["requests_shed"] == 1
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# graceful drain


class TestGracefulDrain:
    def test_engine_drain_finishes_inflight_then_refuses(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=2).start()
        req = eng.submit([5, 9, 3], max_new_tokens=6)
        assert eng.drain(timeout_s=60.0)
        assert req.done and req.error is None
        assert req.tokens == _oracle_tokens(cfg, params, [5, 9, 3], 6)
        assert eng.closed
        with pytest.raises(AdmissionError):
            eng.submit([1, 2], max_new_tokens=2)

    def test_gateway_drain_completes_inflight_and_closes_fleet(
            self, tiny_model):
        cfg, params = tiny_model
        fleet = ReplicaFleet(
            lambda: PagedInferenceEngine(cfg, params, slots=2,
                                         page_size=PAGE))
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny")
        fleet.add_replica()
        result = {}

        def run():
            try:
                result["res"] = gw.generate([7, 2, 8], max_new_tokens=12,
                                            timeout_s=120)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                result["err"] = e

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and gw._inflight == 0:
            time.sleep(0.002)
        assert gw.drain(timeout_s=60.0)
        t.join(60)
        assert "err" not in result, result.get("err")
        assert result["res"]["tokens"] == _oracle_tokens(
            cfg, params, [7, 2, 8], 12)
        # fleet retired, engines closed, new calls shed as draining
        assert fleet.replicas() == []
        with pytest.raises(Unavailable, match="draining"):
            gw.generate([1, 2], max_new_tokens=2)


# ---------------------------------------------------------------------------
# invariant auditors


class TestInvariants:
    def test_healthy_paged_engine_audits_clean(self, tiny_model):
        cfg, params = tiny_model
        eng = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE)
        reqs = [eng.submit(list(range(10 + i)), max_new_tokens=6)
                for i in range(3)]
        for _ in range(200):
            if all(r.done for r in reqs):
                break
            eng.step()
            audit_engine(eng)       # clean after EVERY scheduling round
        assert all(r.done for r in reqs)
        audit_engine(eng)

    def test_auditor_catches_a_leaked_block(self):
        rc = RadixCache(8, PAGE)
        blocks = rc.allocate(2)
        audit_pool(rc)
        rc.pool._ref[blocks[0]] = 0      # drop the ref without freeing
        with pytest.raises(InvariantViolation, match="leaked"):
            audit_pool(rc)

    def test_auditor_catches_free_list_double_ownership(self):
        rc = RadixCache(8, PAGE)
        block = rc.allocate(1)[0]
        rc.pool._free.append(block)      # freed while still referenced
        with pytest.raises(InvariantViolation, match="free list"):
            audit_pool(rc)

    def test_auditor_catches_a_broken_tree_link(self):
        rc = RadixCache(8, PAGE)
        blocks = rc.allocate(2)
        tokens = list(range(2 * PAGE))
        rc.insert(tokens, blocks)
        rc.release(blocks)
        audit_radix(rc)
        node = rc._node_of[blocks[1]]
        node.parent = rc._root           # detach from its true parent
        with pytest.raises(InvariantViolation, match="parent link"):
            audit_radix(rc)

    def test_fence_auditor_rejects_a_shrunk_fence(self):
        session = FenceAuditor().session([1, 2, 3])
        session.on_failover([5, 6], [1, 2, 3, 5, 6])
        with pytest.raises(InvariantViolation, match="shrank"):
            session.on_failover([5], [1, 2, 3, 5])

    def test_fence_auditor_rejects_a_wrong_retry_prompt(self):
        session = FenceAuditor().session([1, 2, 3])
        with pytest.raises(InvariantViolation, match="retry prompt"):
            session.on_failover([5, 6], [1, 2, 3, 5])

    def test_fence_auditor_accepts_a_clean_stream(self):
        fa = FenceAuditor()
        session = fa.session([1, 2, 3])
        session.on_failover([5, 6], [1, 2, 3, 5, 6])
        session.on_complete([5, 6, 7, 8])
        assert fa.failovers_seen == 1 and fa.completions_seen == 1

    def test_fleet_lease_audit_catches_double_lease(self, tiny_model):
        cfg, params = tiny_model
        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=1),
            start_engines=False)
        a = fleet.add_replica()
        b = fleet.add_replica()
        audit_fleet_leases(fleet)
        a.vm_ids.append("vm-x")
        b.vm_ids.append("vm-x")
        with pytest.raises(InvariantViolation, match="leased to both"):
            audit_fleet_leases(fleet)


# ---------------------------------------------------------------------------
# remaining-deadline threading (satellite: failover + disagg staging)


class TestDeadlineAcrossFailover:
    def test_failover_resubmits_with_remaining_deadline(self, tiny_model):
        """The retry after a mid-stream death must carry the REMAINING
        client deadline (anchored at first submission), not a reset
        ``deadline_s``."""
        cfg, params = tiny_model
        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=2))
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny")
        seen = []
        try:
            for _ in range(2):
                replica = fleet.add_replica()
                orig = replica.engine.submit

                def spy(prompt, *, _orig=orig, **kw):
                    seen.append(kw.get("deadline_s"))
                    return _orig(prompt, **kw)

                replica.engine.submit = spy
            result = {}

            def run():
                try:
                    result["res"] = gw.generate(
                        [7, 2, 8, 1], max_new_tokens=24,
                        timeout_s=120, deadline_s=300.0)
                except BaseException as e:  # noqa: BLE001
                    result["err"] = e

            t = threading.Thread(target=run)
            t.start()
            victim = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and victim is None:
                for replica in fleet.replicas():
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim = replica
                        break
                time.sleep(0.005)
            assert victim is not None, "request never reached mid-decode"

            def boom():
                raise RuntimeError("replica host on fire")

            victim.engine.step = boom
            t.join(120)
            assert "err" not in result, result.get("err")
            assert result["res"]["failovers"] == 1
            assert len(seen) == 2
            assert seen[0] is not None and seen[0] <= 300.0
            # the retry carried strictly less than the first submission:
            # time elapsed mid-stream came off the same anchored budget
            assert seen[1] < seen[0]
        finally:
            gw.close()

    def test_disagg_staging_carries_the_deadline_to_the_prefill_pool(
            self, tiny_model):
        cfg, params = tiny_model
        decode_fleet = ReplicaFleet(
            lambda: DecodeEngine(cfg, params, slots=2, page_size=PAGE),
            replica_prefix="decode")
        prefill_fleet = ReplicaFleet(
            lambda: PrefillEngine(cfg, params, slots=2, page_size=PAGE),
            replica_prefix="prefill")
        gw = DisaggGatewayService(
            decode_fleet, prefill_fleet, page_size=PAGE,
            router=PrefixAffinityRouter(PAGE),
            prefill_router=PrefixAffinityRouter(PAGE), model_name="tiny")
        seen = []
        try:
            decode_fleet.add_replica()
            pf = prefill_fleet.add_replica()
            orig = pf.engine.submit

            def spy(prompt, **kw):
                seen.append(kw.get("deadline_s"))
                return orig(prompt, **kw)

            pf.engine.submit = spy
            prompt = list(range(2 * PAGE)) + [40]
            res = gw.generate(prompt, max_new_tokens=4, timeout_s=120,
                              deadline_s=600.0)
            assert res["status"] == "ok"
            assert res["prefilled_by"] == pf.id
            assert len(seen) == 1
            assert seen[0] is not None and 0 < seen[0] <= 600.0
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# autoscaler stability (satellite)


class TestAutoscalerStability:
    def test_flapping_pressure_around_threshold_never_scales(self):
        """Queue depth oscillating across the threshold every second can
        never satisfy the sustain window — zero decisions, zero lease
        churn."""
        scaler = Autoscaler(min_replicas=1, max_replicas=4,
                            up_queue_per_replica=4.0, up_sustain_s=2.0,
                            down_busy_fraction=0.25, down_sustain_s=5.0,
                            cooldown_s=10.0)
        decisions = []
        for i in range(60):
            queue = 8 if i % 2 == 0 else 0
            d = scaler.tick(float(i), replicas=1, queue_depth=queue,
                            busy=1, slots=2)
            if d is not None:
                decisions.append((i, d))
        assert decisions == []

    def test_cooldown_bounds_scale_rate_under_sustained_flap(self):
        """Even pressure sustained long enough to fire repeatedly is
        paced by the shared cooldown: decisions are spaced >= cooldown_s,
        bounding lease/drain churn."""
        scaler = Autoscaler(min_replicas=1, max_replicas=8,
                            up_queue_per_replica=2.0, up_sustain_s=1.0,
                            down_busy_fraction=0.25, down_sustain_s=1.0,
                            cooldown_s=10.0)
        fired = []
        replicas = 1
        for t in range(0, 60):
            d = scaler.tick(float(t), replicas=replicas,
                            queue_depth=50, busy=replicas,
                            slots=replicas)
            if d is not None:
                fired.append(t)
                replicas += 1
        assert len(fired) >= 2
        gaps = [b - a for a, b in zip(fired, fired[1:])]
        assert all(g >= 10 for g in gaps)

    def test_drain_waits_for_inflight_decode_to_retire(self, tiny_model):
        """A DRAINING replica with a slot mid-decode must not be reaped
        until the slot retires — in-flight work finishes on the warm
        engine, never gets dumped."""
        cfg, params = tiny_model
        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=2))
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny")
        try:
            replica = fleet.add_replica()
            req = replica.engine.submit([5, 9, 3], max_new_tokens=40)
            fleet.drain(replica.id)
            assert fleet.reap_drained() == []    # busy: must wait
            assert replica.id in [r.id for r in
                                  fleet.replicas(state="DRAINING")]
            assert req.result(timeout=120) == _oracle_tokens(
                cfg, params, [5, 9, 3], 40)
            deadline = time.monotonic() + 30
            reaped = []
            while time.monotonic() < deadline and not reaped:
                reaped = fleet.reap_drained()
                time.sleep(0.01)
            assert reaped == [replica.id]
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# the chaos soak: disagg gateway + faults at every registered point


def _build_disagg(cfg, params, *, decode=2, prefill=1, tenants=None,
                  prefill_budget=None):
    # a small host tier on every engine puts the kvtier.demote /
    # kvtier.import fault points in play for the soak: evictions demote,
    # admissions attempt promotion, and an injected failure at either
    # must degrade to classic eviction / local re-prefill with greedy
    # output still bit-identical to the oracle
    kw = dict(slots=2, page_size=PAGE, temperature=0.7,
              tenants=tenants, prefill_budget=prefill_budget,
              kv_host_tier_bytes=1 << 20)
    decode_fleet = ReplicaFleet(
        lambda: DecodeEngine(cfg, params, **kw),
        replica_prefix="decode")
    prefill_fleet = ReplicaFleet(
        lambda: PrefillEngine(cfg, params, **kw),
        replica_prefix="prefill")
    scaler = Autoscaler(min_replicas=decode, max_replicas=decode + 1,
                        up_sustain_s=3600.0, down_sustain_s=3600.0,
                        cooldown_s=0.1)
    slo = SloLimiter(tenants) if tenants is not None else None
    gw = DisaggGatewayService(
        decode_fleet, prefill_fleet, page_size=PAGE,
        router=PrefixAffinityRouter(PAGE),
        prefill_router=PrefixAffinityRouter(PAGE),
        autoscaler=scaler, prefill_replicas=prefill, model_name="tiny",
        slo=slo)
    for _ in range(decode):
        decode_fleet.add_replica()
    for _ in range(prefill):
        prefill_fleet.add_replica()
    return gw, decode_fleet, prefill_fleet


def _audit_all(gw, decode_fleet, prefill_fleet):
    for fleet in (decode_fleet, prefill_fleet):
        audit_fleet_leases(fleet)
        for replica in fleet.replicas():
            audit_engine(replica.engine)


def _chaos_round(tiny_model, seed, *, n_requests, max_faults,
                 tenants=False):
    """One seeded soak: mixed greedy+sampled traffic with faults armed
    at EVERY registered point; auditors after every request; greedy
    bit-identical to the uninterrupted oracle. With ``tenants`` the
    traffic is two-tenant with heavy-tailed prompt lengths (an aggressor
    dragging 10+-block prompts next to a short-prompt victim) through
    the SLO layer — rate limits, WFQ, KV quotas, chunked prefill — and
    the same auditors/oracle must hold."""
    cfg, params = tiny_model
    header = list(range(2 * PAGE))          # shared whole-block prefix
    table = None
    if tenants:
        table = TenantTable(default=TenantPolicy(
            requests_per_s=200.0, prompt_tokens_per_s=20000.0,
            burst_s=1.0, kv_block_quota=24, max_queued=8))
        table.set_policy(TenantPolicy(
            tenant="agg", priority=2, requests_per_s=100.0,
            prompt_tokens_per_s=8000.0, burst_s=1.0, kv_block_quota=20,
            max_queued=6))
        table.set_policy(TenantPolicy(tenant="vic", priority=0))
    gw, decode_fleet, prefill_fleet = _build_disagg(
        cfg, params, tenants=table,
        prefill_budget=2 * PAGE if tenants else None)
    gw.fence_auditor = FenceAuditor()
    plan = CHAOS.arm(FaultPlan(
        seed, rate=0.08, modes=(ERROR, DELAY, CRASH),
        max_faults=max_faults))      # per-point cap (seed-replayable)
    try:
        for i in range(n_requests):
            greedy = i % 2 == 0
            tenant = None
            if tenants:
                tenant = "agg" if i % 3 == 0 else "vic"
            if tenants and tenant == "agg" and i % 6 == 0:
                # the heavy tail: a 10-block prompt through chunked
                # prefill while the victim's short prompts interleave
                prompt = header + [(i * 5 + j) % 50 + 1
                                   for j in range(10 * PAGE)]
            else:
                prompt = header + [40 + (i * 7) % 20, 30 + i]
            n = 10 + (i % 3)
            res = None
            for _ in range(30):         # shed/Unavailable => client retry
                try:
                    res = gw.generate(prompt, max_new_tokens=n,
                                      timeout_s=120, greedy=greedy,
                                      tenant=tenant)
                    break
                except QuotaExceeded as e:
                    # tenant-scoped shed: back off on ITS hint
                    time.sleep(min(e.retry_after_s or 0.02, 0.05))
                except Unavailable:
                    gw.tick()           # re-lease toward the floor
                    time.sleep(0.02)
            assert res is not None, f"request {i} shed forever"
            assert res["status"] == "ok", res
            if greedy:
                assert res["tokens"] == _oracle_tokens(
                    cfg, params, prompt, n), f"request {i} diverged"
            else:
                assert len(res["tokens"]) == n
            gw.tick()
            _audit_all(gw, decode_fleet, prefill_fleet)
        # the quiet tail: with the plan exhausted, the fleet must be
        # fully recovered and still bit-exact
        CHAOS.disarm()
        final = gw.generate(header + [63], max_new_tokens=8,
                            timeout_s=120, greedy=True)
        assert final["tokens"] == _oracle_tokens(
            cfg, params, header + [63], 8)
        _audit_all(gw, decode_fleet, prefill_fleet)
        assert gw.fence_auditor.completions_seen >= n_requests
    except AssertionError as e:
        pytest.fail(
            f"chaos seed {seed} failed: {e}\n--- replay ---\n"
            f"LZY_CHAOS_SEED={seed} pytest tests/test_chaos.py -k soak\n"
            f"{plan.describe()}")
    finally:
        CHAOS.disarm()
        gw.close()
    return plan


class TestChaosSmoke:
    def test_fixed_seed_smoke(self, tiny_model):
        """Tier-1: one fixed seed, faults armed at every registered
        point, auditors clean, greedy bit-identical to the oracle."""
        plan = _chaos_round(tiny_model, seed=20260803, n_requests=6,
                            max_faults=1)
        # the smoke must actually have injected something, or it proves
        # nothing; the fixed seed makes this stable
        assert plan.fired > 0, plan.describe()

    def test_fixed_seed_multi_tenant_smoke(self, tiny_model):
        """Tier-1 twin with the SLO layer armed: two tenants,
        heavy-tailed prompts, faults at every point INCLUDING the new
        slo.admit admission boundary — auditors clean, greedy
        bit-identical."""
        plan = _chaos_round(tiny_model, seed=20260804, n_requests=6,
                            max_faults=1, tenants=True)
        assert plan.fired > 0, plan.describe()


@pytest.mark.slow
@pytest.mark.skipif(not os.environ.get("LZY_SLOW"),
                    reason="multi-seed chaos soak: set LZY_SLOW=1")
class TestChaosSoak:
    def test_multi_seed_soak(self, tiny_model):
        from tests.conftest import record_tier_run

        env_seed = os.environ.get("LZY_CHAOS_SEED")
        seeds = ([int(env_seed)] if env_seed
                 else [11, 23, 37, 41, 53])
        total = 0
        for seed in seeds:
            plan = _chaos_round(tiny_model, seed, n_requests=10,
                                max_faults=2)
            total += plan.fired
        assert total > 0
        record_tier_run("chaos_soak",
                        f"seeds={seeds} faults_fired={total}")

    def test_multi_tenant_soak(self, tiny_model):
        """The ISSUE-7 soak: two tenants (long-prompt aggressor,
        short-prompt victim) with the SLO layer on — rate limits, WFQ,
        KV quotas, chunked prefill — faults armed at every point, fence
        and pool auditors after every request, greedy bit-identical."""
        from tests.conftest import record_tier_run

        env_seed = os.environ.get("LZY_CHAOS_SEED")
        seeds = [int(env_seed)] if env_seed else [7, 19, 31]
        total = 0
        for seed in seeds:
            plan = _chaos_round(tiny_model, seed, n_requests=12,
                                max_faults=2, tenants=True)
            total += plan.fired
        assert total > 0
        record_tier_run("chaos_soak_multi_tenant",
                        f"seeds={seeds} faults_fired={total}")
