"""In-process boto3/botocore stand-in for executing ``storage/s3.py``.

The image deliberately ships without boto3, so the S3 client used to get
only import-gated "it raises ImportError" coverage — its multipart and
retry paths never ran (VERDICT missing #5). This module is the missing
server: an in-memory S3 (the reference's InMemoryS3Storage idea) behind
the exact client slice ``S3StorageClient`` calls, installed into
``sys.modules`` as ``boto3``/``botocore`` for the duration of a test so
the real code path — lazy import included — executes unchanged.

Fault injection: ``FakeS3Client.fail_next[op]`` holds a countdown of
calls of ``op`` (e.g. ``"upload_part"``) to fail with a retryable error,
which is how the tests drive the transfer engine's per-part retry and
the abort-on-failure guarantee.
"""

from __future__ import annotations

import sys
import threading
import types
from typing import Dict, Tuple


class FakeClientError(Exception):
    """Shape-compatible with botocore.exceptions.ClientError."""

    def __init__(self, code: str, op: str = "Unknown"):
        super().__init__(f"An error occurred ({code}) calling {op}")
        self.response = {"Error": {"Code": code}}


class _Body:
    def __init__(self, data: bytes):
        self._data = data

    def read(self) -> bytes:
        return self._data


class FakeS3Client:
    """The client-surface slice storage/s3.py uses, over a dict."""

    def __init__(self):
        self._objects: Dict[Tuple[str, str], bytes] = {}
        self._mpu: Dict[str, dict] = {}
        self._mpu_seq = 0
        self._lock = threading.RLock()
        self.fail_next: Dict[str, int] = {}    # op -> remaining failures
        self.calls: Dict[str, int] = {}        # op -> total invocations
        self.aborted: list = []                # aborted multipart UploadIds

    def _enter(self, op: str) -> None:
        with self._lock:
            self.calls[op] = self.calls.get(op, 0) + 1
            if self.fail_next.get(op, 0) > 0:
                self.fail_next[op] -= 1
                raise FakeClientError("SlowDown", op)

    # -- plain object ops ----------------------------------------------------

    def upload_fileobj(self, fileobj, bucket, key):
        self._enter("upload_fileobj")
        self._objects[(bucket, key)] = fileobj.read()

    def download_fileobj(self, bucket, key, fileobj):
        self._enter("download_fileobj")
        fileobj.write(self._require(bucket, key))

    def put_object(self, *, Bucket, Key, Body):
        self._enter("put_object")
        self._objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, *, Bucket, Key, Range=None):
        self._enter("get_object")
        data = self._require(Bucket, Key)
        if Range is not None:
            spec = Range[len("bytes="):]
            start_s, _, end_s = spec.partition("-")
            start = int(start_s)
            data = data[start:] if end_s == "" else data[start:int(end_s) + 1]
        return {"Body": _Body(data)}

    def head_object(self, *, Bucket, Key):
        self._enter("head_object")
        return {"ContentLength": len(self._require(Bucket, Key))}

    def delete_object(self, *, Bucket, Key):
        self._enter("delete_object")
        self._objects.pop((Bucket, Key), None)

    def get_paginator(self, op):
        assert op == "list_objects_v2", op
        client = self

        class _Paginator:
            def paginate(self, *, Bucket, Prefix):
                items = sorted(
                    k for (b, k) in client._objects if b == Bucket
                    and k.startswith(Prefix))
                # two pages exercise the pagination loop, not just one
                mid = (len(items) + 1) // 2
                for chunk in (items[:mid], items[mid:]):
                    yield {"Contents": [{"Key": k} for k in chunk]}

        return _Paginator()

    def generate_presigned_url(self, op, *, Params, ExpiresIn):
        self._enter("generate_presigned_url")
        return (f"https://fake-s3/{Params['Bucket']}/{Params['Key']}"
                f"?sig=deadbeef&expires={ExpiresIn}")

    # -- multipart -----------------------------------------------------------

    def create_multipart_upload(self, *, Bucket, Key):
        self._enter("create_multipart_upload")
        with self._lock:
            self._mpu_seq += 1
            upload_id = f"mpu-{self._mpu_seq}"
            self._mpu[upload_id] = {"bucket": Bucket, "key": Key,
                                    "parts": {}}
        return {"UploadId": upload_id}

    def upload_part(self, *, Bucket, Key, UploadId, PartNumber, Body):
        self._enter("upload_part")
        mpu = self._mpu[UploadId]
        data = bytes(Body)
        with self._lock:
            mpu["parts"][PartNumber] = data
        return {"ETag": f'"etag-{PartNumber}-{len(data)}"'}

    def complete_multipart_upload(self, *, Bucket, Key, UploadId,
                                  MultipartUpload):
        self._enter("complete_multipart_upload")
        mpu = self._mpu.pop(UploadId)
        listed = [p["PartNumber"] for p in MultipartUpload["Parts"]]
        assert listed == sorted(listed), "parts must complete in order"
        assert set(listed) == set(mpu["parts"]), "missing uploaded parts"
        self._objects[(Bucket, Key)] = b"".join(
            mpu["parts"][n] for n in listed)

    def abort_multipart_upload(self, *, Bucket, Key, UploadId):
        self._enter("abort_multipart_upload")
        self._mpu.pop(UploadId, None)
        self.aborted.append(UploadId)

    # -- helpers -------------------------------------------------------------

    def _require(self, bucket: str, key: str) -> bytes:
        try:
            return self._objects[(bucket, key)]
        except KeyError:
            raise FakeClientError("NoSuchKey", "GetObject") from None

    def dangling_multipart(self) -> int:
        return len(self._mpu)


def install(monkeypatch) -> FakeS3Client:
    """Register fake ``boto3``/``botocore`` modules for one test (undone
    automatically with the monkeypatch fixture, so the absence contract
    checked by test_image_contract is untouched elsewhere)."""
    client = FakeS3Client()

    boto3 = types.ModuleType("boto3")
    boto3.client = lambda service, **kw: client if service == "s3" else None

    botocore = types.ModuleType("botocore")
    exceptions = types.ModuleType("botocore.exceptions")
    exceptions.ClientError = FakeClientError
    botocore.exceptions = exceptions

    monkeypatch.setitem(sys.modules, "boto3", boto3)
    monkeypatch.setitem(sys.modules, "botocore", botocore)
    monkeypatch.setitem(sys.modules, "botocore.exceptions", exceptions)
    return client
