"""Million-user load plane (lzy_tpu/load): trace determinism, the
virtual-clock capacity smoke, and overload robustness.

THE acceptance smoke (ISSUE 13): replay over one simulated hour of
multi-tenant traffic (>= 20k requests) against a fleet-in-threads
gateway in < 60 s wall on CPU, deterministically per seed, and emit a
non-degenerate SLO-curve artifact — TTFT/inter-token p99 vs replica
count plus a shed-rate frontier.  The robustness payload: shed-honoring
clients succeed (backoff on ``retry_after_s``), a hammering client gets
pushback instead of service, queue memory stays bounded, and the
autoscaler absorbs bursts without flapping.
"""

import dataclasses
import hashlib
import os
import time

import pytest

from lzy_tpu.load import (
    Collector, FleetConfig, LoadDriver, SimProfile, TraceConfig,
    build_fleet, capacity_artifact, generate_trace, replay, trace_bytes)
from lzy_tpu.utils.clock import VirtualClock

pytestmark = pytest.mark.load


class TestTraceDeterminism:
    def test_same_seed_byte_identical(self):
        cfg = TraceConfig(seed=11, duration_s=300.0, users=8, tenants=4)
        a, b = trace_bytes(cfg), trace_bytes(cfg)
        assert a == b
        assert hashlib.sha256(a).hexdigest() == \
            hashlib.sha256(trace_bytes(cfg)).hexdigest()

    def test_different_seed_differs(self):
        cfg = TraceConfig(seed=11, duration_s=300.0, users=8, tenants=4)
        assert trace_bytes(cfg) != trace_bytes(
            dataclasses.replace(cfg, seed=12))

    def test_workload_shape(self):
        """Heavy-tailed tenants, conversation revisits, bursty think
        times — the knobs actually move the generated trace."""
        cfg = TraceConfig(seed=3, duration_s=1200.0, users=24, tenants=6)
        users = generate_trace(cfg)
        assert len(users) == 24
        turns = [t for turns in users for t in turns]
        assert len(turns) > 500
        tenants = {t.tenant for t in turns}
        assert len(tenants) >= 3
        # heavy tail: the most popular tenant dominates the least
        counts = sorted((sum(1 for t in turns if t.tenant == ten)
                         for ten in tenants), reverse=True)
        assert counts[0] >= 3 * counts[-1]
        # sessions revisit: some session appears in >1 burst of turns
        assert any(not t.fresh for t in turns)


class TestReplayDeterminism:
    def test_identical_capacity_metrics_across_two_runs(self):
        cfg = TraceConfig(seed=5, duration_s=180.0, users=10, tenants=4)
        fc = FleetConfig(replicas=2, profile=SimProfile(
            slots=4, max_queue=32, kv_blocks=256))
        r1 = replay(cfg, fc)
        r2 = replay(cfg, fc)
        assert r1.requests > 100
        assert r1.metrics() == r2.metrics()

    def test_seed_changes_metrics(self):
        fc = FleetConfig(replicas=2)
        r1 = replay(TraceConfig(seed=1, duration_s=120.0, users=6), fc)
        r2 = replay(TraceConfig(seed=2, duration_s=120.0, users=6), fc)
        assert r1.metrics() != r2.metrics()


class TestCapacitySmoke:
    """The acceptance smoke: >= 1 simulated hour, >= 20k requests,
    < 60 s wall, non-degenerate operating curves."""

    def test_one_hour_twenty_k_requests_under_sixty_seconds(self):
        wall0 = time.perf_counter()
        trace = TraceConfig(seed=6, duration_s=560.0, users=36,
                            tenants=8)
        fleet = FleetConfig(replicas=2, profile=SimProfile(
            slots=8, max_queue=48, kv_blocks=384))
        frontier_fleet = FleetConfig(replicas=1, retry_limit=3,
                                     profile=SimProfile(
                                         slots=4, max_queue=16,
                                         kv_blocks=160))
        artifact = capacity_artifact(
            trace, fleet, replica_counts=[1, 2, 4],
            load_factors=[1.0, 5.0],
            frontier_fleet_cfg=frontier_fleet)
        wall = time.perf_counter() - wall0
        slo, frontier = artifact["slo_curve"], artifact["shed_frontier"]
        requests = (sum(r["requests"] for r in slo)
                    + sum(r["requests"] for r in frontier))
        # scale: >= 1 simulated hour and >= 20k requests, < 60 s wall
        assert artifact["replay"]["virtual_s"] >= 3600.0
        assert requests >= 20_000, requests
        assert wall < 60.0, f"smoke took {wall:.1f}s"
        assert artifact["replay"]["speedup_x"] > 10.0
        # SLO curve non-degenerate: real latencies, p99 >= p50, and
        # more replicas strictly improve tail TTFT across the sweep
        for row in slo:
            assert row["ttft_p99_ms"] >= row["ttft_p50_ms"] > 0.0
            assert row["itl_p99_ms"] >= row["itl_p50_ms"] > 0.0
            assert row["ok"] > 0
        by_n = {row["replicas"]: row for row in slo}
        assert by_n[4]["ttft_p99_ms"] < by_n[2]["ttft_p99_ms"] \
            < by_n[1]["ttft_p99_ms"]
        # shed-rate frontier non-degenerate: overload actually sheds,
        # shedding grows with offered load, queue memory stays bounded
        assert frontier[0]["load_factor"] < frontier[-1]["load_factor"]
        assert frontier[-1]["shed_rate"] > frontier[0]["shed_rate"]
        assert frontier[-1]["shed_rate"] > 0.05
        cap = (frontier_fleet.profile.max_queue
               * max(4, frontier_fleet.replicas * 2))
        for row in frontier:
            assert row["peak_queue_depth"] <= cap
            assert row["retries"] > 0      # pushback was exercised

    def test_session_affinity_shows_in_prefix_hits(self):
        """Conversation re-visits + session pinning: the fleet serves a
        real share of prompt tokens from cache expectations (the radix
        accounting the SimEngine models)."""
        cfg = TraceConfig(seed=9, duration_s=240.0, users=12, tenants=4)
        clock = VirtualClock()
        collector = Collector()
        fc = FleetConfig(replicas=2)
        gw, fleet = build_fleet(fc, clock, collector)
        try:
            driver = LoadDriver(gw, fleet, clock, cfg, fleet_cfg=fc,
                                collector=collector)
            report = driver.run()
            assert report.ok > 120
            agg = fleet.aggregate()
            assert agg["prefix_lookup_tokens"] > 0
            hit_rate = (agg["prefix_hit_tokens"]
                        / agg["prefix_lookup_tokens"])
            assert hit_rate > 0.2, hit_rate
            assert gw.router.stats()["routed_total"] > 0
        finally:
            gw.close()


class TestAgentPipeline:
    """Satellite of ISSUE 20: the agent-pipeline trace shape replayed
    through the virtual-clock fleet. Pipeline sessions are multi-step
    conversations whose inter-turn gap is a seed-deterministic TOOL op;
    after each ok turn the driver mirrors the workflow scheduler's
    fused-chain hook (park the conversation KV + speculative next-step
    prefill), so the fused win is measurable against the unfused
    baseline on the SAME trace."""

    # a fleet with KV headroom: parking pins pages, and speculation
    # spends engine rounds to buy next-step TTFT — on a pool already at
    # the eviction cliff the spend outweighs the win (the bench probe
    # sweeps that trade; here the contract under test is the win)
    TRACE = TraceConfig(seed=3, duration_s=120.0, users=12, tenants=4,
                        agent_pipeline_p=0.8, tool_gap_s=0.5)
    FLEET = FleetConfig(replicas=2)

    def test_pipeline_knob_off_keeps_traces_byte_identical(self):
        """agent_pipeline_p=0 draws no extra randomness: the default
        workload is byte-identical to what pre-pipeline seeds produced
        (every turn non-pipeline, same rng stream)."""
        cfg = TraceConfig(seed=11, duration_s=300.0, users=8, tenants=4)
        users = generate_trace(cfg)
        assert all(not t.pipeline for turns in users for t in turns)
        assert trace_bytes(cfg) == trace_bytes(cfg)

    def test_pipeline_trace_shape(self):
        users = generate_trace(self.TRACE)
        turns = [t for turns in users for t in turns]
        pipe = [t for t in turns if t.pipeline]
        assert len(pipe) > 50
        assert any(not t.pipeline for t in turns)
        # tool gaps are short relative to human think times
        gaps = sorted(t.think_s for t in pipe)
        assert gaps[len(gaps) // 2] < self.TRACE.think_s / 2

    def test_fused_replay_parks_speculates_and_beats_unfused_ttft(self):
        fused = replay(self.TRACE, self.FLEET)
        unfused = replay(self.TRACE, self.FLEET, fuse_pipeline=False)
        # the fused hooks actually fired: conversations parked across
        # tool gaps and speculative next-step prefills landed
        assert fused.pipeline_turns > 50
        assert fused.parked_turns > 0
        assert fused.speculations_ok > 0
        assert unfused.parked_turns == 0
        # the perf claim: with the next step's prefix speculatively
        # cached, median TTFT drops vs the identical unfused trace
        assert fused.ok > 100 and unfused.ok > 100
        assert fused.ttft_p50_ms < unfused.ttft_p50_ms

    def test_fused_replay_is_deterministic(self):
        r1 = replay(self.TRACE, self.FLEET)
        r2 = replay(self.TRACE, self.FLEET)
        assert r1.parked_turns == r2.parked_turns > 0
        assert r1.metrics() == r2.metrics()


class TestGatewayRestart:
    """Satellite of ISSUE 15: a scheduled mid-trace ``gateway_restart``
    event (virtual-clock deterministic) performs a zero-downtime rolling
    restart — a journal-backed successor adopts the predecessor's
    replica engines and the predecessor drains. Contract: zero failed
    requests, bounded added TTFT p99."""

    TRACE = TraceConfig(seed=13, duration_s=300.0, users=12, tenants=4)
    FLEET = FleetConfig(replicas=2, profile=SimProfile(
        slots=6, max_queue=32, kv_blocks=256))

    def test_mid_trace_restart_zero_failures_bounded_ttft(self):
        base = replay(self.TRACE, self.FLEET)
        restarted = replay(self.TRACE, dataclasses.replace(
            self.FLEET, gateway_restart_at_s=150.0))
        # the restart actually happened, by adoption not re-lease
        assert restarted.gateway_restarts == 1
        assert restarted.restart_adopted == self.FLEET.replicas
        # zero failed requests: every offered request finished ok (the
        # draining predecessor sheds at most into a retry, never a
        # failure)
        assert restarted.errors == 0
        assert restarted.timeout == 0
        assert restarted.shed == 0
        assert restarted.ok == restarted.requests > 200
        assert restarted.ok >= base.ok
        # bounded added tail latency: the swap is one draining window,
        # not a re-warm — p99 stays within 50% + one retry backoff of
        # the uninterrupted run
        assert restarted.ttft_p99_ms <= 1.5 * base.ttft_p99_ms + 1000.0

    def test_restart_replay_is_deterministic(self):
        cfg = dataclasses.replace(self.FLEET, gateway_restart_at_s=150.0)
        r1 = replay(self.TRACE, cfg)
        r2 = replay(self.TRACE, cfg)
        assert r1.gateway_restarts == r2.gateway_restarts == 1
        assert r1.metrics() == r2.metrics()


class TestShedHonoring:
    """Load clients honor ``retry_after_s`` — and the plane survives the
    client that does not."""

    def _run(self, hammer):
        trace = TraceConfig(seed=4, duration_s=200.0, users=10,
                            tenants=2, think_s=2.0)
        policies = {
            "t0": {"requests_per_s": 3.0, "burst_s": 1.0,
                   "max_queued": 8},
            "t1": {"requests_per_s": 3.0, "burst_s": 1.0,
                   "max_queued": 8},
        }
        fc = FleetConfig(replicas=1, retry_limit=6,
                         tenant_policies=policies,
                         profile=SimProfile(slots=4, max_queue=16,
                                            kv_blocks=192))
        return replay(trace.scaled(4.0), fc,
                      hammer_tenant="t1" if hammer else None,
                      max_virtual_s=600.0)

    def test_polite_replay_succeeds_hammer_gets_pushback(self):
        """Same trace twice: once all-polite, once with tenant t1
        hammering (retries every 20 ms, hints ignored).  Found-and-fixed
        by this harness: with an ADVISORY hint the hammer used to win
        the bucket refill race outright; ``SloLimiter`` backoff
        enforcement makes honoring the hint the winning strategy."""
        polite_run = self._run(hammer=False)
        hammer_run = self._run(hammer=True)
        p_t1 = polite_run.outcomes_by_tenant.get("t1", {})
        h_t1 = hammer_run.outcomes_by_tenant.get("t1", {})
        # the polite client replays on retry_after_s and gets served
        assert p_t1.get("ok", 0) > 0
        assert p_t1.get("retries", 0) > 0
        # hammering the same tenant converts service into sheds: the
        # enforced backoff window means misbehavior buys pushback, not
        # throughput
        assert h_t1.get("shed", 0) > p_t1.get("shed", 0)
        assert h_t1.get("ok", 0) < p_t1.get("ok", 0)
        # the OTHER tenant is untouched by t1's behavior change
        p_t0 = polite_run.outcomes_by_tenant.get("t0", {})
        h_t0 = hammer_run.outcomes_by_tenant.get("t0", {})
        assert h_t0.get("ok", 0) >= int(0.9 * p_t0.get("ok", 0))
        # bounded queue memory in both worlds
        assert polite_run.peak_queue_depth <= 16
        assert hammer_run.peak_queue_depth <= 16


class TestAutoscalerUnderBursts:
    def test_bursty_traffic_scales_up_without_flapping(self):
        trace = TraceConfig(seed=8, duration_s=600.0, users=24,
                            tenants=4, think_s=6.0, burst_factor=10.0,
                            burst_on_s=120.0, burst_off_s=120.0)
        fc = FleetConfig(
            replicas=1,
            autoscaler=dict(min_replicas=1, max_replicas=6,
                            up_queue_per_replica=4.0, up_sustain_s=5.0,
                            down_busy_fraction=0.2, down_sustain_s=120.0,
                            cooldown_s=30.0),
            profile=SimProfile(slots=4, max_queue=32, kv_blocks=256))
        report = replay(trace, fc, max_virtual_s=1800.0)
        assert report.scale_ups >= 1, report.doc()
        # no flapping: bounded lease churn over the whole replay
        assert report.scale_ups + report.scale_downs <= 12
        assert report.ok > 600


@pytest.mark.slow
@pytest.mark.skipif(not os.environ.get("LZY_SLOW"),
                    reason="full capacity sweep: set LZY_SLOW=1")
class TestFullSweep:
    def test_full_operating_curves(self, tmp_path):
        """LZY_SLOW tier: the bigger artifact — longer traces, wider
        sweeps, plus the WFQ-weight and autoscaler-gain tuning rows."""
        import json

        from conftest import record_tier_run
        from lzy_tpu.load import (
            autoscaler_gain_sweep, wfq_weight_sweep)

        trace = TraceConfig(seed=0, duration_s=1800.0, users=64,
                            tenants=8)
        fleet = FleetConfig(replicas=2, profile=SimProfile(
            slots=8, max_queue=64, kv_blocks=512))
        artifact = capacity_artifact(
            trace, fleet, replica_counts=[1, 2, 4, 8],
            load_factors=[1.0, 2.0, 4.0, 8.0],
            frontier_fleet_cfg=FleetConfig(
                replicas=2, retry_limit=4,
                profile=SimProfile(slots=4, max_queue=24,
                                   kv_blocks=192)))
        artifact["wfq_weight_sweep"] = wfq_weight_sweep(
            dataclasses.replace(trace, duration_s=600.0), fleet,
            [0.5, 2.0, 8.0])
        artifact["autoscaler_gain_sweep"] = autoscaler_gain_sweep(
            dataclasses.replace(trace, duration_s=600.0), fleet, [
                dict(min_replicas=1, max_replicas=8, up_sustain_s=2.0,
                     cooldown_s=5.0),
                dict(min_replicas=1, max_replicas=8, up_sustain_s=10.0,
                     cooldown_s=30.0),
            ])
        out = tmp_path / "capacity_full.json"
        out.write_text(json.dumps(artifact, indent=1, sort_keys=True))
        slo = artifact["slo_curve"]
        assert slo[-1]["ttft_p99_ms"] < slo[0]["ttft_p99_ms"]
        # a bigger WFQ weight buys the tenant tokens share
        ws = artifact["wfq_weight_sweep"]
        assert ws[-1]["tenant_tokens"] >= ws[0]["tenant_tokens"]
        # twitchier gains scale more
        gs = artifact["autoscaler_gain_sweep"]
        assert gs[0]["scale_ups"] >= gs[-1]["scale_ups"]
        record_tier_run("load:full-sweep",
                        f"{sum(r['requests'] for r in slo)} requests")
