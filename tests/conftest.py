"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/SPMD tests run on
8 virtual CPU devices (same XLA partitioner, same collectives), mirroring the
driver's dryrun. Must run before jax is imported anywhere.
"""

import os

# Force the virtual CPU mesh at the jax-config level, not just env vars: the
# machine's site customization may have already registered a TPU platform
# plugin and pinned jax_platforms, which env vars can no longer override.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


def record_tier_run(tier: str, detail: str = "") -> None:
    """Append run evidence for a gated test tier (VERDICT r4 weak #6:
    'gated' must never mean 'unverifiable'). Called by the conda/docker/
    LZY_SLOW-gated tests when they actually execute."""
    import datetime
    import json

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tpu_evidence", "TIER_RUNS.jsonl")
    rec = {
        "t": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "tier": tier,
        "detail": detail,
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


@pytest.fixture()
def tmp_storage_uri(tmp_path):
    return f"file://{tmp_path}/storage"


@pytest.fixture(autouse=True)
def _clear_mem_storage():
    yield
    from lzy_tpu.storage.mem import MemStorageClient

    MemStorageClient.clear_all()
