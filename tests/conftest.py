"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/SPMD tests run on
8 virtual CPU devices (same XLA partitioner, same collectives), mirroring the
driver's dryrun. Must run before jax is imported anywhere.
"""

import os

# Force the virtual CPU mesh at the jax-config level, not just env vars: the
# machine's site customization may have already registered a TPU platform
# plugin and pinned jax_platforms, which env vars can no longer override.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

from lzy_tpu.utils.compat import request_cpu_devices  # noqa: E402

jax.config.update("jax_platforms", "cpu")
request_cpu_devices(8)

# Persistent XLA compilation cache for the test tier. The suite builds the
# SAME tiny-model programs hundreds of times (every engine/fleet/parallel
# test re-jits its own closures, whose jit caches never share), and XLA
# compilation dominates tier-1 wall time — a measured engine build+run
# drops ~3.3s → ~0.7s on a cache hit. The cache is keyed on the HLO +
# compile-options hash, so it can only dedupe byte-identical programs:
# executables (and therefore test numerics) are unchanged. Scoped to the
# test tier only — bench.py measures real compiles and must not see this.
_cache_dir = os.environ.get(
    "LZY_TEST_JAX_CACHE", os.path.join("/tmp", "lzy_test_jax_cache"))
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # default min-compile-time (1s) would skip most tiny-model programs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:  # noqa: BLE001 — older jax without the knobs: run cold
    pass
# worker subprocesses (serve_entrypoint, process workers) inherit the env
# and warm the same cache
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")

import pytest  # noqa: E402


def record_tier_run(tier: str, detail: str = "") -> None:
    """Append run evidence for a gated test tier (VERDICT r4 weak #6:
    'gated' must never mean 'unverifiable'). Called by the conda/docker/
    LZY_SLOW-gated tests when they actually execute."""
    import datetime
    import json

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tpu_evidence", "TIER_RUNS.jsonl")
    rec = {
        "t": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "tier": tier,
        "detail": detail,
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def durable_store_backends():
    """Backends the durable/lease tiers parametrize over (VERDICT r4 #2):
    sqlite (canonical), the fake-DBAPI Postgres store (dialect + retry
    layer, runs everywhere), and a real server behind LZY_PG_DSN."""
    return [
        "sqlite",
        "fakepg",
        pytest.param("postgres", marks=pytest.mark.skipif(
            not os.environ.get("LZY_PG_DSN"),
            reason="set LZY_PG_DSN=postgresql://user:pw@host/db to run "
                   "the real-server leg")),
    ]


def make_durable_store(backend: str, path: str, fresh: bool = True):
    """Construct a store for ``backend``; ``path`` keys shared state so
    two handles on one path see each other (the two-plane topology).
    ``fresh=False`` skips the per-test server-table wipe."""
    if backend == "sqlite":
        from lzy_tpu.durable import OperationStore

        return OperationStore(path)
    if backend == "fakepg":
        from fake_pg import fake_connect

        from lzy_tpu.durable.pg_store import PostgresOperationStore

        return PostgresOperationStore(path, _connect=fake_connect)
    if backend == "postgres":
        from lzy_tpu.durable.pg_store import PostgresOperationStore

        dsn = os.environ["LZY_PG_DSN"]
        s = PostgresOperationStore(dsn)
        if fresh:
            with s._lock:
                for table in ("operations", "kv", "leases"):
                    s._execute(f"DELETE FROM {table}")
        record_tier_run("postgres:durable", dsn.rsplit("@", 1)[-1])
        return s
    raise ValueError(backend)


@pytest.fixture()
def tmp_storage_uri(tmp_path):
    return f"file://{tmp_path}/storage"


@pytest.fixture(autouse=True)
def _clear_mem_storage():
    yield
    from lzy_tpu.storage.mem import MemStorageClient

    MemStorageClient.clear_all()
