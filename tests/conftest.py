"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/SPMD tests run on
8 virtual CPU devices (same XLA partitioner, same collectives), mirroring the
driver's dryrun. Must run before jax is imported anywhere.
"""

import os

# Force the virtual CPU mesh at the jax-config level, not just env vars: the
# machine's site customization may have already registered a TPU platform
# plugin and pinned jax_platforms, which env vars can no longer override.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

from lzy_tpu.utils.compat import request_cpu_devices  # noqa: E402

jax.config.update("jax_platforms", "cpu")
request_cpu_devices(8)

import pytest  # noqa: E402


def record_tier_run(tier: str, detail: str = "") -> None:
    """Append run evidence for a gated test tier (VERDICT r4 weak #6:
    'gated' must never mean 'unverifiable'). Called by the conda/docker/
    LZY_SLOW-gated tests when they actually execute."""
    import datetime
    import json

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tpu_evidence", "TIER_RUNS.jsonl")
    rec = {
        "t": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "tier": tier,
        "detail": detail,
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def durable_store_backends():
    """Backends the durable/lease tiers parametrize over (VERDICT r4 #2):
    sqlite (canonical), the fake-DBAPI Postgres store (dialect + retry
    layer, runs everywhere), and a real server behind LZY_PG_DSN."""
    return [
        "sqlite",
        "fakepg",
        pytest.param("postgres", marks=pytest.mark.skipif(
            not os.environ.get("LZY_PG_DSN"),
            reason="set LZY_PG_DSN=postgresql://user:pw@host/db to run "
                   "the real-server leg")),
    ]


def make_durable_store(backend: str, path: str, fresh: bool = True):
    """Construct a store for ``backend``; ``path`` keys shared state so
    two handles on one path see each other (the two-plane topology).
    ``fresh=False`` skips the per-test server-table wipe."""
    if backend == "sqlite":
        from lzy_tpu.durable import OperationStore

        return OperationStore(path)
    if backend == "fakepg":
        from fake_pg import fake_connect

        from lzy_tpu.durable.pg_store import PostgresOperationStore

        return PostgresOperationStore(path, _connect=fake_connect)
    if backend == "postgres":
        from lzy_tpu.durable.pg_store import PostgresOperationStore

        dsn = os.environ["LZY_PG_DSN"]
        s = PostgresOperationStore(dsn)
        if fresh:
            with s._lock:
                for table in ("operations", "kv", "leases"):
                    s._execute(f"DELETE FROM {table}")
        record_tier_run("postgres:durable", dsn.rsplit("@", 1)[-1])
        return s
    raise ValueError(backend)


@pytest.fixture()
def tmp_storage_uri(tmp_path):
    return f"file://{tmp_path}/storage"


@pytest.fixture(autouse=True)
def _clear_mem_storage():
    yield
    from lzy_tpu.storage.mem import MemStorageClient

    MemStorageClient.clear_all()
