"""SDK-core tests over LocalRuntime (reference tiers: ``pylzy/tests/core`` unit
tests + the local slices of the scenario suite, SURVEY.md §4.1/§4.4)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu import Lzy, op, whiteboard
from lzy_tpu.core.workflow import RemoteCallError, WorkflowError
from lzy_tpu.proxy import is_lzy_proxy, materialized
from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig


@pytest.fixture()
def lzy():
    reg = DefaultStorageRegistry()
    reg.register_storage("default", StorageConfig(uri="mem://wf"), default=True)
    return Lzy(storage_registry=reg)


@op
def inc(x: int) -> int:
    return x + 1


@op
def add(a: int, b: int) -> int:
    return a + b


@op
def duo(x: int) -> tuple[int, str]:
    return x * 2, f"v{x}"


def test_op_without_workflow_runs_directly():
    assert inc(1) == 2


def test_single_op_lazy_then_materialize(lzy):
    with lzy.workflow("wf") as wf:
        r = inc(1)
        assert is_lzy_proxy(r)
        assert not materialized(r)
        assert r == 2  # touch triggers barrier
        assert materialized(r)


def test_chained_ops(lzy):
    with lzy.workflow("wf") as wf:
        r = add(inc(1), inc(2))
    assert r == 5


def test_multi_output_op(lzy):
    with lzy.workflow("wf"):
        a, b = duo(21)
        assert a == 42
        assert b == "v21"


def test_barrier_on_exit_without_touch(lzy):
    log = []

    @op
    def record(x: int) -> int:
        log.append(x)
        return x

    with lzy.workflow("wf"):
        record(5)
    assert log == [5]  # executed on workflow exit even untouched


def test_exception_reraised_with_remote_traceback(lzy):
    @op
    def boom() -> int:
        raise ValueError("inner failure")

    with pytest.raises(RemoteCallError) as exc_info:
        with lzy.workflow("wf"):
            r = boom()
            _ = r + 1
    cause = exc_info.value.__cause__
    assert isinstance(cause, ValueError)
    assert "inner failure" in str(cause)
    assert any("remote traceback" in n for n in getattr(cause, "__notes__", []))


def test_type_validation_rejects_wrong_arg():
    lzy_local = Lzy(storage_registry=_mem_registry())
    with pytest.raises(TypeError, match="expected int"):
        with lzy_local.workflow("wf"):
            inc("not an int")


def _mem_registry():
    reg = DefaultStorageRegistry()
    reg.register_storage("default", StorageConfig(uri="mem://wf2"), default=True)
    return reg


def test_jax_array_through_ops(lzy):
    @op
    def scale(x: jnp.ndarray) -> jnp.ndarray:
        return x * 2.0

    with lzy.workflow("wf"):
        out = scale(jnp.ones((4, 4), jnp.bfloat16))
        arr = np.asarray(out)
    assert arr.shape == (4, 4)
    np.testing.assert_array_equal(arr, np.full((4, 4), 2.0))


def test_bool_and_none_results_materialize_eagerly(lzy):
    @op
    def check(x: int) -> bool:
        return x > 0

    @op
    def nothing() -> None:
        return None

    with lzy.workflow("wf"):
        b = check(3)
        assert b is True  # real bool, not proxy
        n = nothing()
        assert n is None


def test_env_vars_applied_locally(lzy):
    """LocalRuntime applies call env_vars exactly like remote workers do."""
    import os

    from lzy_tpu import env_vars

    @op(env=env_vars(LZY_LOCAL_FLAVOR="mint"))
    def read_flavor() -> str:
        return os.environ.get("LZY_LOCAL_FLAVOR", "unset")

    with lzy.workflow("wf"):
        assert str(read_flavor()) == "mint"
    assert os.environ.get("LZY_LOCAL_FLAVOR") is None


def test_optional_annotations_supported(lzy):
    from typing import Optional

    @op
    def maybe(x: Optional[int]) -> Optional[int]:
        return x

    with lzy.workflow("wf"):
        assert maybe(5) == 5


def test_failed_exit_barrier_aborts_runtime(lzy):
    """An op failing in the implicit exit barrier must abort, not finish."""
    from lzy_tpu.runtime.local import LocalRuntime

    events = []

    class SpyRuntime(LocalRuntime):
        def finish(self, workflow):
            events.append("finish")

        def abort(self, workflow):
            events.append("abort")

    spy_lzy = Lzy(storage_registry=_mem_registry(), runtime=SpyRuntime())

    @op
    def boom() -> int:
        raise ValueError("late failure")

    with pytest.raises(RemoteCallError):
        with spy_lzy.workflow("wf"):
            boom()  # only fails at exit barrier
    assert events == ["abort"]


def test_lazy_arguments_false_forces_producer(lzy):
    order = []

    @op
    def produce() -> int:
        order.append("produce")
        return 1

    @op(lazy_arguments=False)
    def consume(x: int) -> int:
        order.append("consume")
        return x

    with lzy.workflow("wf"):
        p = produce()
        order.append("registering-consume")
        consume(p)  # registration forces produce() via barrier
    assert order == ["registering-consume", "produce", "consume"]


def test_nested_workflow_forbidden(lzy):
    with lzy.workflow("outer"):
        with pytest.raises(WorkflowError, match="already active"):
            with lzy.workflow("inner"):
                pass


def test_abort_on_user_exception_skips_queue(lzy):
    log = []

    @op
    def record(x: int) -> int:
        log.append(x)
        return x

    with pytest.raises(RuntimeError, match="user code"):
        with lzy.workflow("wf"):
            record(1)
            raise RuntimeError("user code")
    assert log == []  # queued call was aborted, not executed


class TestCaching:
    def test_repeated_execs_use_cache(self, lzy):
        runs = []

        @op(cache=True, version="1.0")
        def heavy(x: int) -> int:
            runs.append(x)
            return x * 10

        for _ in range(2):
            with lzy.workflow("wf"):
                r = heavy(4)
                assert r == 40
        assert runs == [4]  # second run served from cache

    def test_version_bump_invalidates(self, lzy):
        runs = []

        def make_op(version):
            @op(cache=True, version=version)
            def heavy(x: int) -> int:
                runs.append(version)
                return x

            return heavy

        with lzy.workflow("wf"):
            make_op("1.0")(1)
        with lzy.workflow("wf"):
            make_op("2.0")(1)
        assert runs == ["1.0", "2.0"]

    def test_different_inputs_different_cache_keys(self, lzy):
        runs = []

        @op(cache=True, version="1.0")
        def heavy(x: int) -> int:
            runs.append(x)
            return x

        with lzy.workflow("wf"):
            heavy(1)
        with lzy.workflow("wf"):
            heavy(2)
        assert runs == [1, 2]

    def test_cached_op_downstream_of_noncached_producer(self, lzy):
        """Cache key must be lineage-stable even when the producer is not
        cached (its output URI is execution-scoped and random)."""
        runs = []

        @op
        def produce(n: int) -> int:
            runs.append("produce")
            return n + 1

        @op(cache=True, version="1.0")
        def consume(x: int) -> int:
            runs.append("consume")
            return x * 2

        for _ in range(2):
            with lzy.workflow("wf"):
                assert consume(produce(1)) == 4
        assert runs == ["produce", "consume", "produce"]

    def test_kwarg_names_in_cache_key(self, lzy):
        """f(x=5) and f(y=5) must not collide in the cache."""
        runs = []

        @op(cache=True, version="1.0")
        def f(x: int = 0, y: int = 0) -> int:
            runs.append((x, y))
            return x - y

        with lzy.workflow("wf"):
            assert f(x=5) == 5
        with lzy.workflow("wf"):
            assert f(y=5) == -5
        assert runs == [(5, 0), (0, 5)]

    def test_chained_cache_keys_stable_across_runs(self, lzy):
        runs = []

        @op(cache=True, version="1.0")
        def first(x: int) -> int:
            runs.append("first")
            return x + 1

        @op(cache=True, version="1.0")
        def second(x: int) -> int:
            runs.append("second")
            return x * 2

        for _ in range(2):
            with lzy.workflow("wf"):
                r = second(first(1))
                assert r == 4
        assert runs == ["first", "second"]


class TestWhiteboards:
    def test_write_finalize_read(self, lzy):
        @whiteboard("best_model")
        @dataclasses.dataclass
        class BestModel:
            score: float
            params: dict

        @op
        def train(seed: int) -> dict:
            return {"w": seed * 1.5}

        with lzy.workflow("wf") as wf:
            wb = wf.create_whiteboard(BestModel, tags=["exp1"])
            wb.params = train(2)  # proxy assignment
            wb.score = 0.9        # local assignment
            wb_id = wb.id

        loaded = lzy.whiteboard(id_=wb_id)
        assert loaded.score == 0.9
        assert loaded.params == {"w": 3.0}
        assert loaded.name == "best_model"

    def test_query_by_name_and_tags(self, lzy):
        @whiteboard("query_wb")
        @dataclasses.dataclass
        class Wb:
            x: int

        for i, tags in enumerate([["a"], ["a", "b"]]):
            with lzy.workflow("wf") as wf:
                wb = wf.create_whiteboard(Wb, tags=tags)
                wb.x = i

        assert len(lzy.whiteboards(name="query_wb")) == 2
        both = lzy.whiteboards(name="query_wb", tags=["b"])
        assert len(both) == 1
        assert both[0].x == 1
        assert lzy.whiteboards(name="missing") == []

    def test_unassigned_field_fails_finalize(self, lzy):
        @whiteboard("partial_wb")
        @dataclasses.dataclass
        class Wb:
            x: int
            y: int

        with pytest.raises(ValueError, match="unassigned"):
            with lzy.workflow("wf") as wf:
                wb = wf.create_whiteboard(Wb)
                wb.x = 1

    def test_non_whiteboard_type_rejected(self, lzy):
        class Plain:
            pass

        with lzy.workflow("wf") as wf:
            with pytest.raises(TypeError, match="not a whiteboard type"):
                wf.create_whiteboard(Plain)


class TestMainModuleOpPickling:
    """``__main__`` ops pickle as reference + embedded copy: the same
    interpreter resolves the live object (shared state), another process
    falls back to the shipped clone (its __main__ is a different module)."""

    def _main_op(self):
        import sys

        from lzy_tpu.core.op import op as op_decorator

        @op_decorator
        def main_op(x: int) -> int:
            return x + 5

        main_op.__module__ = "__main__"
        main_op.__qualname__ = "main_op"
        main_op.func.__module__ = "__main__"
        main_op.func.__qualname__ = "main_op"
        setattr(sys.modules["__main__"], "main_op", main_op)
        return main_op

    def test_same_interpreter_resolves_live_object(self):
        import pickle
        import sys

        main_op = self._main_op()
        try:
            clone = pickle.loads(pickle.dumps(main_op))
            assert clone is main_op
        finally:
            delattr(sys.modules["__main__"], "main_op")

    def test_foreign_interpreter_gets_by_value_copy(self):
        import pickle
        import sys

        main_op = self._main_op()
        data = pickle.dumps(main_op)
        # simulate the worker binary: its __main__ lacks the attribute
        delattr(sys.modules["__main__"], "main_op")
        clone = pickle.loads(data)
        assert clone is not main_op
        assert clone(3) == 8           # runs outside a workflow
