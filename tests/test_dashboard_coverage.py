"""Every registered metric must reach the generated Grafana dashboard.

``tools/gen_dashboard.py`` builds panels from the metrics REGISTRY after
importing a curated list of service modules. The failure mode this file
pins: a new module registers ``lzy_*`` metrics but is never added to the
generator's import list — the process registry sees the metric in tests
(everything is imported here), the standalone generator does not, and
the dashboard silently loses the panel. So:

- the generator runs in a SUBPROCESS (its own imports only) and its
  panel set must cover every metric this process can find by walking the
  whole ``lzy_tpu`` package;
- the committed ``deploy/grafana/dashboard.json`` must equal a fresh
  generation (hand-edits and forgotten regens both fail loudly).
"""

import importlib
import json
import os
import pkgutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DASHBOARD = os.path.join(REPO, "deploy", "grafana", "dashboard.json")


def _walk_import_all():
    """Import every importable lzy_tpu module so each one's metrics land
    in the process REGISTRY. Modules with unavailable optional deps are
    skipped — they cannot register metrics in production either."""
    import lzy_tpu

    for info in pkgutil.walk_packages(lzy_tpu.__path__,
                                      prefix="lzy_tpu."):
        try:
            importlib.import_module(info.name)
        except Exception:  # noqa: BLE001 — optional deps, script mains
            pass


def _registry_names():
    from lzy_tpu.utils.metrics import REGISTRY

    return set(REGISTRY._metrics)


class TestDashboardCoversRegistry:
    def test_every_registered_metric_has_a_panel(self):
        _walk_import_all()
        names = _registry_names()
        assert names, "metric walk found nothing — broken test"
        committed = json.load(open(DASHBOARD))
        covered = set(committed.get("_generated_from", []))
        missing = sorted(names - covered)
        assert not missing, (
            f"metrics registered in lzy_tpu but absent from the "
            f"dashboard: {missing}. Add their module to "
            f"tools/gen_dashboard.py registry_metrics() and run "
            f"`python tools/gen_dashboard.py`.")

    @pytest.mark.slow
    def test_committed_dashboard_is_regenerated(self, tmp_path):
        """A fresh standalone generation must byte-match the committed
        dashboard — running the generator in a subprocess also proves
        its OWN import list reaches every metric (no inherited
        test-process imports). Slow tier: the subprocess pays a full
        jax import."""
        before = open(DASHBOARD, "rb").read()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "gen_dashboard.py")],
            cwd=tmp_path, env=env, capture_output=True, text=True,
            timeout=300)
        assert out.returncode == 0, out.stderr
        after = open(DASHBOARD, "rb").read()
        assert before == after, (
            "deploy/grafana/dashboard.json is stale — commit the "
            "regenerated file (python tools/gen_dashboard.py)")
