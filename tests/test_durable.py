"""Durable-operation kernel tests, modeled on the reference's restart tests
(``LzyServiceRestartTests``, ``RestartExecuteGraphTest`` — SURVEY.md §4.3):
kill mid-operation via injected failures, then "reboot" the service and assert
resume from the persisted step."""

import threading
import time

import pytest

from lzy_tpu.durable import (
    DONE,
    FAILED,
    RUNNING,
    InjectedFailures,
    OperationRunner,
    OperationsExecutor,
    OperationStore,
    StepResult,
)


@pytest.fixture(autouse=True)
def _clear_failures():
    yield
    InjectedFailures.clear()


from conftest import durable_store_backends, make_durable_store


@pytest.fixture(params=durable_store_backends())
def store(request, tmp_path):
    s = make_durable_store(request.param, str(tmp_path / "meta.db"))
    yield s
    s.close()


def make_executor(store, runners):
    ex = OperationsExecutor(store, workers=2)
    for kind, factory in runners.items():
        ex.register(kind, factory)
    return ex


class ThreeStep(OperationRunner):
    kind = "three_step"
    log = []

    def steps(self):
        return [
            ("a", self._a),
            ("b", self._b),
            ("c", self._c),
        ]

    def _a(self):
        self.hook("a")
        self.log.append("a")
        self.state["a_done"] = True
        return StepResult.CONTINUE

    def _b(self):
        self.hook("b")
        self.log.append("b")
        self.state["b_done"] = True
        return StepResult.CONTINUE

    def _c(self):
        self.log.append("c")
        return StepResult.finish({"ok": True, **self.state})


def test_steps_run_in_order_and_persist(store):
    ThreeStep.log = []
    ex = make_executor(store, {"three_step": ThreeStep})
    op_id = ex.submit("three_step", {"x": 1})
    record = ex.await_op(op_id, timeout_s=10)
    assert record.status == DONE
    assert record.result == {"ok": True, "x": 1, "a_done": True, "b_done": True}
    assert ThreeStep.log == ["a", "b", "c"]
    ex.shutdown()


def test_idempotency_key_dedup(store):
    ThreeStep.log = []
    ex = make_executor(store, {"three_step": ThreeStep})
    id1 = ex.submit("three_step", {}, idempotency_key="k1")
    id2 = ex.submit("three_step", {}, idempotency_key="k1")
    assert id1 == id2
    ex.await_op(id1, timeout_s=10)
    assert ThreeStep.log.count("a") == 1
    ex.shutdown()


def test_crash_and_restart_resumes_from_persisted_step(store):
    """The restart discipline: crash at step b, reboot, resume at b (a is NOT
    re-run)."""
    ThreeStep.log = []
    InjectedFailures.arm("three_step.b")
    ex1 = make_executor(store, {"three_step": ThreeStep})
    op_id = ex1.submit("three_step", {})
    time.sleep(0.5)
    record = store.load(op_id)
    assert record.status == RUNNING  # crashed, not failed
    assert record.step == 1          # step a persisted
    assert record.state["a_done"] is True
    ex1.shutdown()

    # "reboot": fresh executor over the same store
    ex2 = make_executor(store, {"three_step": ThreeStep})
    assert ex2.restore() == 1
    final = ex2.await_op(op_id, timeout_s=10)
    assert final.status == DONE
    assert ThreeStep.log == ["a", "b", "c"]  # a exactly once
    ex2.shutdown()


class Polling(OperationRunner):
    kind = "polling"
    ready_at = 0.0

    def steps(self):
        return [("poll", self._poll)]

    def _poll(self):
        self.state["polls"] = self.state.get("polls", 0) + 1
        if time.time() < Polling.ready_at:
            return StepResult.restart(0.05)
        return StepResult.finish(self.state["polls"])


def test_restart_outcome_polls_until_ready(store):
    Polling.ready_at = time.time() + 0.4
    ex = make_executor(store, {"polling": Polling})
    op_id = ex.submit("polling", {})
    record = ex.await_op(op_id, timeout_s=10)
    assert record.status == DONE
    assert record.result >= 2  # several poll rounds happened
    ex.shutdown()


class Failing(OperationRunner):
    kind = "failing"
    compensated = []

    def steps(self):
        return [("die", self._die)]

    def _die(self):
        raise RuntimeError("boom")

    def on_failed(self, error):
        Failing.compensated.append(str(error))


def test_terminal_failure_marks_failed_and_compensates(store):
    Failing.compensated = []
    ex = make_executor(store, {"failing": Failing})
    op_id = ex.submit("failing", {})
    record = ex.await_op(op_id, timeout_s=10)
    assert record.status == FAILED
    assert "boom" in record.error
    assert Failing.compensated == ["boom"]
    ex.shutdown()


class Sleepy(OperationRunner):
    kind = "sleepy"
    expired = []

    def steps(self):
        return [("wait", lambda: StepResult.restart(0.05))]

    def on_expired(self):
        Sleepy.expired.append(self.record.id)


def test_deadline_expiry(store):
    Sleepy.expired = []
    ex = make_executor(store, {"sleepy": Sleepy})
    op_id = ex.submit("sleepy", {}, deadline_s=0.3)
    record = ex.await_op(op_id, timeout_s=10)
    assert record.status == FAILED
    assert "deadline" in record.error
    assert Sleepy.expired == [op_id]
    ex.shutdown()


def test_concurrent_operations(store):
    done = []

    class Worker(OperationRunner):
        kind = "worker"

        def steps(self):
            return [("go", self._go)]

        def _go(self):
            time.sleep(0.02)
            done.append(self.record.id)
            return StepResult.finish(None)

    ex = make_executor(store, {"worker": Worker})
    ids = [ex.submit("worker", {"i": i}) for i in range(10)]
    for op_id in ids:
        ex.await_op(op_id, timeout_s=10)
    assert sorted(done) == sorted(ids)
    ex.shutdown()


def test_unknown_kind_rejected(store):
    ex = make_executor(store, {})
    with pytest.raises(KeyError, match="no runner registered"):
        ex.submit("ghost", {})
    ex.shutdown()
