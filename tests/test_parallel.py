"""Parallel-layer tests on the 8-device virtual CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``) — same XLA partitioner and
collectives as TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lzy_tpu.parallel import (
    MeshSpec,
    TrainState,
    fsdp_mesh,
    make_train_step,
    mesh_for,
    mfu,
    named_sharding,
    ring_attention,
    shard_tree,
    infer_param_logical_axes,
)


def test_eight_devices_available():
    assert jax.device_count() == 8


class TestMesh:
    def test_fsdp_mesh_shape(self):
        mesh = fsdp_mesh()
        assert mesh.shape == {"pp": 1, "dp": 1, "fsdp": 8, "ep": 1,
                              "tp": 1, "sp": 1}

    def test_mixed_mesh(self):
        mesh = mesh_for(tp=2, fsdp=-1)
        assert mesh.shape["tp"] == 2
        assert mesh.shape["fsdp"] == 4

    def test_bad_mesh_rejected(self):
        with pytest.raises(ValueError, match="needs 6 devices"):
            MeshSpec(dp=2, tp=3).build()
        with pytest.raises(ValueError, match="not divisible"):
            MeshSpec(dp=3, fsdp=-1).build()
        with pytest.raises(ValueError, match="one mesh axis"):
            MeshSpec(dp=-1, fsdp=-1).build()


class TestSharding:
    def test_named_sharding_spec(self):
        mesh = fsdp_mesh()
        # activations: batch over (dp, fsdp); params: embed over fsdp, mlp over tp
        assert named_sharding(mesh, "batch", None).spec == P(("dp", "fsdp"), None)
        assert named_sharding(mesh, "embed", "mlp").spec == P("fsdp", "tp")

    def test_shard_tree_places_on_devices(self):
        mesh = fsdp_mesh()
        params = {"w": jnp.ones((16, 8)), "b": jnp.zeros((8,))}
        sharded = shard_tree(
            params, mesh, {"w": ("embed", None), "b": (None,)}
        )
        # w's first dim (16) split over 8 fsdp devices → shard shape (2, 8)
        shard_shapes = {s.data.shape for s in sharded["w"].addressable_shards}
        assert shard_shapes == {(2, 8)}
        assert len(sharded["b"].addressable_shards) == 8  # replicated

    def test_infer_logical_axes_picks_largest_dim(self):
        params = {"k": jnp.ones((4, 100)), "v": jnp.ones((3,))}
        axes = infer_param_logical_axes(params)
        assert axes["k"] == (None, "embed")
        assert axes["v"] == (None,)


class TestTrainStep:
    def _setup(self, accum_steps=1):
        mesh = fsdp_mesh()
        params = {
            "w1": jnp.ones((16, 32), jnp.float32) * 0.01,
            "w2": jnp.ones((32, 4), jnp.float32) * 0.01,
        }

        def loss_fn(p, batch):
            x, y = batch["x"], batch["y"]
            h = jnp.tanh(x @ p["w1"])
            logits = h @ p["w2"]
            return jnp.mean((logits - y) ** 2)

        tx = optax.adam(1e-2)
        step, shard_state, batch_sh = make_train_step(
            loss_fn, tx, mesh=mesh,
            param_logical_axes={"w1": (None, "embed"), "w2": ("embed", None)},
            batch_logical_axes=("batch", None),
            accum_steps=accum_steps,
        )
        state = shard_state(TrainState.create(params, tx))
        batch = {
            "x": jnp.ones((16, 16)),
            "y": jnp.zeros((16, 4)),
        }
        return step, state, batch, batch_sh

    def test_loss_decreases(self):
        step, state, batch, _ = self._setup()
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_params_stay_sharded(self):
        step, state, batch, _ = self._setup()
        state, _ = step(state, batch)
        sh = state.params["w1"].sharding
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P(None, "fsdp")

    def test_grad_accumulation_matches_full_batch(self):
        step1, state1, batch, _ = self._setup(accum_steps=1)
        step4, state4, _, _ = self._setup(accum_steps=4)
        s1, m1 = step1(state1, batch)
        s4, m4 = step4(state4, batch)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m4["loss"]), rtol=1e-5
        )
        w1_a = np.asarray(jax.device_get(s1.params["w1"]))
        w1_b = np.asarray(jax.device_get(s4.params["w1"]))
        # adam drives weights through ~0 after one step; relative tolerance is
        # meaningless there, compare absolutely at float32 resolution
        np.testing.assert_allclose(w1_a, w1_b, atol=1e-8)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_attention(self, causal):
        mesh = mesh_for(sp=8)
        b, h, s, d = 2, 4, 64, 16
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
        k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
        v = jax.random.normal(kv, (b, h, s, d), jnp.float32)

        out = ring_attention(q, k, v, mesh=mesh, causal=causal)

        # dense reference
        scale = d ** -0.5
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            logits = jnp.where(mask, logits, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, axis=-1), v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_jittable_and_sharded(self):
        mesh = mesh_for(sp=8)
        b, h, s, d = 1, 2, 32, 8
        q = jnp.ones((b, h, s, d))

        @jax.jit
        def run(q):
            return ring_attention(q, q, q, mesh=mesh, causal=True)

        out = run(q)
        assert out.shape == q.shape


def test_mfu_math():
    # 1000 tok/s on a 1B model over 16 v5e chips
    val = mfu(1000.0, 1_000_000_000, 16, chip="v5e")
    assert 0 < val < 1
    np.testing.assert_allclose(val, 6e12 / (197e12 * 16), rtol=1e-6)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from lzy_tpu.parallel import ulysses_attention

        mesh = mesh_for(sp=8)
        b, h, s, d = 2, 8, 64, 16
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q, k, v = (jax.random.normal(x, (b, h, s, d), jnp.float32) for x in ks)

        out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)

        scale = d ** -0.5
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            logits = jnp.where(mask, logits, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_ring(self):
        from lzy_tpu.parallel import ulysses_attention

        mesh = mesh_for(sp=8)
        b, h, s, d = 1, 8, 128, 8
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(x, (b, h, s, d), jnp.float32) for x in ks)
        a = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        b_out = ring_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_out),
                                   atol=3e-5, rtol=3e-5)

    def test_head_divisibility_enforced(self):
        from lzy_tpu.parallel import ulysses_attention

        mesh = mesh_for(sp=8)
        q = jnp.ones((1, 6, 64, 8))  # 6 heads not divisible by sp=8
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=mesh)


class TestHybridMesh:
    """Multi-slice ICI x DCN meshes (virtual slices on CPU devices)."""

    def test_dcn_dp_layout_keeps_slices_contiguous(self):
        from lzy_tpu.parallel import hybrid_mesh

        mesh = hybrid_mesh(dcn_dp=2, fsdp=-1)
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "pp": 1, "dp": 2, "fsdp": 4, "ep": 1, "tp": 1, "sp": 1}
        devs = jax.devices()
        # dp index 0 must hold exactly slice 0 (first half of the devices):
        # fsdp collectives then never cross the DCN boundary
        dp0 = set(mesh.devices[0, 0, :, 0, 0, 0].ravel().tolist())
        assert dp0 == set(devs[:4])
        dp1 = set(mesh.devices[0, 1, :, 0, 0, 0].ravel().tolist())
        assert dp1 == set(devs[4:])

    def test_dcn_pp_with_inner_axes(self):
        from lzy_tpu.parallel import hybrid_mesh

        mesh = hybrid_mesh(dcn_pp=2, tp=2, fsdp=2)
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "pp": 2, "dp": 1, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1}
        devs = jax.devices()
        assert set(mesh.devices[0].ravel().tolist()) == set(devs[:4])

    def test_single_slice_falls_back(self):
        from lzy_tpu.parallel import hybrid_mesh, mesh_for

        mesh = hybrid_mesh(fsdp=-1)
        assert mesh.devices.shape == mesh_for(fsdp=-1).devices.shape

    def test_trains_on_hybrid_mesh(self):
        """A sharded train step over a dcn_dp x fsdp hybrid mesh runs and
        learns — the full multi-slice code path minus the physical DCN."""
        import optax

        from lzy_tpu.models import llama, unbox
        from lzy_tpu.parallel import TrainState, hybrid_mesh, make_train_step

        cfg = llama.LlamaConfig.tiny(vocab_size=128)
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        mesh = hybrid_mesh(dcn_dp=2, fsdp=2, tp=2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg), optax.adamw(1e-2), mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"))
        state = shard_state(TrainState.create(unbox(boxed), optax.adamw(1e-2)))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
        losses = []
        for _ in range(4):
            state, m = step(state, {"tokens": tokens})
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_errors(self):
        from lzy_tpu.parallel import hybrid_mesh

        with pytest.raises(ValueError, match="not divisible"):
            hybrid_mesh(dcn_dp=3, fsdp=-1)
        with pytest.raises(ValueError, match="may not be -1"):
            hybrid_mesh(dcn_dp=2, dp=-1)
        with pytest.raises(ValueError, match="dcn axes must be >= 1"):
            hybrid_mesh(dcn_dp=-1, fsdp=-1)


class TestSegmentedSequenceParallel:
    """Packed documents under sequence parallelism: ids ride the ring with
    K/V (or all-gather under Ulysses), so documents may straddle shards."""

    @staticmethod
    def _inputs(b=2, h=4, s=64, d=16, seed=0):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
        k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
        v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
        # uneven documents, deliberately NOT aligned to the 8-way shards
        cuts = np.array([13, 30, 47])
        seg = jnp.asarray(
            np.searchsorted(cuts, np.arange(s), side="right")[None, :]
            .repeat(b, 0)
        )
        return q, k, v, seg

    @staticmethod
    def _dense(q, k, v, seg, causal):
        s = q.shape[2]
        scale = q.shape[-1] ** -0.5
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        keep = seg[:, None, :, None] == seg[:, None, None, :]
        if causal:
            keep = keep & np.tril(np.ones((s, s), bool))[None, None]
        logits = jnp.where(keep, logits, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd",
                          jax.nn.softmax(logits, axis=-1), v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_matches_dense(self, causal):
        mesh = mesh_for(sp=8)
        q, k, v, seg = self._inputs()
        out = ring_attention(q, k, v, mesh=mesh, causal=causal,
                             segment_ids=seg)
        ref = self._dense(q, k, v, seg, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ulysses_matches_dense(self):
        from lzy_tpu.parallel.ulysses import ulysses_attention

        mesh = mesh_for(sp=8)
        q, k, v, seg = self._inputs(h=8)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True,
                                segment_ids=seg)
        ref = self._dense(q, k, v, seg, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_packed_train_step_on_sp_mesh(self):
        """Differentiate a packed llama train step through ring attention."""
        import dataclasses

        import optax

        from lzy_tpu.models import llama, unbox
        from lzy_tpu.parallel import TrainState, make_train_step

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=64),
                                  use_ring_attention=True)
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        mesh = mesh_for(dp=2, sp=4)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), optax.adam(1e-3), mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(unbox(boxed),
                                              optax.adam(1e-3)))
        rng = np.random.default_rng(0)
        batch = {
            "tokens": jnp.asarray(rng.integers(0, 64, (2, 64))),
            "segments": jnp.asarray(
                np.searchsorted([21, 40], np.arange(64), side="right")
                [None, :].repeat(2, 0)
            ),
        }
        losses = []
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]


class TestEvalStep:
    def test_eval_matches_loss_and_never_mutates_params(self):
        import dataclasses

        import optax

        from lzy_tpu.models import llama
        from lzy_tpu.models.llama import LlamaConfig
        from lzy_tpu.parallel import (
            TrainState, make_eval_step, make_train_step, mesh_for)

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=128),
                                  dtype=jnp.float32)
        mesh = mesh_for(8, fsdp=4, tp=2)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        loss_fn = llama.make_loss_fn(cfg, mesh)
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            loss_fn, tx, mesh=mesh, param_logical_axes=axes,
            batch_logical_axes=("batch", "seq"), donate=False)
        state = shard_state(TrainState.create(params, tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)}

        eval_step = make_eval_step(loss_fn, mesh=mesh)
        before = float(eval_step(state.params, batch)["loss"])
        # eval over the SHARDED params equals the direct loss
        direct = float(loss_fn(jax.device_get(state.params), batch))
        np.testing.assert_allclose(before, direct, rtol=1e-5)

        # interleave: train one step, eval again — params still usable
        # (no donation) and the eval loss tracks training
        state, _ = step(state, batch)
        after = float(eval_step(state.params, batch)["loss"])
        assert after < before

    def test_eval_step_dict_metrics(self):
        from lzy_tpu.parallel import make_eval_step, mesh_for

        mesh = mesh_for(8, fsdp=-1)

        def metrics(params, batch):
            x = batch["x"]
            return {"mean": (x * params["w"]).mean(),
                    "max": (x * params["w"]).max()}

        eval_step = make_eval_step(metrics, mesh=mesh,
                                   batch_logical_axes=("batch",))
        out = eval_step({"w": jnp.float32(2.0)},
                        {"x": jnp.arange(8.0)})
        np.testing.assert_allclose(float(out["mean"]), 7.0)
        np.testing.assert_allclose(float(out["max"]), 14.0)


class TestCustomRuleThreading:
    """ADVICE r5: activation anchors and the batch-sharded attention
    wrapper must follow the ACTIVE rule table, not assume DEFAULT_RULES
    and dp/fsdp/tp axis names — a remapped deployment (here: one custom
    'data' axis) used to crash on the missing mesh axes."""

    def _rules(self):
        return {"batch": "data", "embed": "data", "vocab": None,
                "mlp": None, "heads": None, "heads_merged": None,
                "seq": None, "act_embed": None, "act_vocab": None,
                "act_mlp": None, "act_heads": None, "channels_out": None}

    def test_llama_trains_on_remapped_mesh(self):
        from jax.sharding import Mesh

        from lzy_tpu.models import llama, unbox
        from lzy_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig.tiny(vocab_size=128)
        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        rules = self._rules()
        boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-3)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh, rules=rules), tx, mesh=mesh,
            param_logical_axes=axes, rules=rules,
            batch_logical_axes=("batch", "seq"))
        state = shard_state(TrainState.create(unbox(boxed), tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (16, 32), 0, cfg.vocab_size)}
        state, metrics = step(state, batch)
        assert 0.0 < float(metrics["loss"]) < 20.0
        emb = state.params["embed_tokens"]
        assert "data" in str(emb.sharding.spec), emb.sharding.spec

    def test_remapped_matches_default_rules_numerics(self):
        """Sharding rules relocate data; they must not change the loss."""
        from lzy_tpu.models import llama, unbox
        from lzy_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig.tiny(vocab_size=128)
        boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = unbox(boxed)
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)}

        from jax.sharding import Mesh

        default_mesh = mesh_for(8, fsdp=-1)
        ref = float(jax.jit(llama.make_loss_fn(cfg, default_mesh))(
            params, batch))
        custom = Mesh(np.array(jax.devices()[:8]), ("data",))
        got = float(jax.jit(llama.make_loss_fn(
            cfg, custom, rules=self._rules()))(params, batch))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_freeze_rules_roundtrip(self):
        from lzy_tpu.parallel.sharding import freeze_rules

        rules = {"batch": ("dp", "fsdp"), "embed": "fsdp", "seq": None}
        frozen = freeze_rules(rules)
        assert hash(frozen) is not None
        assert dict(frozen) == rules
        assert freeze_rules(None) is None and freeze_rules({}) is None
