"""Native slot-streamer tests: serve, pull, interrupt + offset resume,
integrity — the data-plane contract of SURVEY.md §3.4 at the native layer."""

import os

import pytest

from lzy_tpu.native import (
    SlotServer,
    fnv1a_file,
    native_available,
    pull_with_resume,
)
from lzy_tpu.native.slots import pull, remote_size

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


@pytest.fixture()
def served_file(tmp_path):
    root = tmp_path / "root"
    root.mkdir()
    payload = os.urandom(3 * (1 << 20) + 12345)  # ~3MB, odd size
    (root / "data.bin").write_bytes(payload)
    with SlotServer(str(root)) as srv:
        yield srv, payload, tmp_path


def test_full_pull_and_integrity(served_file):
    srv, payload, tmp = served_file
    dest = tmp / "out.bin"
    n = pull("127.0.0.1", srv.port, "data.bin", str(dest))
    assert n == len(payload)
    assert dest.read_bytes() == payload
    assert fnv1a_file(str(dest)) == fnv1a_file(str(tmp / "root" / "data.bin"))


def test_remote_size(served_file):
    srv, payload, _ = served_file
    assert remote_size("127.0.0.1", srv.port, "data.bin") == len(payload)


def test_interrupted_transfer_resumes_from_offset(served_file):
    srv, payload, tmp = served_file
    dest = tmp / "out.bin"
    # simulate a dying connection: cap the first pull mid-file
    n1 = pull("127.0.0.1", srv.port, "data.bin", str(dest), max_bytes=1 << 20)
    assert 0 < n1 < len(payload)
    # resume pulls only the remainder
    n2 = pull_with_resume("127.0.0.1", srv.port, "data.bin", str(dest))
    assert n2 == len(payload)
    assert dest.read_bytes() == payload


def test_missing_remote_object(served_file):
    srv, _, tmp = served_file
    with pytest.raises(OSError):
        pull("127.0.0.1", srv.port, "nope.bin", str(tmp / "x"))


def test_path_escape_rejected(served_file):
    srv, _, tmp = served_file
    secret = tmp / "secret.txt"
    secret.write_text("top secret")
    with pytest.raises(OSError):
        pull("127.0.0.1", srv.port, "../secret.txt", str(tmp / "y"))


def test_nested_names_served(served_file):
    srv, _, tmp = served_file
    sub = tmp / "root" / "a" / "b"
    sub.mkdir(parents=True)
    (sub / "n.bin").write_bytes(b"nested")
    dest = tmp / "n.out"
    assert pull("127.0.0.1", srv.port, "a/b/n.bin", str(dest)) == 6
    assert dest.read_bytes() == b"nested"


def test_p2p_channel_path_in_cluster(tmp_path):
    """End-to-end: with p2p enabled, a consumer on another VM pulls the
    producer's value through the native slot stream (device residency is
    disabled here to force the byte path)."""
    from lzy_tpu import op
    from lzy_tpu.service import InProcessCluster

    cluster = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        p2p_spill_root=str(tmp_path / "spill"),
    )
    try:
        @op
        def produce_text() -> str:
            return "payload-" * 1000

        @op
        def consume_text(x: str) -> int:
            return len(x)

        from lzy_tpu.proxy import get_proxy_entry_id

        lzy = cluster.lzy()
        with lzy.workflow("p2p") as wf:
            p = produce_text()
            assert len(str(p)) == 8000          # barrier 1: producer runs
            eid = get_proxy_entry_id(p)
            # force the byte path: evict the device-resident value AND delete
            # the storage object (keep .meta) — only the native peer stream
            # can satisfy the consumer now
            cluster.channels.device.evict_execution([eid])
            uri = wf.snapshot.get_entry(eid).storage_uri
            cluster.storage_client.delete(uri)
            n = consume_text(p)
            assert n == 8000                    # served by the slot peer
    finally:
        cluster.shutdown()


def test_concurrent_pulls(served_file):
    import threading

    srv, payload, tmp = served_file
    errors = []

    def one(i):
        try:
            dest = tmp / f"c{i}.bin"
            pull("127.0.0.1", srv.port, "data.bin", str(dest))
            assert dest.read_bytes() == payload
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
