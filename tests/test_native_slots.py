"""Native slot-streamer tests: serve, pull, interrupt + offset resume,
integrity — the data-plane contract of SURVEY.md §3.4 at the native layer."""

import os

import pytest

from lzy_tpu.native import (
    SlotServer,
    fnv1a_file,
    native_available,
    pull_with_resume,
)
from lzy_tpu.native.slots import pull, remote_size

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


@pytest.fixture()
def served_file(tmp_path):
    root = tmp_path / "root"
    root.mkdir()
    payload = os.urandom(3 * (1 << 20) + 12345)  # ~3MB, odd size
    (root / "data.bin").write_bytes(payload)
    with SlotServer(str(root)) as srv:
        yield srv, payload, tmp_path


def test_full_pull_and_integrity(served_file):
    srv, payload, tmp = served_file
    dest = tmp / "out.bin"
    n = pull("127.0.0.1", srv.port, "data.bin", str(dest))
    assert n == len(payload)
    assert dest.read_bytes() == payload
    assert fnv1a_file(str(dest)) == fnv1a_file(str(tmp / "root" / "data.bin"))


def test_remote_size(served_file):
    srv, payload, _ = served_file
    assert remote_size("127.0.0.1", srv.port, "data.bin") == len(payload)


def test_interrupted_transfer_resumes_from_offset(served_file):
    srv, payload, tmp = served_file
    dest = tmp / "out.bin"
    # simulate a dying connection: cap the first pull mid-file
    n1 = pull("127.0.0.1", srv.port, "data.bin", str(dest), max_bytes=1 << 20)
    assert 0 < n1 < len(payload)
    # resume pulls only the remainder
    n2 = pull_with_resume("127.0.0.1", srv.port, "data.bin", str(dest))
    assert n2 == len(payload)
    assert dest.read_bytes() == payload


def test_missing_remote_object(served_file):
    srv, _, tmp = served_file
    with pytest.raises(OSError):
        pull("127.0.0.1", srv.port, "nope.bin", str(tmp / "x"))


def test_path_escape_rejected(served_file):
    srv, _, tmp = served_file
    secret = tmp / "secret.txt"
    secret.write_text("top secret")
    with pytest.raises(OSError):
        pull("127.0.0.1", srv.port, "../secret.txt", str(tmp / "y"))


def test_nested_names_served(served_file):
    srv, _, tmp = served_file
    sub = tmp / "root" / "a" / "b"
    sub.mkdir(parents=True)
    (sub / "n.bin").write_bytes(b"nested")
    dest = tmp / "n.out"
    assert pull("127.0.0.1", srv.port, "a/b/n.bin", str(dest)) == 6
    assert dest.read_bytes() == b"nested"


def test_p2p_channel_path_in_cluster(tmp_path):
    """End-to-end: with p2p enabled, a consumer on another VM pulls the
    producer's value through the native slot stream (device residency is
    disabled here to force the byte path)."""
    from lzy_tpu import op
    from lzy_tpu.service import InProcessCluster

    cluster = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        p2p_spill_root=str(tmp_path / "spill"),
    )
    try:
        @op
        def produce_text() -> str:
            return "payload-" * 1000

        @op
        def consume_text(x: str) -> int:
            return len(x)

        from lzy_tpu.proxy import get_proxy_entry_id

        lzy = cluster.lzy()
        with lzy.workflow("p2p") as wf:
            p = produce_text()
            assert len(str(p)) == 8000          # barrier 1: producer runs
            eid = get_proxy_entry_id(p)
            # force the byte path: evict the device-resident value AND delete
            # the storage object (keep .meta) — only the native peer stream
            # can satisfy the consumer now
            cluster.channels.device.evict_execution([eid])
            uri = wf.snapshot.get_entry(eid).storage_uri
            cluster.storage_client.delete(uri)
            n = consume_text(p)
            assert n == 8000                    # served by the slot peer
    finally:
        cluster.shutdown()


class TestPeerFailover:
    """channels/p2p failure path: a peer dying mid-stream leaves a
    partial file that the NEXT peer resumes from byte offset — the
    consumer never re-transfers the prefix it already has, and the FNV
    check still gates what counts as success."""

    def _two_peers(self, tmp_path, payload):
        from lzy_tpu.channels.p2p import SlotPeer

        roots = []
        for name in ("a", "b"):
            root = tmp_path / name
            root.mkdir()
            (root / "data.bin").write_bytes(payload)
            roots.append(root)
        srv_a = SlotServer(str(roots[0]))
        srv_b = SlotServer(str(roots[1]))
        digest = fnv1a_file(str(roots[0] / "data.bin"))
        peer_a = SlotPeer("127.0.0.1", srv_a.port, "data.bin", digest)
        peer_b = SlotPeer("127.0.0.1", srv_b.port, "data.bin", digest)
        return srv_a, srv_b, peer_a, peer_b

    def test_peer_killed_mid_stream_second_peer_resumes(self, tmp_path):
        import os as _os

        from lzy_tpu.channels.p2p import fetch_via_peers

        payload = _os.urandom(2 * (1 << 20) + 999)
        srv_a, srv_b, peer_a, peer_b = self._two_peers(tmp_path, payload)
        dest = tmp_path / "out.bin"
        try:
            # the stream from A dies mid-file...
            n1 = pull("127.0.0.1", srv_a.port, "data.bin", str(dest),
                      max_bytes=1 << 20)
            assert 0 < n1 < len(payload)
            srv_a.stop()                       # ...and A is gone for good
            # A is tried first (fails fast: connection refused), B resumes
            # from the partial offset and the FNV check passes
            assert fetch_via_peers([peer_a, peer_b], str(dest))
            assert dest.read_bytes() == payload
            assert fnv1a_file(str(dest)) == peer_b.fnv1a
        finally:
            srv_a.stop()
            srv_b.stop()

    def test_mismatched_resume_is_discarded_by_the_fnv_check(self,
                                                             tmp_path):
        """A second peer serving DIFFERENT bytes under the same name must
        not be able to splice a franken-file past the integrity check:
        the fetch fails and the corrupt output is deleted."""
        import dataclasses as _dc
        import os as _os

        from lzy_tpu.channels.p2p import fetch_via_peers

        payload = _os.urandom(1 << 20)
        srv_a, srv_b, peer_a, peer_b = self._two_peers(tmp_path, payload)
        # corrupt B's copy (same size, different tail bytes)
        evil = payload[: (1 << 19)] + _os.urandom(len(payload) - (1 << 19))
        (tmp_path / "b" / "data.bin").write_bytes(evil)
        dest = tmp_path / "out.bin"
        try:
            n1 = pull("127.0.0.1", srv_a.port, "data.bin", str(dest),
                      max_bytes=1 << 19)
            assert 0 < n1 < len(payload)
            srv_a.stop()
            # B resumes from A's partial — the splice fails the FNV gate
            # (peer_b still advertises the ORIGINAL digest)
            peer_b = _dc.replace(peer_b, fnv1a=peer_a.fnv1a)
            assert not fetch_via_peers([peer_a, peer_b], str(dest))
            assert not dest.exists(), "corrupt splice left behind"
        finally:
            srv_a.stop()
            srv_b.stop()


def test_concurrent_pulls(served_file):
    import threading

    srv, payload, tmp = served_file
    errors = []

    def one(i):
        try:
            dest = tmp / f"c{i}.bin"
            pull("127.0.0.1", srv.port, "data.bin", str(dest))
            assert dest.read_bytes() == payload
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
