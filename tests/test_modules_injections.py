"""Module-sync and integration tests."""

import subprocess
import sys
import textwrap

import pytest

from lzy_tpu.env.modules import package_module, unpack_modules, upload_local_modules
from lzy_tpu.injections import extend, remote_fit
from lzy_tpu.storage import MemStorageClient


class TestModuleSync:
    def test_package_and_unpack_package_dir(self, tmp_path):
        pkg = tmp_path / "mymod"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("VALUE = 41\n")
        (pkg / "helper.py").write_text("def f():\n    return 1\n")
        (pkg / "__pycache__").mkdir()
        (pkg / "__pycache__" / "junk.pyc").write_bytes(b"x")

        client = MemStorageClient()
        uris = upload_local_modules([str(pkg)], client, "mem://bucket")
        assert len(uris) == 1
        # content addressing: same content → same uri, no second upload
        assert upload_local_modules([str(pkg)], client, "mem://bucket") == uris

        dest = tmp_path / "worker_site"
        unpack_modules(uris, client, str(dest))
        assert (dest / "mymod" / "__init__.py").read_text() == "VALUE = 41\n"
        assert not (dest / "mymod" / "__pycache__").exists()

    def test_changed_content_changes_uri(self, tmp_path):
        mod = tmp_path / "single.py"
        mod.write_text("A = 1\n")
        client = MemStorageClient()
        (uri1,) = upload_local_modules([str(mod)], client, "mem://bucket")
        mod.write_text("A = 2\n")
        (uri2,) = upload_local_modules([str(mod)], client, "mem://bucket")
        assert uri1 != uri2

    def test_isolated_worker_imports_synced_module(self, tmp_path):
        """End-to-end in a separate interpreter: pack here, unpack + import
        there (what a real remote worker does)."""
        pkg = tmp_path / "shipped"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("ANSWER = 42\n")
        data, _ = package_module(pkg)
        archive = tmp_path / "shipped.zip"
        archive.write_bytes(data)
        script = textwrap.dedent(f"""
            import sys, zipfile
            dest = r"{tmp_path}/site"
            zipfile.ZipFile(r"{archive}").extractall(dest)
            sys.path.insert(0, dest)
            import shipped
            print(shipped.ANSWER)
        """)
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=60)
        assert out.stdout.strip() == "42"


class FakeEstimator:
    def __init__(self):
        self.fitted_on = None

    def fit(self, X, y):  # noqa: N803
        self.fitted_on = (list(X), list(y))
        return self


class TestInjections:
    def test_remote_fit_round_trips_estimator(self, tmp_path):
        from lzy_tpu import Lzy
        from lzy_tpu.storage import DefaultStorageRegistry, StorageConfig

        reg = DefaultStorageRegistry()
        reg.register_storage("default",
                             StorageConfig(uri=f"file://{tmp_path}/s"),
                             default=True)
        lzy = Lzy(storage_registry=reg)
        fitted = remote_fit(FakeEstimator(), [1, 2], [3, 4], lzy=lzy)
        assert fitted.fitted_on == ([1, 2], [3, 4])

    def test_extend_attaches_method(self):
        class Plain:
            pass

        @extend(Plain)
        def shout(self):
            return "hi"

        assert Plain().shout() == "hi"

    def test_catboost_injection_gated(self):
        from lzy_tpu.injections.catboost_inject import inject_catboost

        try:
            import catboost  # type: ignore # noqa: F401

            pytest.skip("catboost installed; gate test not applicable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="catboost"):
            inject_catboost()
