"""In-process ``azure.storage.blob`` stand-in for executing ``storage/azure.py``.

Mirror of ``fake_boto3``: the image deliberately ships without the azure
SDK, so the Azure client used to get only import-gated coverage — its
object ops, ranged reads, block-blob multipart and retry paths never ran
(VERDICT component 16, the last "partial"). This module is the missing
server: an in-memory blob service behind the exact SDK slice
``AzureStorageClient`` calls, installed into ``sys.modules`` as
``azure``/``azure.storage``/``azure.storage.blob`` for one test so the
real code path — lazy import included — executes unchanged.

Fault injection: ``FakeBlobService.fail_next[op]`` holds a countdown of
calls of ``op`` (e.g. ``"stage_block"``) to fail with a retryable error,
driving the transfer engine's per-part retry and the
nothing-committed-on-failure guarantee (Azure has no abort call;
uncommitted blocks are service-side garbage, so "aborted" means "the
blob never appeared").
"""

from __future__ import annotations

import sys
import threading
import types
from typing import Dict, List, Tuple


class FakeAzureError(Exception):
    """Stands in for azure.core exceptions (the client code does not
    catch SDK-specific types, so any exception type exercises the same
    paths)."""

    def __init__(self, op: str):
        super().__init__(f"fake azure failure in {op}")


class _DownloadStream:
    def __init__(self, data: bytes):
        self._data = data
        self.size = len(data)

    def chunks(self):
        # two chunks exercise the read loop, not just one pass
        mid = (len(self._data) + 1) // 2
        for part in (self._data[:mid], self._data[mid:]):
            if part:
                yield part

    def readall(self) -> bytes:
        return self._data


class FakeBlobService:
    """The service-level state every blob/container client shares."""

    def __init__(self):
        self.account_name = "fakeaccount"
        self.credential = types.SimpleNamespace(account_key="fake-key")
        self._blobs: Dict[Tuple[str, str], bytes] = {}
        # (container, name) -> {block_id: data}; uncommitted staging area
        self._staged: Dict[Tuple[str, str], Dict[str, bytes]] = {}
        self._lock = threading.RLock()
        self.fail_next: Dict[str, int] = {}    # op -> remaining failures
        self.calls: Dict[str, int] = {}        # op -> total invocations

    def _enter(self, op: str) -> None:
        with self._lock:
            self.calls[op] = self.calls.get(op, 0) + 1
            if self.fail_next.get(op, 0) > 0:
                self.fail_next[op] -= 1
                raise FakeAzureError(op)

    def dangling_blocks(self) -> int:
        """Uncommitted staged blocks across all blobs (the Azure analog
        of a dangling multipart upload — the service GCs them, but a
        failed upload must never have committed)."""
        with self._lock:
            return sum(len(v) for v in self._staged.values())


class FakeBlobClient:
    def __init__(self, svc: FakeBlobService, container: str, name: str):
        self._svc = svc
        self._key = (container, name)
        self.url = f"https://{svc.account_name}.blob/{container}/{name}"

    # -- plain object ops ----------------------------------------------------

    def upload_blob(self, data, overwrite: bool = False):
        self._svc._enter("upload_blob")
        if hasattr(data, "read"):
            data = data.read()
        with self._svc._lock:
            if not overwrite and self._key in self._svc._blobs:
                raise FakeAzureError("upload_blob: exists")
            self._svc._blobs[self._key] = bytes(data)

    def download_blob(self, offset=None, length=None) -> _DownloadStream:
        self._svc._enter("download_blob")
        data = self._require()
        if offset is not None:
            data = data[offset:] if length is None \
                else data[offset:offset + length]
        return _DownloadStream(data)

    def exists(self) -> bool:
        self._svc._enter("exists")
        with self._svc._lock:
            return self._key in self._svc._blobs

    def get_blob_properties(self):
        self._svc._enter("get_blob_properties")
        return types.SimpleNamespace(size=len(self._require()))

    def delete_blob(self) -> None:
        self._svc._enter("delete_blob")
        with self._svc._lock:
            self._svc._blobs.pop(self._key, None)

    # -- block-blob multipart ------------------------------------------------

    def stage_block(self, block_id: str, data) -> None:
        self._svc._enter("stage_block")
        with self._svc._lock:
            self._svc._staged.setdefault(self._key, {})[block_id] = \
                bytes(data)

    def commit_block_list(self, blocks: List) -> None:
        self._svc._enter("commit_block_list")
        with self._svc._lock:
            staged = self._svc._staged.pop(self._key, {})
            ids = [b.id for b in blocks]
            missing = [bid for bid in ids if bid not in staged]
            assert not missing, f"committing unstaged blocks: {missing}"
            self._svc._blobs[self._key] = b"".join(
                staged[bid] for bid in ids)

    def _require(self) -> bytes:
        with self._svc._lock:
            try:
                return self._svc._blobs[self._key]
            except KeyError:
                raise FakeAzureError("blob not found") from None


class FakeContainerClient:
    def __init__(self, svc: FakeBlobService, container: str):
        self._svc = svc
        self._container = container

    def list_blobs(self, name_starts_with: str = ""):
        self._svc._enter("list_blobs")
        with self._svc._lock:
            names = sorted(
                name for (c, name) in self._svc._blobs
                if c == self._container and name.startswith(name_starts_with))
        return [types.SimpleNamespace(name=n) for n in names]


class FakeBlobServiceClient:
    """Class surface ``AzureStorageClient`` constructs through."""

    # the one shared service instance per install() (tests reach it via
    # the return value of install)
    _service: FakeBlobService = None

    def __init__(self, account_url=None, credential=None):
        self._svc = type(self)._service
        self.account_name = self._svc.account_name
        # SAS-credentialed clients have no account key to sign with
        self.credential = self._svc.credential if credential is None \
            else types.SimpleNamespace(sas=credential)

    @classmethod
    def from_connection_string(cls, conn_str: str):
        assert conn_str, "connection string must be non-empty"
        return cls()

    def get_blob_client(self, container: str, blob: str) -> FakeBlobClient:
        return FakeBlobClient(self._svc, container, blob)

    def get_container_client(self, container: str) -> FakeContainerClient:
        return FakeContainerClient(self._svc, container)


class BlobBlock:
    def __init__(self, block_id: str):
        self.id = block_id


class BlobSasPermissions:
    def __init__(self, read: bool = False):
        self.read = read


def generate_blob_sas(*, account_name, container_name, blob_name,
                      account_key, permission, expiry):
    assert account_key, "signing needs the account key"
    return (f"sv=fake&sr=b&sig=deadbeef&sp={'r' if permission.read else ''}"
            f"&se={expiry.isoformat()}")


def install(monkeypatch) -> FakeBlobService:
    """Register fake ``azure.storage.blob`` modules for one test (undone
    automatically with the monkeypatch fixture, so the absence contract
    checked by test_image_contract is untouched elsewhere)."""
    service = FakeBlobService()
    FakeBlobServiceClient._service = service

    blob_mod = types.ModuleType("azure.storage.blob")
    blob_mod.BlobServiceClient = FakeBlobServiceClient
    blob_mod.BlobBlock = BlobBlock
    blob_mod.BlobSasPermissions = BlobSasPermissions
    blob_mod.generate_blob_sas = generate_blob_sas

    storage_mod = types.ModuleType("azure.storage")
    storage_mod.blob = blob_mod
    azure_mod = types.ModuleType("azure")
    azure_mod.storage = storage_mod

    monkeypatch.setitem(sys.modules, "azure", azure_mod)
    monkeypatch.setitem(sys.modules, "azure.storage", storage_mod)
    monkeypatch.setitem(sys.modules, "azure.storage.blob", blob_mod)
    return service
