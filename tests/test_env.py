"""Env-system tests (reference tier: ``pylzy/tests/env``)."""

import pytest

from lzy_tpu.env import (
    Any,
    AutoPythonEnv,
    DockerContainer,
    LzyEnvironment,
    ManualPythonEnv,
    NoPoolError,
    Provisioning,
    TpuProvisioning,
    tpu_requirement,
)
from lzy_tpu.env.shortcuts import env_vars, provisioning, tpu
from lzy_tpu.types import TpuPoolSpec, VmSpec

POOLS = [
    VmSpec(label="s", cpu_count=4, ram_gb=32),
    VmSpec(label="m", cpu_count=16, ram_gb=64),
    TpuPoolSpec(label="tpu-v5e-8", tpu_type="v5e", topology="2x4"),
    TpuPoolSpec(label="tpu-v5e-16", tpu_type="v5e", topology="4x4"),
    TpuPoolSpec(label="tpu-v5e-64", tpu_type="v5e", topology="8x8"),
    TpuPoolSpec(label="tpu-v5p-8", tpu_type="v5p", topology="2x2x2"),
]


class TestProvisioning:
    def test_default_picks_smallest_cpu_pool(self):
        assert Provisioning().resolve_pool(POOLS).label == "s"

    def test_cpu_requirements_filter(self):
        assert Provisioning(cpu_count=8).resolve_pool(POOLS).label == "m"

    def test_no_pool_error_lists_pools(self):
        with pytest.raises(NoPoolError, match="tpu-v5e-16"):
            Provisioning(cpu_count=64).resolve_pool(POOLS)

    def test_cpu_provisioning_never_claims_tpu(self):
        pool = Provisioning(cpu_count=Any, ram_gb=Any).resolve_pool(POOLS)
        assert isinstance(pool, VmSpec)


class TestTpuProvisioning:
    def test_min_chips_picks_smallest_adequate_slice(self):
        assert TpuProvisioning(tpu_type="v5e", min_chips=12).resolve_pool(POOLS).label == "tpu-v5e-16"

    def test_exact_topology(self):
        assert TpuProvisioning(tpu_type="v5e", tpu_topology="8x8").resolve_pool(POOLS).label == "tpu-v5e-64"

    def test_any_type_matches_all_generations(self):
        pool = TpuProvisioning(tpu_type=Any, min_chips=8).resolve_pool(POOLS)
        assert pool.chips == 8

    def test_gang_size(self):
        pool = TpuProvisioning(tpu_type="v5e", min_chips=64).resolve_pool(POOLS)
        assert pool.hosts == 8  # v5e has 8 chips/host

    def test_shorthand_parsing(self):
        req = tpu_requirement("v5e-16")
        assert req.tpu_type == "v5e" and req.min_chips == 16
        req = tpu_requirement("v5p:2x2x2")
        assert req.tpu_topology == "2x2x2"
        with pytest.raises(ValueError):
            tpu_requirement("16")
        with pytest.raises(ValueError):
            tpu_requirement("v5e:4yy4")


class TestEnvironmentMerge:
    def test_env_vars_merge_rightmost_wins(self):
        merged = env_vars(A="1", B="1").combine(env_vars(B="2", C="2"))
        assert merged.env_vars == {"A": "1", "B": "2", "C": "2"}

    def test_provisioning_fieldwise_merge(self):
        base = provisioning(cpu_count=8)
        call = provisioning(ram_gb=64)
        merged = base.combine(call)
        assert merged.provisioning.cpu_count == 8
        assert merged.provisioning.ram_gb == 64

    def test_kind_switch_replaces(self):
        base = provisioning(cpu_count=8)
        call = tpu("v5e-16")
        merged = base.combine(call)
        assert isinstance(merged.provisioning, TpuProvisioning)
        assert merged.provisioning.cpu_count is None  # replaced, not merged

    def test_three_level_merge_order(self):
        lzy = env_vars(X="lzy").with_container(DockerContainer(image="base"))
        wf = env_vars(X="wf")
        call = LzyEnvironment()
        final = lzy.combine(wf).combine(call)
        assert final.env_vars["X"] == "wf"
        assert final.container.image == "base"


class TestPythonEnv:
    def test_auto_captures_interpreter_and_jax(self):
        spec = AutoPythonEnv().spec()
        assert spec.python_version.startswith("3.")
        names = [n.lower() for n, _ in spec.packages]
        assert "jax" in names  # imported by conftest

    def test_manual_conda_yaml(self):
        spec = ManualPythonEnv(
            python_version="3.12", packages={"jax": "0.9.0", "flax": "0.12.0"}
        ).spec()
        yaml = spec.to_conda_yaml()
        assert "python==3.12" in yaml
        assert "  - jax==0.9.0" in yaml
