"""Streaming inference delivery (``serving/streams`` + the
``InferStream``/``InferStreamPoll``/``InferCancel`` wire surface).

What this file pins:

- **frames are the fence**: position-tagged long-poll frames reproduce
  the ``generate()`` oracle byte-identically, and re-polling any
  position re-reads the identical continuation (the resume token);
- **robustness is the headline**: a client that disconnects while
  QUEUED is reaped in place (no slot ever spent), a slot-resident one
  is evicted within one decode round with KV blocks released and pool
  invariants clean, slow consumers are shed at the bounded buffer, and
  ``InferCancel`` lands in every phase (queued / prefill / decode /
  mid-failover) on dense, paged and disagg planes;
- **chaos**: fixed-seed faults at the new ``rpc.stream`` (frame
  drop / connection death) and ``stream.consumer`` (dead client)
  points, a replica death mid-stream resuming byte-identically through
  the gateway fence, and an LZY_SLOW streaming soak with auditors.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.chaos.faults import CHAOS, DELAY, ERROR, FaultPlan
from lzy_tpu.chaos.invariants import FenceAuditor, audit_engine
from lzy_tpu.gateway import (
    GatewayService, PrefixAffinityRouter, ReplicaFleet)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import InferenceEngine, PagedInferenceEngine
from lzy_tpu.serving.streams import (
    CANCELS, ConsumerGone, RESUMES, SHED_SLOW, StreamSessionManager)
from lzy_tpu.service.inference import InferenceService

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    CHAOS.disarm()


def _oracle_tokens(cfg, params, prompt_ids, n):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _service(cfg, params, *, paged=False, slots=2, **engine_kw):
    if paged:
        engine = PagedInferenceEngine(cfg, params, slots=slots,
                                      page_size=PAGE, **engine_kw)
    else:
        engine = InferenceEngine(cfg, params, slots=slots, **engine_kw)
    engine.start()
    return InferenceService(engine, model_name="tiny"), engine


def _drain_stream(streams, rid, *, start=0, wait_s=2.0, budget_s=60.0):
    """Poll a session to completion; returns (tokens, final_frame)."""
    pos, toks = start, []
    deadline = time.monotonic() + budget_s
    while True:
        frame = streams.poll(rid, pos, wait_s=wait_s)
        toks.extend(frame["tokens"])
        pos += len(frame["tokens"])
        if frame["done"]:
            return toks, frame
        assert time.monotonic() < deadline, "stream never finished"


def _counter(counter, **labels):
    from lzy_tpu.utils.metrics import _label_key

    return counter._values.get(_label_key(labels), 0.0)


def _make_gateway(cfg, params, *, replicas=2, slots=2, **engine_kw):
    fleet = ReplicaFleet(
        lambda: PagedInferenceEngine(cfg, params, slots=slots,
                                     page_size=PAGE, **engine_kw))
    gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                        model_name="tiny")
    for _ in range(replicas):
        fleet.add_replica()
    return gw, fleet


# -- channel-level ack / lag plumbing -----------------------------------------

class TestChannelAck:
    def test_ack_is_monotonic_and_bounded(self):
        from lzy_tpu.channels.token_stream import TokenStreamChannel

        ch = TokenStreamChannel()
        ch.publish(0, [1, 2, 3, 4])
        assert ch.consumer_lag == 4
        ch.ack(3)
        assert ch.acked == 3 and ch.consumer_lag == 1
        ch.ack(1)                      # a resume re-read never rewinds
        assert ch.acked == 3
        ch.ack(99)                     # cannot ack past the fence
        assert ch.acked == 4

    def test_wait_past_returns_keepalive_not_raises(self):
        from lzy_tpu.channels.token_stream import TokenStreamChannel

        ch = TokenStreamChannel()
        out = ch.wait_past(0, timeout_s=0.02)
        assert out["tokens"] == [] and not out["closed"]
        ch.publish(0, [7])
        out = ch.wait_past(0, timeout_s=1.0)
        assert out["tokens"] == [7] and not out["closed"]
        ch.close("ok")
        out = ch.wait_past(1, timeout_s=0.1)
        assert out["closed"] and out["status"] == "ok"

    def test_read_and_iter_record_consumer_progress(self):
        from lzy_tpu.channels.token_stream import TokenStreamChannel

        ch = TokenStreamChannel()
        ch.publish(0, [1, 2, 3])
        assert ch.read(0, timeout_s=1.0) == [1, 2, 3]
        assert ch.acked == 3


# -- frames, resume tokens, keepalives ----------------------------------------

class TestStreamFrames:
    def test_frames_reproduce_the_oracle(self, tiny_model):
        cfg, params = tiny_model
        svc, engine = _service(cfg, params)
        try:
            opened = svc.streams.open([5, 9, 3], max_new_tokens=10,
                                      greedy=True)
            toks, frame = _drain_stream(svc.streams,
                                        opened["request_id"])
            assert toks == _oracle_tokens(cfg, params, [5, 9, 3], 10)
            assert frame["status"] == "ok"
            # the done frame carries the unary reply's route metadata
            assert frame["reply"]["model"] == "tiny"
            assert "tokens" not in frame["reply"]
        finally:
            svc.close()

    def test_repoll_any_position_is_byte_identical(self, tiny_model):
        """The resume token in action: after the stream completes, every
        (request_id, position) re-read returns exactly the suffix an
        uninterrupted consumer saw — a client that lost its connection
        (or its reply) resumes with no splice and no gap."""
        cfg, params = tiny_model
        svc, _ = _service(cfg, params)
        try:
            opened = svc.streams.open([5, 9, 3], max_new_tokens=8,
                                      greedy=True)
            rid = opened["request_id"]
            toks, _ = _drain_stream(svc.streams, rid)
            before = _counter(RESUMES)
            for pos in (0, 3, len(toks)):
                frame = svc.streams.poll(rid, pos, wait_s=1.0)
                assert frame["tokens"] == toks[pos:]
                assert frame["done"]
            assert _counter(RESUMES) > before
        finally:
            svc.close()

    def test_poll_past_the_fence_is_rejected(self, tiny_model):
        cfg, params = tiny_model
        svc, _ = _service(cfg, params)
        try:
            opened = svc.streams.open([5, 9, 3], max_new_tokens=4,
                                      greedy=True)
            rid = opened["request_id"]
            _drain_stream(svc.streams, rid)
            with pytest.raises(ValueError, match="past the fence"):
                svc.streams.poll(rid, 999, wait_s=0.1)
        finally:
            svc.close()

    def test_unknown_stream_is_not_found(self, tiny_model):
        cfg, params = tiny_model
        svc, _ = _service(cfg, params)
        try:
            with pytest.raises(KeyError):
                svc.streams.poll("stream-nope", 0, wait_s=0.1)
        finally:
            svc.close()

    def test_keepalive_carries_the_queued_phase(self, tiny_model):
        """A keepalive frame distinguishes a stalled engine from a
        request that simply has not started: while slot-starved, the
        frame says ``queued``; once decoding it says ``decode``."""
        cfg, params = tiny_model
        svc, engine = _service(cfg, params, slots=1)
        try:
            first = svc.streams.open([5, 9], max_new_tokens=120,
                                     greedy=True)
            # wait until the first request actually holds the slot
            deadline = time.monotonic() + 30
            while not any(r is not None for r in engine._active):
                assert time.monotonic() < deadline
                time.sleep(0.005)
            second = svc.streams.open([6, 1], max_new_tokens=4,
                                      greedy=True)
            frame = svc.streams.poll(second["request_id"], 0,
                                     wait_s=0.05)
            assert frame["keepalive"] and frame["phase"] == "queued"
            svc.streams.cancel(first["request_id"])
            toks, done = _drain_stream(svc.streams,
                                       second["request_id"])
            assert done["status"] == "ok" and len(toks) == 4
        finally:
            svc.close()

    def test_fast_admission_errors_surface_on_open(self, tiny_model):
        from lzy_tpu.serving.scheduler import PromptTooLong

        cfg, params = tiny_model
        svc, _ = _service(cfg, params)
        try:
            with pytest.raises(PromptTooLong):
                svc.streams.open([5, 9], max_new_tokens=100000)
            assert svc.streams.sessions() == []     # nothing leaked
        finally:
            svc.close()

    def test_session_cap_sheds_opens(self, tiny_model):
        from lzy_tpu.rpc.core import Unavailable

        cfg, params = tiny_model
        svc, _ = _service(cfg, params, slots=1)
        svc.streams.max_sessions = 1
        try:
            svc.streams.open([5, 9], max_new_tokens=120, greedy=True)
            with pytest.raises(Unavailable, match="retry_after_s"):
                svc.streams.open([6, 1], max_new_tokens=4)
        finally:
            svc.close()


# -- client-disconnect reaping ------------------------------------------------

class TestClientDisconnect:
    def test_queued_dead_client_never_occupies_a_slot(self, tiny_model):
        """The satellite fix: a request whose client disconnected while
        still QUEUED is reaped in place by ``RequestQueue.reap_dead``'s
        liveness check — previously only deadline reaping covered it,
        so a dead client's request would eventually burn a slot."""
        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=1)   # synchronous
        occupant = engine.submit([5, 9], max_new_tokens=60, greedy=True)
        ghost = engine.submit([6, 1], max_new_tokens=60, greedy=True,
                              tenant="ghost", liveness=lambda: False)
        for _ in range(8):
            engine.step()
            assert engine._active[0] is not ghost, \
                "dead client occupied a slot"
        assert ghost.done and ghost.status == "cancelled"
        assert "disconnected" in ghost.error
        assert not occupant.done or occupant.status == "ok"
        row = engine.stats_by_tenant()["ghost"]
        assert row["requests_cancelled"] == 1    # counted exactly once
        occupant.cancel()
        engine.close()

    def test_slot_resident_disconnect_evicted_within_one_round(
            self, tiny_model):
        """Mid-decode disconnect: the next scheduling round frees the
        slot and every KV block; pool invariants audit clean."""
        cfg, params = tiny_model
        engine = PagedInferenceEngine(cfg, params, slots=2,
                                      page_size=PAGE)
        alive = {"v": True}
        req = engine.submit([5, 9, 3], max_new_tokens=120, greedy=True,
                            tenant="flaky", liveness=lambda: alive["v"])
        rounds = 0
        while len(req.tokens) < 3:
            engine.step()
            rounds += 1
            assert rounds < 300
        slot = engine._active.index(req)
        assert engine._slot_blocks[slot], "expected resident blocks"
        alive["v"] = False
        engine.step()                       # ONE round reaps it
        assert req.done and req.status == "cancelled"
        assert engine._active[slot] is None
        assert engine._slot_blocks[slot] == []
        audit_engine(engine)
        assert engine.stats_by_tenant()["flaky"]["requests_cancelled"] \
            == 1
        engine.close()

    def test_stale_stream_session_reaps_by_poll_cadence(self, tiny_model):
        """End to end: a stream nobody polls counts as a disconnected
        client after ``liveness_timeout_s`` and the engine evicts it."""
        cfg, params = tiny_model
        svc, engine = _service(cfg, params)
        svc.streams.liveness_timeout_s = 0.2
        try:
            opened = svc.streams.open([5, 9], max_new_tokens=200,
                                      greedy=True)
            sess = svc.streams._get(opened["request_id"])
            deadline = time.monotonic() + 30
            while not sess.channel.closed:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            assert sess.channel.status == "cancelled"
            assert "disconnected" in (sess.dead_reason or "")
            time.sleep(0.1)
            assert all(r is None for r in engine._active)
        finally:
            svc.close()

    def test_parked_poll_counts_as_liveness(self):
        """A poll BLOCKED in the long-poll wait is a live connection:
        wait_s may exceed the liveness window without the actively
        waiting client's request being reaped as disconnected. Driven
        against a fake service whose generate probes liveness every
        round (exactly the engine reaper's cadence) while producing
        nothing for a while — a long prefill."""

        class _SlowPrefill:
            model_name = "fake"

            def generate(self, prompt, stream=None, liveness=None,
                         **kw):
                deadline = time.monotonic() + 0.9
                while time.monotonic() < deadline:
                    if not liveness():
                        stream.close("cancelled")
                        return {"status": "cancelled", "tokens": []}
                    time.sleep(0.01)
                stream.publish(0, [1, 2])
                stream.close("ok")
                return {"status": "ok", "tokens": [1, 2],
                        "request_id": "r-1"}

        mgr = StreamSessionManager(_SlowPrefill(),
                                   liveness_timeout_s=0.25)
        opened = mgr.open([1], max_new_tokens=2, greedy=True)
        rid = opened["request_id"]
        # park 0.6s — past the 0.25s liveness window — while nothing
        # is produced: the parked poll must keep the request alive
        frame = mgr.poll(rid, 0, wait_s=0.6)
        assert frame["keepalive"], frame
        sess = mgr._get(rid)
        assert sess.dead_reason is None
        toks, done = _drain_stream(mgr, rid, wait_s=0.6)
        assert done["status"] == "ok" and toks == [1, 2]

    def test_broken_liveness_probe_never_cancels(self, tiny_model):
        """A RAISING probe is detached and treated as alive — a bug in
        the streaming layer must not kill a healthy request (the
        deadline still bounds it)."""
        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=1)

        def boom():
            raise RuntimeError("probe bug")

        req = engine.submit([5, 9], max_new_tokens=4, greedy=True,
                            liveness=boom)
        for _ in range(60):
            if req.done:
                break
            engine.step()
        assert req.done and req.status == "ok"
        assert req.liveness is None          # detached after one raise
        engine.close()


# -- bounded buffers: slow-consumer shed --------------------------------------

class TestSlowConsumerShed:
    def test_stalled_consumer_is_shed_not_buffered(self, tiny_model):
        cfg, params = tiny_model
        svc, engine = _service(cfg, params)
        svc.streams.ack_window = 4
        # short grace: with a warm XLA compilation cache the tiny model
        # decodes ~1ms/token, and a 0.2s grace let the 200-token request
        # FINISH before the stall window elapsed (the shed never fired
        # and the test flaked fast-machine-dependently); 0.05s still
        # spans dozens of decode rounds past the ack window
        svc.streams.stall_grace_s = 0.05
        try:
            before = _counter(SHED_SLOW)
            opened = svc.streams.open([5, 9], max_new_tokens=200,
                                      greedy=True)
            rid = opened["request_id"]
            # one poll keeps the client "connected" but acks nothing
            # beyond position 0 — the producer runs ahead of the window
            svc.streams.poll(rid, 0, wait_s=0.5)
            sess = svc.streams._get(rid)
            deadline = time.monotonic() + 30
            while not sess.channel.closed:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            assert _counter(SHED_SLOW) == before + 1
            assert "slow consumer" in (sess.dead_reason or "")
            # the shed frees the slot like any cancel
            time.sleep(0.1)
            assert all(r is None for r in engine._active)
            # the terminal frame names the shed for the (slow) client
            frame = svc.streams.poll(rid, sess.channel.position,
                                     wait_s=1.0)
            assert frame["done"] and frame["status"] == "cancelled"
            assert "slow consumer" in (frame["error"] or "")
        finally:
            svc.close()


# -- cancellation in every phase ----------------------------------------------

class TestCancelPhases:
    def _deltas(self):
        return {phase: _counter(CANCELS, phase=phase)
                for phase in ("queued", "prefill", "decode", "failover")}

    def test_cancel_queued_paged(self, tiny_model):
        cfg, params = tiny_model
        engine = PagedInferenceEngine(cfg, params, slots=1,
                                      page_size=PAGE)
        before = self._deltas()
        occupant = engine.submit([5, 9], max_new_tokens=60, greedy=True)
        victim = engine.submit([6, 1], max_new_tokens=60, greedy=True,
                               liveness=lambda: True)
        engine.step()
        victim.cancel()
        engine.step()
        assert victim.done and victim.status == "cancelled"
        audit_engine(engine)
        after = self._deltas()
        assert after["queued"] == before["queued"] + 1
        assert after["decode"] == before["decode"]
        occupant.cancel()
        engine.close()

    def test_cancel_mid_prefill_releases_staged_blocks(self, tiny_model):
        """Chunked prefill holds a staged job across rounds; a cancel
        mid-job releases every staged block (pool conservation audited)
        and counts under the ``prefill`` phase."""
        cfg, params = tiny_model
        engine = PagedInferenceEngine(cfg, params, slots=1,
                                      page_size=PAGE,
                                      prefill_chunk=PAGE,
                                      prefill_budget=PAGE)
        before = self._deltas()
        prompt = [(i * 7) % 50 + 1 for i in range(6 * PAGE)]
        req = engine.submit(prompt, max_new_tokens=8, greedy=True,
                            liveness=lambda: True)
        engine.step()                     # stages + first budget round
        assert engine._prefill_jobs, "job should be staged"
        req.cancel()
        engine.step()
        assert req.done and req.status == "cancelled"
        assert not engine._prefill_jobs
        audit_engine(engine)
        after = self._deltas()
        assert after["prefill"] == before["prefill"] + 1
        engine.close()

    def test_cancel_mid_decode_frees_blocks_one_round(self, tiny_model):
        cfg, params = tiny_model
        engine = PagedInferenceEngine(cfg, params, slots=2,
                                      page_size=PAGE)
        before = self._deltas()
        req = engine.submit([5, 9, 3], max_new_tokens=120, greedy=True,
                            liveness=lambda: True)
        while len(req.tokens) < 2:
            engine.step()
        req.cancel()
        engine.step()
        assert req.done and req.status == "cancelled"
        assert all(r is None for r in engine._active)
        audit_engine(engine)
        after = self._deltas()
        assert after["decode"] == before["decode"] + 1
        engine.close()

    def test_cancel_dense_engine_all_phases_clean(self, tiny_model):
        """The dense plane has no pool to audit but the same phase
        accounting; queued + decode cancels both land."""
        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=1)
        before = self._deltas()
        occupant = engine.submit([5, 9], max_new_tokens=60, greedy=True,
                                 liveness=lambda: True)
        queued = engine.submit([6, 1], max_new_tokens=60, greedy=True,
                               liveness=lambda: True)
        while len(occupant.tokens) < 2:
            engine.step()
        queued.cancel()
        occupant.cancel()
        engine.step()
        assert queued.status == "cancelled"
        assert occupant.status == "cancelled"
        after = self._deltas()
        assert after["queued"] == before["queued"] + 1
        assert after["decode"] == before["decode"] + 1
        engine.close()

    def test_cancel_mid_failover_short_circuits(self, tiny_model):
        """InferCancel landing while the gateway is BETWEEN attempts
        (the replica died, the retry has not been submitted): the
        gateway finishes with the cancelled contract — fenced partials
        readable — instead of resubmitting, and the cancel counts under
        the ``failover`` phase."""
        from lzy_tpu.channels.token_stream import TokenStreamChannel

        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=2)
        before = self._deltas()
        alive = {"v": True}
        ch = TokenStreamChannel()
        result = {}

        def run():
            try:
                result["reply"] = gw.generate(
                    [7, 2, 8, 1], max_new_tokens=48, greedy=True,
                    timeout_s=120, stream=ch,
                    liveness=lambda: alive["v"])
            except BaseException as e:  # noqa: BLE001
                result["err"] = e

        t = threading.Thread(target=run)
        t.start()
        try:
            victim = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and victim is None:
                for replica in fleet.replicas():
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim = replica
                        break
                time.sleep(0.005)
            assert victim is not None, "never reached mid-decode"

            def boom():
                raise RuntimeError("replica host on fire")

            # kill the replica FIRST (its loop can no longer reap), then
            # drop the client: the gateway hits the failover path and
            # must not resubmit the corpse
            victim.engine.step = boom
            alive["v"] = False
            t.join(60)
            assert "err" not in result, result.get("err")
            reply = result["reply"]
            assert reply["status"] == "cancelled"
            assert ch.status == "cancelled"
            # fenced partials delivered, never duplicated
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 48)
            assert reply["tokens"] == oracle[:len(reply["tokens"])]
            assert ch.tokens() == reply["tokens"]
            after = self._deltas()
            assert after["failover"] == before["failover"] + 1
        finally:
            gw.close()

    def test_cancel_on_disagg_plane_audits_clean(self, tiny_model):
        """Mid-stream cancel through the two-pool plane: decode slot
        and KV blocks released, both pools' invariants clean, shed
        counters unmoved (a cancel is not a shed)."""
        from lzy_tpu.gateway.disagg import DisaggGatewayService
        from lzy_tpu.serving import DecodeEngine, PrefillEngine

        cfg, params = tiny_model
        decode_fleet = ReplicaFleet(
            lambda: DecodeEngine(cfg, params, slots=2, page_size=PAGE),
            replica_prefix="decode")
        prefill_fleet = ReplicaFleet(
            lambda: PrefillEngine(cfg, params, slots=2, page_size=PAGE),
            replica_prefix="prefill")
        gw = DisaggGatewayService(
            decode_fleet, prefill_fleet, page_size=PAGE,
            router=PrefixAffinityRouter(PAGE),
            prefill_router=PrefixAffinityRouter(PAGE),
            model_name="tiny")
        decode_fleet.add_replica()
        prefill_fleet.add_replica()
        try:
            opened = gw.streams.open(
                [(i * 3) % 50 + 1 for i in range(2 * PAGE)] + [9],
                max_new_tokens=200, greedy=True)
            rid = opened["request_id"]
            frame = gw.streams.poll(rid, 0, wait_s=5.0)
            pos = len(frame["tokens"])
            assert not frame["done"]
            gw.streams.cancel(rid)
            toks, done = _drain_stream(gw.streams, rid, start=pos)
            assert done["status"] == "cancelled"
            time.sleep(0.2)
            for fleet in (decode_fleet, prefill_fleet):
                for replica in fleet.replicas():
                    assert all(r is None
                               for r in replica.engine._active)
                    audit_engine(replica.engine)
        finally:
            gw.close()


# -- chaos: the new fault points ----------------------------------------------

@pytest.mark.chaos
class TestStreamChaos:
    def test_fixed_seed_rpc_stream_faults_survived(self, tiny_model):
        """Faults at ``rpc.stream`` (frame drop/delay) during a streamed
        generation over the REAL wire: the client's poll retry resumes
        at the fence position and the delivered sequence is
        byte-identical to the oracle."""
        import tempfile

        from lzy_tpu.channels.token_stream import TokenStreamChannel
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2).start()
        tmp = tempfile.mkdtemp()
        cluster = InProcessCluster(
            db_path=f"{tmp}/meta.db", storage_uri=f"file://{tmp}/s",
            worker_mode="process",
            inference_service=InferenceService(engine,
                                               model_name="tiny"))
        plan = CHAOS.arm(FaultPlan(
            20260805, rate=0.4, modes=(ERROR, DELAY),
            points=("rpc.stream",), max_faults=4))
        try:
            client = RpcInferenceClient(cluster.rpc_server.address)
            ch = TokenStreamChannel()
            reply = client.generate([5, 9, 3], max_new_tokens=16,
                                    greedy=True, stream=ch)
            oracle = _oracle_tokens(cfg, params, [5, 9, 3], 16)
            assert reply["tokens"] == oracle
            assert ch.tokens() == oracle and ch.status == "ok"
            assert plan.fired > 0, plan.describe()
            client.close()
        finally:
            CHAOS.disarm()
            cluster.shutdown()

    def test_fixed_seed_consumer_death_reaps_within_round(
            self, tiny_model):
        """``stream.consumer`` error mode is the client dying mid-poll:
        the session flips dead and the engine evicts the request —
        slot free, pool clean — within one decode round."""
        cfg, params = tiny_model
        svc, engine = _service(cfg, params, paged=True)
        plan = CHAOS.arm(FaultPlan(
            7, rate=1.0, modes=(ERROR,), points=("stream.consumer",),
            max_faults=1))
        try:
            opened = svc.streams.open([5, 9], max_new_tokens=200,
                                      greedy=True)
            rid = opened["request_id"]
            with pytest.raises(ConsumerGone):
                svc.streams.poll(rid, 0, wait_s=1.0)
            sess = svc.streams._get(rid)
            deadline = time.monotonic() + 30
            while not sess.channel.closed:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            assert sess.channel.status == "cancelled"
            time.sleep(0.1)
            assert all(r is None for r in engine._active)
            audit_engine(engine)
            assert plan.fired == 1
        finally:
            CHAOS.disarm()
            svc.close()

    def test_replica_death_mid_stream_resumes_byte_identical(
            self, tiny_model):
        """The acceptance headline: kill the serving replica mid-stream
        and the long-poll consumer sees a byte-identical sequence — the
        fence is the wire position, verified by the channel's splice
        gate and the fence auditor."""
        cfg, params = tiny_model
        gw, fleet = _make_gateway(cfg, params, replicas=3)
        gw.fence_auditor = FenceAuditor()
        try:
            opened = gw.streams.open([7, 2, 8, 1], max_new_tokens=24,
                                     greedy=True, timeout_s=120)
            rid = opened["request_id"]
            got = []
            killed = False
            pos = 0
            deadline = time.monotonic() + 90
            while True:
                frame = gw.streams.poll(rid, pos, wait_s=1.0)
                got.extend(frame["tokens"])
                pos += len(frame["tokens"])
                if not killed and len(got) >= 3:
                    # kill whichever replica currently decodes it
                    for replica in fleet.replicas():
                        if any(r is not None
                               for r in replica.engine._active):
                            def boom():
                                raise RuntimeError("host on fire")
                            replica.engine.step = boom
                            killed = True
                            break
                if frame["done"]:
                    break
                assert time.monotonic() < deadline
            assert killed, "request finished before the kill"
            oracle = _oracle_tokens(cfg, params, [7, 2, 8, 1], 24)
            assert got == oracle
            assert frame["status"] == "ok"
            assert frame["resumptions"] == 1
            assert frame["reply"]["failovers"] == 1
            assert gw.fence_auditor.completions_seen >= 1
        finally:
            gw.close()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.skipif(not os.environ.get("LZY_SLOW"),
                    reason="streaming chaos soak: set LZY_SLOW=1")
class TestStreamingSoak:
    def test_streaming_soak_with_fence_auditors(self, tiny_model):
        """LZY_SLOW soak: a batch of streamed generations through the
        gateway with faults armed at rpc.stream + stream.consumer +
        engine.step — every surviving stream byte-identical to the
        oracle, every killed one cleanly cancelled, auditors clean
        after each, and the fleet fully recovered at the end."""
        from tests.conftest import record_tier_run

        from lzy_tpu.gateway import Autoscaler

        cfg, params = tiny_model
        seed = int(os.environ.get("LZY_CHAOS_SEED", "20260806"))
        fleet = ReplicaFleet(
            lambda: PagedInferenceEngine(cfg, params, slots=2,
                                         page_size=PAGE))
        gw = GatewayService(
            fleet, router=PrefixAffinityRouter(PAGE),
            # self-healing floor: a chaos-killed replica is re-leased by
            # the tick, so the soak exercises recovery, not extinction
            autoscaler=Autoscaler(min_replicas=2, max_replicas=3),
            model_name="tiny")
        for _ in range(2):
            fleet.add_replica()
        gw.fence_auditor = FenceAuditor()
        plan = CHAOS.arm(FaultPlan(
            seed, rate=0.1, modes=(ERROR, DELAY),
            points=("rpc.stream", "stream.consumer", "engine.step"),
            max_faults=3))
        ok = cancelled = 0
        try:
            for i in range(12):
                prompt = [7, 2, (i * 5) % 50 + 1]
                n = 10 + (i % 4)
                opened = None
                for _ in range(20):
                    try:
                        opened = gw.streams.open(
                            prompt, max_new_tokens=n, greedy=True,
                            timeout_s=120)
                        break
                    except Exception:  # noqa: BLE001 — shed, retry
                        gw.tick()
                        time.sleep(0.02)
                assert opened is not None, f"request {i} shed forever"
                rid = opened["request_id"]
                got, pos, frame = [], 0, None
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    try:
                        frame = gw.streams.poll(rid, pos, wait_s=1.0)
                    except ConsumerGone:
                        continue     # the server killed us; read tail
                    except ConnectionError:
                        continue     # dropped frame: re-poll the fence
                    got.extend(frame["tokens"])
                    pos += len(frame["tokens"])
                    if frame["done"]:
                        break
                assert frame is not None and frame["done"]
                oracle = _oracle_tokens(cfg, params, prompt, n)
                if frame["status"] == "ok":
                    assert got == oracle, f"request {i} diverged"
                    ok += 1
                else:
                    # cancelled (consumer killed) or error (the whole
                    # fleet was momentarily dead): the delivered prefix
                    # must still be fenced — never spliced, never wrong
                    assert frame["status"] in ("cancelled", "error")
                    assert got == oracle[:len(got)], \
                        f"request {i} spliced"
                    cancelled += 1
                gw.tick()
                for replica in fleet.replicas():
                    audit_engine(replica.engine)
            CHAOS.disarm()
            final = gw.streams.open([7, 2, 63], max_new_tokens=8,
                                    greedy=True)
            got, frame = _drain_stream(gw.streams,
                                       final["request_id"])
            assert got == _oracle_tokens(cfg, params, [7, 2, 63], 8)
            assert ok >= 6, (ok, cancelled)
            record_tier_run("slow:stream_soak",
                            f"seed={seed} ok={ok} "
                            f"cancelled={cancelled} "
                            f"fired={plan.fired}")
        except AssertionError as e:
            pytest.fail(
                f"streaming soak seed {seed} failed: {e}\n--- replay "
                f"---\nLZY_CHAOS_SEED={seed} LZY_SLOW=1 pytest "
                f"tests/test_streaming.py -k soak\n{plan.describe()}")
        finally:
            CHAOS.disarm()
            gw.close()


# -- the wire surface ---------------------------------------------------------

class TestRpcStreamDelivery:
    @pytest.fixture()
    def cluster(self, tiny_model, tmp_path):
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2).start()
        cluster = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            inference_service=InferenceService(engine,
                                               model_name="tiny"))
        cluster._test_engine = engine
        try:
            yield cluster
        finally:
            cluster.shutdown()

    def test_streamed_generate_matches_unary(self, tiny_model, cluster):
        from lzy_tpu.channels.token_stream import TokenStreamChannel
        from lzy_tpu.rpc import RpcInferenceClient

        cfg, params = tiny_model
        client = RpcInferenceClient(cluster.rpc_server.address)
        try:
            ch = TokenStreamChannel()
            reply = client.generate([5, 9, 3], max_new_tokens=12,
                                    greedy=True, stream=ch)
            oracle = _oracle_tokens(cfg, params, [5, 9, 3], 12)
            assert reply["tokens"] == oracle
            assert reply["status"] == "ok" and reply["model"] == "tiny"
            assert ch.tokens() == oracle and ch.status == "ok"
        finally:
            client.close()

    def test_connection_death_resumes_from_position(self, tiny_model,
                                                    cluster):
        """Kill the client's CONNECTION mid-stream: a brand-new client
        resumes from the last consumed position and the concatenation
        is byte-identical to an uninterrupted run."""
        from lzy_tpu.rpc import RpcInferenceClient

        cfg, params = tiny_model
        client = RpcInferenceClient(cluster.rpc_server.address)
        opened = client.stream_open([5, 9, 3], max_new_tokens=12,
                                    greedy=True)
        rid = opened["request_id"]
        frame = client.stream_poll(rid, 0, wait_s=2.0)
        got = list(frame["tokens"])
        client.close()                      # the connection dies
        client2 = RpcInferenceClient(cluster.rpc_server.address)
        try:
            pos = len(got)
            for frame in client2.iter_stream(rid, pos):
                got.extend(frame["tokens"])
            assert got == _oracle_tokens(cfg, params, [5, 9, 3], 12)
        finally:
            client2.close()

    def test_infer_cancel_frees_within_one_round(self, tiny_model,
                                                 cluster):
        from lzy_tpu.rpc import RpcInferenceClient

        client = RpcInferenceClient(cluster.rpc_server.address)
        try:
            opened = client.stream_open([5, 9], max_new_tokens=200,
                                        greedy=True)
            rid = opened["request_id"]
            frame = client.stream_poll(rid, 0, wait_s=2.0)
            client.cancel(rid)
            pos = len(frame["tokens"])
            for frame in _frames(client, rid, pos):
                if frame["done"]:
                    break
            assert frame["status"] == "cancelled"
            deadline = time.monotonic() + 10
            engine = cluster._test_engine
            while any(r is not None for r in engine._active):
                assert time.monotonic() < deadline
                time.sleep(0.02)
        finally:
            client.close()


def _frames(client, rid, pos):
    while True:
        frame = client.stream_poll(rid, pos, wait_s=2.0)
        yield frame
        pos += len(frame["tokens"])
        if frame["done"]:
            return
