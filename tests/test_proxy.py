"""Lazy-proxy tests (reference tier: ``pylzy/tests/proxy``)."""

import numpy as np
import pytest

from lzy_tpu.proxy import (
    get_proxy_entry_id,
    is_lzy_proxy,
    lzy_proxy,
    materialize,
    materialized,
)


def make(value, typ=None, counter=None):
    def fn():
        if counter is not None:
            counter.append(1)
        return value

    return lzy_proxy(fn, "entry-1", typ or type(value))


def test_materialize_on_touch_only_once():
    calls = []
    p = make(41, counter=calls)
    assert not materialized(p)
    assert p + 1 == 42
    assert materialized(p)
    assert p * 2 == 82
    assert len(calls) == 1  # cached after first touch


def test_attribute_and_method_forwarding():
    p = make("hello world")
    assert p.upper() == "HELLO WORLD"
    assert p.split() == ["hello", "world"]
    assert len(p) == 11
    assert "world" in p


def test_isinstance_via_fake_class():
    p = make([1, 2, 3], typ=list)
    assert isinstance(p, list)
    assert p.__class__ is list


def test_isinstance_before_materialization_uses_declared_type():
    touched = []
    p = make({"a": 1}, typ=dict, counter=touched)
    assert isinstance(p, dict)
    assert not touched  # isinstance must not trigger materialization


def test_arithmetic_both_sides():
    p = make(10)
    assert p + 5 == 15
    assert 5 + p == 15
    assert 2 * p == 20
    assert p / 4 == 2.5
    assert 100 - p == 90


def test_comparison_and_hash():
    p = make(7)
    assert p == 7 and p < 8 and p >= 7
    assert hash(p) == hash(7)
    assert {p: "x"}[7] == "x"


def test_container_mutation():
    p = make([1, 2])
    p.append(3)
    p[0] = 0
    assert materialize(p) == [0, 2, 3]
    assert list(reversed(p)) == [3, 2, 0]


def test_numpy_interop():
    p = make(np.arange(4.0))
    out = p + np.ones(4)
    np.testing.assert_array_equal(out, [1.0, 2.0, 3.0, 4.0])
    np.testing.assert_array_equal(np.asarray(p), np.arange(4.0))


def test_proxy_of_proxy_argument():
    a = make(3)
    b = make(4)
    assert a + b == 7


def test_entry_id_and_helpers():
    p = make(1)
    assert is_lzy_proxy(p)
    assert not is_lzy_proxy(1)
    assert get_proxy_entry_id(p) == "entry-1"
    assert materialize(5) == 5


def test_str_repr_format():
    p = make(3.5)
    assert str(p) == "3.5"
    assert repr(p) == "3.5"
    assert f"{p:.1f}" == "3.5"


def test_pickle_materializes():
    import pickle

    p = make({"k": 1})
    assert pickle.loads(pickle.dumps(p)) == {"k": 1}
