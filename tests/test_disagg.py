"""Disaggregated prefill/decode serving (serving/disagg + gateway/disagg).

Acceptance criterion (ISSUE 4): disaggregated greedy and sampled outputs
are bit-identical to the monolithic ``PagedInferenceEngine`` and the
sequential ``generate()`` oracle — including under a prefill-replica kill
mid-transfer, where the request silently re-prefills on the decode side
and NEVER fails. Unit layers underneath: manifest encode/decode, refcount
integrity on the exporting pool while a transfer is in flight, and import
into a nearly-full pool (evict-then-import, never corrupting resident
requests).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.channels.kv_transfer import (
    InMemoryKVTransport, KVBlockExport, KVTransferError, StorageKVTransport,
    build_kv_manifest, fetch_kv_export, parse_kv_manifest, spill_kv_export)
from lzy_tpu.gateway import (
    DisaggGatewayService, PrefixAffinityRouter, ReplicaFleet)
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import (
    DecodeEngine, NoFreeBlocks, PagedInferenceEngine, PrefillEngine,
    export_kv, import_kv)

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drive(eng, *reqs, rounds=300):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        eng.step()
    raise AssertionError("requests did not finish")


def _prefill_export(cfg, params, prompt, **kw):
    """Run one prompt through a synchronous PrefillEngine; returns the
    export its request carries."""
    pf = PrefillEngine(cfg, params, slots=1, page_size=PAGE, **kw)
    req = pf.submit(prompt)
    _drive(pf, req)
    assert req.error is None, req.error
    return req.kv_export


@pytest.fixture(scope="module")
def export16(tiny_model):
    """One shared export of the 2-block prompt ``range(16) + [40]`` —
    engine construction is the expensive part of these tests, and the
    export itself is read-only for every consumer."""
    cfg, params = tiny_model
    return _prefill_export(cfg, params, list(range(16)) + [40])


def _make_disagg(cfg, params, *, prefill=1, decode=2, slots=2,
                 start_engines=True, transport=None, **engine_kw):
    decode_fleet = ReplicaFleet(
        lambda: DecodeEngine(cfg, params, slots=slots, page_size=PAGE,
                             **engine_kw),
        start_engines=start_engines, replica_prefix="decode")
    prefill_fleet = ReplicaFleet(
        lambda: PrefillEngine(cfg, params, slots=slots, page_size=PAGE,
                              **engine_kw),
        start_engines=start_engines, replica_prefix="prefill")
    gw = DisaggGatewayService(
        decode_fleet, prefill_fleet, page_size=PAGE,
        router=PrefixAffinityRouter(PAGE),
        prefill_router=PrefixAffinityRouter(PAGE),
        transport=transport, prefill_replicas=prefill, model_name="tiny")
    for _ in range(decode):
        decode_fleet.add_replica()
    for _ in range(prefill):
        prefill_fleet.add_replica()
    return gw, decode_fleet, prefill_fleet


class TestManifest:
    def _export(self):
        rng = np.random.default_rng(0)
        return KVBlockExport(
            tokens=list(range(16)), page_size=PAGE,
            leaves={
                "['layer_0']['k']": rng.standard_normal(
                    (2, PAGE, 2, 4)).astype(np.float32),
                "['layer_0']['v']": rng.standard_normal(
                    (2, PAGE, 2, 4)).astype(np.float32),
            },
            prefilled_by="prefill-1")

    def test_manifest_roundtrip(self):
        export = self._export()
        uris = {k: f"mem://kv/{i}" for i, k in enumerate(export.leaves)}
        doc = parse_kv_manifest(build_kv_manifest(export, uris))
        assert doc["page_size"] == PAGE
        assert doc["tokens"] == export.tokens
        assert doc["prefilled_by"] == "prefill-1"
        assert set(doc["leaves"]) == set(export.leaves)
        meta = doc["leaves"]["['layer_0']['k']"]
        assert meta["shape"] == [2, PAGE, 2, 4]
        assert meta["dtype"] == "float32"

    def test_parse_rejects_foreign_documents(self):
        with pytest.raises(ValueError, match="manifest"):
            parse_kv_manifest(b'{"format": "jax_sharded_array"}')
        with pytest.raises(ValueError, match="version"):
            parse_kv_manifest(
                b'{"format": "kv_block_manifest", "v": 99}')

    def test_storage_spill_fetch_roundtrip(self):
        from lzy_tpu.storage.mem import MemStorageClient

        storage = MemStorageClient()
        export = self._export()
        uri = spill_kv_export(storage, "mem://bucket/xfer/kv-1", export)
        back = fetch_kv_export(storage, uri)
        assert back.tokens == export.tokens
        assert back.page_size == PAGE
        assert back.prefilled_by == "prefill-1"
        for key, arr in export.leaves.items():
            np.testing.assert_array_equal(back.leaves[key], arr)

    def test_storage_transport_discard_removes_payload(self):
        from lzy_tpu.storage.mem import MemStorageClient

        storage = MemStorageClient()
        transport = StorageKVTransport(storage, "mem://bucket/xfers")
        ref = transport.publish("kv-9", self._export())
        assert transport.fetch(ref).tokens == list(range(16))
        transport.discard(ref)
        with pytest.raises(KVTransferError):
            transport.fetch(ref)

    def test_in_memory_transport_peer_death(self):
        transport = InMemoryKVTransport()
        ref = transport.publish("kv-1", self._export())
        transport.fail_next_fetch = 1
        with pytest.raises(KVTransferError, match="mid-stream"):
            transport.fetch(ref)
        # the next fetch (a retry in a real fabric) succeeds again
        assert transport.fetch(ref).page_size == PAGE


class TestExportImportUnits:
    def test_export_pins_blocks_while_in_flight(self, tiny_model):
        """Refcount integrity on the exporting pool mid-transfer: while
        the gather runs, the exported blocks are pinned — an allocation
        storm cannot evict them — and after the export every refcount is
        back to zero (the tree keeps the blocks cached)."""
        cfg, params = tiny_model
        pf = PrefillEngine(cfg, params, slots=1, page_size=PAGE,
                           kv_blocks=8)               # 7 usable
        prompt = list(range(16)) + [40]               # 2 full blocks
        req = pf.submit(prompt)
        _drive(pf, req)
        seen = {}

        def while_pinned():
            pinned = [b for b in range(pf.kv.pool.n_blocks)
                      if pf.kv.pool.refcount(b) > 0]
            seen["pinned"] = len(pinned)
            # everything evictable is allocatable EXCEPT the pinned
            # blocks: draining the pool must fail before touching them
            with pytest.raises(NoFreeBlocks):
                pf.kv.allocate(pf.kv.available() + 1)
            seen["match_during"] = pf.kv.match_len(prompt[:16])

        export = export_kv(pf, prompt, on_pinned=while_pinned)
        assert export is not None and export.n_blocks == 2
        assert seen["pinned"] == 2
        assert seen["match_during"] == 16
        assert all(pf.kv.pool.refcount(b) == 0
                   for b in range(pf.kv.pool.n_blocks)), "leaked refs"
        # the exported prefix is still cached locally (tree unchanged)
        assert pf.kv.match_len(prompt[:16]) == 16
        # same engine: a sub-block prompt has nothing worth transferring
        short = pf.submit([5, 9, 3])
        _drive(pf, short)
        assert short.error is None and short.kv_export is None

    def test_import_into_nearly_full_pool_evicts_then_imports(
            self, tiny_model, export16):
        """Evict-then-import: a destination pool whose blocks are all
        cached (unreferenced) makes room by LRU eviction; a pool whose
        blocks are PINNED by a resident request refuses the import —
        and the resident request decodes on, bit-identical."""
        cfg, params = tiny_model
        export = export16
        de = DecodeEngine(cfg, params, slots=2, page_size=PAGE,
                          kv_blocks=4)                # 3 usable
        # fill the pool: a finished request leaves 2 cached blocks + 1 free
        warm = de.submit(list(range(32, 48)) + [41], max_new_tokens=2)
        _drive(de, warm)
        assert de.kv.match_len(list(range(32, 48))) == 16
        assert import_kv(de, export) == 2             # 1 free + 1 evicted
        assert de.kv.evictions >= 1, "import did not need eviction"
        assert de.kv.match_len(export.tokens) == 16
        # now pin the whole pool with a live request and import on top
        resident = de.submit(list(range(48, 64)) + [42, 43],
                             max_new_tokens=5)
        de.step()
        assert not resident.done
        # a fresh 2-block payload (tokens differ; the refusal happens on
        # the block budget before any leaf data is read)
        import dataclasses
        big = dataclasses.replace(export, tokens=list(range(16, 32)))
        # free+evictable cannot cover 2 blocks with the resident pinned:
        # the import is refused outright, never forced
        assert de.kv.available() < 2
        assert import_kv(de, big) == 0
        _drive(de, resident)
        assert resident.result(0) == _oracle_tokens(
            cfg, params, resident.prompt, 5), "resident request corrupted"

    def test_import_contract_on_one_engine(self, tiny_model, export16):
        """Three import contracts on ONE decode engine (construction is
        the expensive part): a page-size-mismatched payload is skipped; a
        queued import applies strictly before the admission that wants it
        (prefill runs only the sub-block tail); re-importing an
        already-cached prefix is a no-op that allocates nothing."""
        import dataclasses

        cfg, params = tiny_model
        de = DecodeEngine(cfg, params, slots=1, page_size=PAGE)
        # 1) page-size mismatch → skipped outright
        assert import_kv(
            de, dataclasses.replace(export16, page_size=PAGE * 2)) == 0
        # 2) queued import lands before the admission round
        prompt = export16.tokens + [40, 41]
        de.queue_kv_import(export16)
        req = de.submit(prompt, max_new_tokens=4)
        _drive(de, req)
        assert req.result(0) == _oracle_tokens(cfg, params, prompt, 4)
        s = de.stats()
        assert s.kv_imports == 1 and s.kv_import_blocks == 2
        assert s.prefill_tokens_saved == 16
        # 3) the prefix is now cached: importing it again is a no-op
        free_before = de.kv.pool.free_count()
        assert import_kv(de, export16) == 0
        assert de.kv.pool.free_count() == free_before


class TestDisaggParity:
    """The acceptance property: two-pool output == monolithic paged
    engine == sequential oracle, greedy and sampled."""

    def test_greedy_bit_identical_two_pool_fleet(self, tiny_model):
        cfg, params = tiny_model
        gw, _, _ = _make_disagg(cfg, params, prefill=1, decode=2)
        try:
            mono = PagedInferenceEngine(cfg, params, slots=2,
                                        page_size=PAGE)
            prompts = [list(range(i, i + 20)) + [3, i] for i in range(4)]
            for p in prompts:
                res = gw.generate(p, max_new_tokens=6, timeout_s=120)
                assert res["status"] == "ok" and res["failovers"] == 0
                oracle = _oracle_tokens(cfg, params, p, 6)
                assert res["tokens"] == oracle
                m = mono.submit(p, max_new_tokens=6)
                _drive(mono, m)
                assert res["tokens"] == m.result(0)
                # long prompts went through the prefill pool
                assert res["prefilled_by"].startswith("prefill-")
                assert res["kv_transfer_ms"] is not None
            s = gw.stats()
            assert s["disagg"] is True
            assert s["kv_transfers"] == 4
            assert s["kv_transfer_bytes"] > 0
        finally:
            gw.close()

    def test_sampled_bit_identical_to_monolithic(self, tiny_model):
        """Fresh two-pool fleet vs fresh monolithic engine, same seed:
        the decode replica samples the first token from its own suffix
        prefill — the same rng draw order as a monolithic engine — so
        the sampled stream matches bit-for-bit."""
        cfg, params = tiny_model
        kw = dict(temperature=0.8, top_k=20, seed=7)
        prompt = list(range(8, 28)) + [5]
        mono = PagedInferenceEngine(cfg, params, slots=2, page_size=PAGE,
                                    **kw)
        ref = mono.submit(prompt, max_new_tokens=6)
        _drive(mono, ref)
        gw, _, _ = _make_disagg(cfg, params, prefill=1, decode=2, **kw)
        try:
            res = gw.generate(prompt, max_new_tokens=6, timeout_s=120)
            assert res["tokens"] == ref.result(0)
            assert res["prefilled_by"] is not None
        finally:
            gw.close()

    def test_short_prompt_direct_and_repeat_prefix_skips_transfer(
            self, tiny_model):
        """One gateway, the two no-transfer paths in order: a sub-block
        prompt never touches the prefill pool at all, and a prompt whose
        prefix is expected on the chosen decode replica pays neither
        prefill-pool time nor transfer bytes on the repeat."""
        cfg, params = tiny_model
        gw, _, prefill_fleet = _make_disagg(cfg, params, prefill=1,
                                            decode=2)
        try:
            # sub-block prompt: routed straight to decode
            res = gw.generate([5, 9, 3], max_new_tokens=3, timeout_s=120)
            assert res["tokens"] == _oracle_tokens(cfg, params,
                                                   [5, 9, 3], 3)
            assert res["prefilled_by"] is None
            pf = prefill_fleet.replicas()[0]
            assert pf.engine.stats().requests_finished == 0
            # first long prompt: transferred (staged AND used — the
            # decode engine's prefix match hit the imported blocks)
            shared = list(range(16))
            first = gw.generate(shared + [40, 41], max_new_tokens=3,
                                timeout_s=120)
            assert first["prefilled_by"] is not None
            assert first["kv_staged_by"] == first["prefilled_by"]
            # repeat of the shared prefix: affinity-routed, transfer
            # skipped — nothing newly staged, but the KV actually used
            # still credits the pool that produced it (provenance
            # follows the blocks, not the transfer)
            again = gw.generate(shared + [50], max_new_tokens=3,
                                timeout_s=120)
            assert again["tokens"] == _oracle_tokens(
                cfg, params, shared + [50], 3)
            assert again["kv_transfer_skipped"] is True
            assert again["kv_staged_by"] is None
            assert again["prefilled_by"] == first["prefilled_by"]
            assert again["replica"] == first["replica"]
            s = gw.stats()
            assert s["kv_transfer_skipped_by_cache"] == 1
            assert s["kv_transfers"] == 1
        finally:
            gw.close()


class TestPrefillDeath:
    def test_prefill_kill_and_transport_death_fall_back(self, tiny_model):
        """One gateway, both mid-transfer failure windows in sequence —
        either way the decode side silently re-prefills, the request
        NEVER fails, and output stays bit-identical to the oracle.

        1. The transport stream dies AFTER a successful prefill (the
           literal mid-transfer window, injected at fetch).
        2. The only prefill replica's engine loop dies while the request
           is in flight; the dead replica is retired and the next tick
           re-leases the pool back to size, after which transfers flow
           again."""
        cfg, params = tiny_model
        transport = InMemoryKVTransport()
        gw, _, prefill_fleet = _make_disagg(cfg, params, prefill=1,
                                            decode=1, transport=transport)
        try:
            # 1) payload dies between publish and fetch
            transport.fail_next_fetch = 1
            p = list(range(40, 60)) + [2]
            res = gw.generate(p, max_new_tokens=5, timeout_s=120)
            assert res["status"] == "ok" and res["reprefills"] == 1
            assert res["tokens"] == _oracle_tokens(cfg, params, p, 5)
            # 2) prefill replica host dies mid-request
            victim = prefill_fleet.replicas()[0]

            def boom():
                raise RuntimeError("prefill host on fire")

            victim.engine.step = boom
            p = list(range(20)) + [7]
            res = gw.generate(p, max_new_tokens=5, timeout_s=120)
            assert res["status"] == "ok"
            assert res["tokens"] == _oracle_tokens(cfg, params, p, 5)
            assert res["reprefills"] == 1
            assert res["prefilled_by"] is None
            assert gw.stats()["reprefill_fallbacks"] == 2
            # the dead replica left the pool; the tick restores the size
            assert victim.id not in [r.id for r in
                                     prefill_fleet.replicas()]
            gw.tick()
            assert len(prefill_fleet.replicas()) == 1
            # and the restored pool serves transfers again
            p2 = list(range(30, 50)) + [8]
            res2 = gw.generate(p2, max_new_tokens=4, timeout_s=120)
            assert res2["tokens"] == _oracle_tokens(cfg, params, p2, 4)
            assert res2["prefilled_by"] is not None
        finally:
            gw.close()

    def test_decode_replica_killed_mid_stream_fails_over(self, tiny_model):
        """Decode-side death keeps the parent gateway's fenced-token
        failover, and the retry restages KV for the surviving replica:
        final output identical to an uninterrupted run."""
        cfg, params = tiny_model
        gw, decode_fleet, _ = _make_disagg(cfg, params, prefill=1,
                                           decode=2)
        result = {}
        prompt = list(range(4, 24)) + [9]

        def run():
            try:
                result["res"] = gw.generate(prompt, max_new_tokens=24,
                                            timeout_s=120)
            except BaseException as e:  # surfaced in the main thread
                result["err"] = e

        try:
            t = threading.Thread(target=run)
            t.start()
            victim = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                for replica in decode_fleet.replicas():
                    live = [r for r in replica.engine._active
                            if r is not None]
                    if live and len(live[0].tokens) >= 3:
                        victim = replica
                        break
                if victim:
                    break
                time.sleep(0.005)
            assert victim is not None, "request never reached mid-decode"

            def boom():
                raise RuntimeError("decode host on fire")

            victim.engine.step = boom
            t.join(120)
            assert "err" not in result, result.get("err")
            res = result["res"]
            assert res["tokens"] == _oracle_tokens(cfg, params, prompt, 24)
            assert res["failovers"] == 1 and res["status"] == "ok"
            assert victim.id not in [r.id for r in decode_fleet.replicas()]
        finally:
            gw.close()


class TestDisaggRpc:
    def test_disagg_generate_and_pool_stats_over_the_control_plane(
            self, tiny_model, tmp_path):
        """In-process two-pool fleet behind the real RPC stack: replies
        carry prefilled_by/kv_transfer_ms, InferStats carries the disagg
        counters, InferFleetStats splits per pool."""
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.service import InProcessCluster

        cfg, params = tiny_model

        def factory(cluster):
            gw, _, _ = _make_disagg(cfg, params, prefill=1, decode=2)
            return gw

        cluster = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            inference_factory=factory,
        )
        try:
            client = RpcInferenceClient(cluster.rpc_server.address)
            try:
                p = list(range(20)) + [3]
                res = client.generate(p, max_new_tokens=4, timeout_s=120)
                assert res["tokens"] == _oracle_tokens(cfg, params, p, 4)
                assert res["prefilled_by"].startswith("prefill-")
                assert res["kv_transfer_ms"] is not None
                stats = client.stats()
                assert stats["disagg"] is True
                assert stats["kv_transfers"] == 1
                fs = client.fleet_stats()
                assert fs["pools"] == {"decode": 2, "prefill": 1}
                pools = {r["replica"]: r["pool"] for r in fs["replicas"]}
                assert pools["prefill-1"] == "prefill"
                assert pools["decode-1"] == "decode"
            finally:
                client.close()
        finally:
            cluster.shutdown()


class TestGlobalIndexInPreSubmit:
    """Satellite (ROADMAP item 2 remainder): the disagg ``_pre_submit``
    also consults the fleet-global KV index — prefill-pool staging keeps
    priority, but when the pool lands nothing, a DECODE-pool sibling
    holding a deeper chain than the routed replica's own radix+tier
    coverage is imported where previously the request always
    re-prefilled locally."""

    def _make(self, cfg, params, *, prefill=0, decode=2):
        from lzy_tpu.gateway import GlobalKVIndex, RoundRobinRouter

        decode_fleet = ReplicaFleet(
            lambda: DecodeEngine(cfg, params, slots=2, page_size=PAGE,
                                 kv_blocks=32),
            replica_prefix="decode")
        prefill_fleet = ReplicaFleet(
            lambda: PrefillEngine(cfg, params, slots=2, page_size=PAGE,
                                  kv_blocks=32),
            replica_prefix="prefill")
        gw = DisaggGatewayService(
            decode_fleet, prefill_fleet, page_size=PAGE,
            # round-robin pins request i to decode replica (i % N): the
            # second request DETERMINISTICALLY lands on the cold sibling
            router=RoundRobinRouter(PAGE),
            prefill_router=RoundRobinRouter(PAGE),
            prefill_replicas=prefill, model_name="tiny",
            kv_index=GlobalKVIndex(PAGE))
        for _ in range(decode):
            decode_fleet.add_replica()
        for _ in range(prefill):
            prefill_fleet.add_replica()
        return gw, decode_fleet

    def test_decode_sibling_import_replaces_reprefill(self, tiny_model):
        """Prefill pool EMPTY (every staging falls back): request 2,
        routed to the cold decode replica, imports the warm sibling's
        blocks instead of re-prefilling — bit-identical output, import
        counted on the cold engine, prefill tokens saved."""
        cfg, params = tiny_model
        gw, dfleet = self._make(cfg, params, prefill=0)
        try:
            shared = list(range(1, 4 * PAGE + 1))
            r1 = gw.generate(shared + [5], max_new_tokens=6,
                             timeout_s=120)
            assert r1["tokens"] == _oracle_tokens(cfg, params,
                                                  shared + [5], 6)
            assert r1["prefilled_by"] is None       # pool is empty
            assert r1["reprefills"] == 1            # fallback counted
            gw.tick()       # decode replicas advertise into the index
            r2 = gw.generate(shared + [9], max_new_tokens=6,
                             timeout_s=120)
            assert r2["tokens"] == _oracle_tokens(cfg, params,
                                                  shared + [9], 6)
            assert r2["replica"] != r1["replica"]
            # the import was staged from the decode-pool SIBLING (not a
            # prefill replica) and the prefix match really hit it
            assert r2["kv_import_staged_from"] == r1["replica"]
            assert r2["kv_import_from"] == r1["replica"]
            assert r2["kv_import_tier"] == "hbm"
            cold = dfleet.get(r2["replica"]).engine
            assert cold.kv_imports == 1
            assert cold.kv.stats().prefill_tokens_saved >= 4 * PAGE
            stats = gw.stats()
            assert stats["kvtier_imports"] == 1
            assert stats["reprefill_fallbacks"] == 2
        finally:
            gw.close()

    def test_prefill_pool_keeps_priority(self, tiny_model):
        """With a live prefill pool, staging comes from it and the
        global index is NOT consulted (no cross-replica import)."""
        cfg, params = tiny_model
        gw, dfleet = self._make(cfg, params, prefill=1)
        try:
            shared = list(range(1, 4 * PAGE + 1))
            r1 = gw.generate(shared + [5], max_new_tokens=4,
                             timeout_s=120)
            assert r1["kv_staged_by"] is not None
            assert r1["kv_staged_by"].startswith("prefill-")
            gw.tick()
            r2 = gw.generate(shared + [9], max_new_tokens=4,
                             timeout_s=120)
            assert r2["tokens"] == _oracle_tokens(cfg, params,
                                                  shared + [9], 4)
            # the prefill pool staged (or the router expected residency);
            # either way no decode-sibling import was needed
            assert gw.stats()["kvtier_imports"] == 0
        finally:
            gw.close()
