"""Storage + snapshot tests (reference tier: ``pylzy/tests/storage``)."""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.serialization import default_registry
from lzy_tpu.snapshot import Snapshot
from lzy_tpu.storage import (
    DefaultStorageRegistry,
    FsStorageClient,
    MemStorageClient,
    StorageConfig,
)
from lzy_tpu.storage.api import join_uri


@pytest.mark.parametrize("kind", ["fs", "mem"])
def test_storage_roundtrip(kind, tmp_storage_uri):
    client = FsStorageClient() if kind == "fs" else MemStorageClient()
    prefix = tmp_storage_uri if kind == "fs" else "mem://bucket"
    uri = join_uri(prefix, "a/b/obj")
    assert not client.exists(uri)
    client.write_bytes(uri, b"hello world")
    assert client.exists(uri)
    assert client.size(uri) == 11
    assert client.read_bytes(uri) == b"hello world"
    assert client.read_range(uri, 6) == b"world"
    assert client.read_range(uri, 0, 5) == b"hello"
    assert list(client.list(prefix)) == [uri]
    client.delete(uri)
    assert not client.exists(uri)


def test_fs_write_atomic(tmp_storage_uri):
    """A failing source stream must not leave a partial object behind."""
    client = FsStorageClient()
    uri = join_uri(tmp_storage_uri, "obj")

    class Boom(io.RawIOBase):
        def read(self, n=-1):
            raise RuntimeError("stream died")

    with pytest.raises(RuntimeError):
        client.write(uri, Boom())
    assert not client.exists(uri)


def test_storage_registry_default():
    reg = DefaultStorageRegistry()
    assert reg.default_client() is None
    reg.register_storage("a", StorageConfig(uri="mem://a"))
    reg.register_storage("b", StorageConfig(uri="mem://b"), default=True)
    assert reg.default_name() == "b"
    assert reg.config("a").uri == "mem://a"
    reg.unregister_storage("b")
    assert reg.default_name() == "a"


def test_snapshot_put_get_entries():
    snap = Snapshot(
        workflow_name="wf",
        execution_id="exec-1",
        storage_client=MemStorageClient(),
        storage_prefix="mem://bucket",
        serializers=default_registry(),
    )
    e1 = snap.create_entry("arg_0", int)
    snap.put(e1.id, 41)
    assert snap.get(e1.id) == 41
    assert e1.materialized and e1.hash

    arr = jnp.arange(8, dtype=jnp.bfloat16)
    e2 = snap.create_entry("ret_0")
    snap.put(e2.id, arr)
    out = snap.get(e2.id)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))

    # copy (whiteboard aliasing path)
    e3 = snap.create_entry("wb_field")
    snap.copy_from_uri(e3.id, e2.storage_uri, e2.data_scheme)
    assert e3.hash == e2.hash
    np.testing.assert_array_equal(np.asarray(snap.get(e3.id)), np.asarray(arr))


def test_snapshot_same_value_same_hash():
    snap = Snapshot(
        workflow_name="wf",
        execution_id="exec-2",
        storage_client=MemStorageClient(),
        storage_prefix="mem://bucket",
        serializers=default_registry(),
    )
    a = snap.create_entry("a")
    b = snap.create_entry("b")
    snap.put(a.id, {"x": 1})
    snap.put(b.id, {"x": 1})
    assert a.hash == b.hash
    assert a.storage_uri != b.storage_uri
