"""Parallel ranged transfer engine: multipart round-trips, retry policy,
progress reporting, atomic completion (util-s3 UploadProcessingLoop parity)."""

import hashlib
import os
import threading

import pytest

from lzy_tpu.storage import StorageConfig
from lzy_tpu.storage.fs import FsStorageClient
from lzy_tpu.storage.mem import MemStorageClient
from lzy_tpu.storage.registry import client_for
from lzy_tpu.storage.transfer import (
    TransferConfig,
    TransferError,
    download,
    log_progress,
    upload,
)

SMALL_CFG = TransferConfig(part_size=1 * 1024 * 1024, max_workers=8,
                           retries=3, backoff_s=0.01)


def make_blob(path, mb: int) -> str:
    """Deterministic pseudorandom content; returns its sha256."""
    h = hashlib.sha256()
    with open(path, "wb") as f:
        for i in range(mb):
            chunk = hashlib.sha256(f"chunk-{i}".encode()).digest() * 32768
            chunk = chunk[: 1024 * 1024]
            f.write(chunk)
            h.update(chunk)
    return h.hexdigest()


def sha256_file(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def ranged_only(client):
    """Hide the local-fs kernel-copy fast path so a test exercises the
    generic ranged machinery (the path network object stores take)."""
    client.upload_file = None
    client.download_file = None
    return client


class FlakyClient(FsStorageClient):
    """Fails the first N calls of read_range/size to exercise retries.
    Fast paths are hidden: the injected failures live in the ranged path."""

    def __init__(self, fail_first: int):
        self._failures_left = fail_first
        self._lock = threading.Lock()
        ranged_only(self)

    def _maybe_fail(self, what: str):
        with self._lock:
            if self._failures_left > 0:
                self._failures_left -= 1
                raise ConnectionError(f"injected {what} failure")

    def read_range(self, uri, offset, length=-1):
        self._maybe_fail("read_range")
        return super().read_range(uri, offset, length)


class TestRoundTrip:
    def test_fs_multipart_round_trip_64mb(self, tmp_path):
        src = tmp_path / "src.bin"
        digest = make_blob(src, 64)                  # 64 parts of 1 MB
        client = ranged_only(FsStorageClient())
        uri = f"file://{tmp_path}/store/blob.bin"

        events = []
        n = upload(client, uri, str(src), config=SMALL_CFG,
                   progress=lambda d, t: events.append((d, t)))
        assert n == 64 * 1024 * 1024 == client.size(uri)

        dest = tmp_path / "dest.bin"
        n2 = download(client, uri, str(dest), config=SMALL_CFG)
        assert n2 == n and sha256_file(dest) == digest

        # progress: monotone, byte-accurate, ends at total
        dones = [d for d, _ in events]
        assert dones == sorted(dones) and dones[-1] == n
        assert all(t == n for _, t in events)

    def test_mem_backend_download(self, tmp_path):
        client = MemStorageClient()
        data = os.urandom(3 * 1024 * 1024 + 17)      # non-aligned size
        client.write_bytes("mem://b/x", data)
        dest = tmp_path / "out.bin"
        n = download(client, "mem://b/x", str(dest),
                     config=TransferConfig(part_size=1024 * 1024,
                                           max_workers=4, retries=2,
                                           backoff_s=0.01))
        assert n == len(data) and dest.read_bytes() == data

    def test_fs_fast_path_round_trip(self, tmp_path):
        """On a local fs backend the engine takes the kernel-copy fast
        path (upload_file/download_file) by default; same bytes, atomic
        at the destination."""
        src = tmp_path / "src.bin"
        data = os.urandom(5 * 1024 * 1024 + 13)
        src.write_bytes(data)
        client = FsStorageClient()
        assert client.upload_file is not None     # fast path present
        uri = f"file://{tmp_path}/store/fast.bin"
        n = upload(client, uri, str(src), config=SMALL_CFG)
        assert n == len(data)
        dest = tmp_path / "fast-out.bin"
        n2 = download(client, uri, str(dest), config=SMALL_CFG)
        assert n2 == len(data) and dest.read_bytes() == data
        assert not os.path.exists(str(dest) + ".part")

    def test_zero_byte_object(self, tmp_path):
        client = FsStorageClient()
        uri = f"file://{tmp_path}/empty.bin"
        client.write_bytes(uri, b"")
        dest = tmp_path / "empty.out"
        assert download(client, uri, str(dest), config=SMALL_CFG) == 0
        assert dest.read_bytes() == b""

    @pytest.mark.skipif(not os.environ.get("LZY_BIG_STORAGE_TEST"),
                        reason="1-GB round-trip is opt-in (LZY_BIG_STORAGE_TEST=1)")
    def test_fs_round_trip_1gb(self, tmp_path):
        src = tmp_path / "big.bin"
        digest = make_blob(src, 1024)
        client = FsStorageClient()
        uri = f"file://{tmp_path}/store/big.bin"
        cfg = TransferConfig(part_size=64 * 1024 * 1024, max_workers=8,
                             retries=3, backoff_s=0.05)
        upload(client, uri, str(src), config=cfg,
               progress=log_progress("upload big.bin"))
        dest = tmp_path / "big.out"
        download(client, uri, str(dest), config=cfg,
                 progress=log_progress("download big.bin"))
        assert sha256_file(dest) == digest


class TestRetries:
    def test_transient_failures_are_retried(self, tmp_path):
        client = FlakyClient(fail_first=5)
        uri = f"file://{tmp_path}/blob.bin"
        payload = os.urandom(4 * 1024 * 1024)
        client.write_bytes(uri, payload)
        dest = tmp_path / "out.bin"
        n = download(client, uri, str(dest), config=SMALL_CFG)
        assert n == len(payload) and dest.read_bytes() == payload

    def test_persistent_failure_surfaces_after_retries(self, tmp_path):
        client = FlakyClient(fail_first=10_000)
        uri = f"file://{tmp_path}/blob.bin"
        FsStorageClient().write_bytes(uri, os.urandom(1024))
        with pytest.raises(TransferError, match="after 3 attempts"):
            download(client, uri, str(tmp_path / "out.bin"), config=SMALL_CFG)
        # atomic: no half-written destination, no .part litter
        assert not (tmp_path / "out.bin").exists()
        assert not (tmp_path / "out.bin.part").exists()

    def test_failed_upload_leaves_no_partial_object(self, tmp_path):
        client = ranged_only(FsStorageClient())
        src = tmp_path / "src.bin"
        src.write_bytes(os.urandom(2 * 1024 * 1024))
        uri = f"file://{tmp_path}/store/obj.bin"

        real_pread = os.pread
        calls = {"n": 0}

        def flaky_pread(fd, length, offset):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise OSError("disk on fire")
            return real_pread(fd, length, offset)

        os.pread = flaky_pread
        try:
            with pytest.raises(TransferError):
                upload(client, uri, str(src), config=SMALL_CFG)
        finally:
            os.pread = real_pread
        assert not client.exists(uri)
        leftovers = [p for p in (tmp_path / "store").glob("*")
                     if p.is_file()] if (tmp_path / "store").is_dir() else []
        assert leftovers == []


class TestGatedS3:
    def test_s3_multipart_gated(self):
        pytest.importorskip("boto3")
        # boto3 exists in this env only if an operator installed it; then the
        # client constructs and exposes the multipart capability
        client = client_for(StorageConfig(uri="s3://bucket/prefix"))
        assert callable(getattr(client, "multipart_upload", None))


class TestGatedAzure:
    def test_azure_gated_with_clear_error(self):
        """Without the azure SDK the client must fail at construction with an
        actionable message, never at first use."""
        # syntactically valid: the SDK parses eagerly (no network at init)
        cs = ("DefaultEndpointsProtocol=https;AccountName=a;"
              "AccountKey=aGV5;EndpointSuffix=core.windows.net")
        try:
            import azure.storage.blob  # noqa: F401
        except ImportError:
            with pytest.raises(ImportError, match="azure-storage-blob"):
                client_for(StorageConfig(uri="azure://container/prefix",
                                         connection_string=cs))
        else:
            client = client_for(StorageConfig(uri="azure://container/prefix",
                                              connection_string=cs))
            assert client.scheme == "azure"

    def test_azure_requires_credentials(self):
        pytest.importorskip("azure.storage.blob")
        with pytest.raises(ValueError, match="connection_string"):
            client_for(StorageConfig(uri="azure://container/prefix"))
