"""Serialization tests, modeled on the reference's
``pylzy/tests/serialization`` tier (SURVEY.md §4.1)."""

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.serialization import default_registry
from lzy_tpu.types import File


@pytest.fixture(scope="module")
def reg():
    return default_registry()


def roundtrip(reg, value):
    ser = reg.find_by_instance(value)
    buf = io.BytesIO()
    ser.serialize(value, buf)
    buf.seek(0)
    # read side resolves by stored format, like Snapshot.get
    reader = reg.find_by_format(ser.data_scheme(value).data_format)
    return ser, reader.deserialize(buf, type(value))


@pytest.mark.parametrize("value", [42, 3.14, "hello", True, None])
def test_primitives(reg, value):
    ser, out = roundtrip(reg, value)
    assert ser.format_name() == "primitive"
    assert out == value


def test_numpy_array(reg):
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    ser, out = roundtrip(reg, arr)
    assert ser.format_name() == "jax_array"
    np.testing.assert_array_equal(out, arr)
    assert isinstance(out, np.ndarray)


def test_jax_array_bfloat16(reg):
    arr = jnp.linspace(-2, 2, 16, dtype=jnp.bfloat16).reshape(4, 4)
    ser, out = roundtrip(reg, arr)
    assert ser.format_name() == "jax_array"
    assert isinstance(out, jax.Array)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))


def test_pytree_params(reg):
    params = {
        "dense": {"kernel": jnp.ones((8, 8), jnp.bfloat16), "bias": jnp.zeros((8,))},
        "steps": 7,
    }
    ser, out = roundtrip(reg, params)
    assert ser.format_name() == "jax_pytree"
    assert out["steps"] == 7
    assert out["dense"]["kernel"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["dense"]["bias"]), np.zeros((8,))
    )


def test_arbitrary_object_falls_to_cloudpickle(reg):
    @dataclasses.dataclass
    class Model:
        name: str
        score: float

    ser, out = roundtrip(reg, Model("m", 0.9))
    assert ser.format_name() == "cloudpickle"
    assert out == Model("m", 0.9)
    assert not ser.stable()


def test_file_serializer(reg, tmp_path):
    p = tmp_path / "data.bin"
    p.write_bytes(b"\x00\x01payload")
    f = File(p)
    ser = reg.find_by_instance(f)
    assert ser.format_name() == "raw_file"
    buf = io.BytesIO()
    ser.serialize(f, buf)
    buf.seek(0)
    out = ser.deserialize(buf)
    assert isinstance(out, File)
    assert out.read_bytes() == b"\x00\x01payload"
    assert str(out) != str(f)


def test_custom_serializer_registration(reg):
    from lzy_tpu.serialization.registry import Serializer, SerializerRegistry

    class UpperStr(Serializer):
        def format_name(self):
            return "upper_str"

        def supports_type(self, typ):
            return typ is str

        def serialize(self, obj, dest):
            dest.write(obj.upper().encode())

        def deserialize(self, src, typ=None):
            return src.read().decode()

    r = SerializerRegistry()
    r.register(UpperStr())
    assert r.find_by_instance("x").format_name() == "upper_str"
    with pytest.raises(TypeError):
        r.find_by_instance(42)
    with pytest.raises(ValueError):
        r.register(UpperStr())
