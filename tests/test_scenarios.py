"""Scenario-diff harness: run each end-to-end scenario in its own process and
compare stdout against its ``expected_stdout``, the reference's tier-4 pattern
(``PythonContextTests`` + ``pylzy/tests/scenarios/<name>/expected_stdout``,
SURVEY.md §4.4)."""

import pathlib
import subprocess
import sys

import pytest

SCENARIOS_DIR = pathlib.Path(__file__).parent / "scenarios"
REPO_ROOT = SCENARIOS_DIR.parent.parent

SCENARIOS = sorted(
    p.name for p in SCENARIOS_DIR.iterdir()
    if p.is_dir() and (p / "expected_stdout").exists()
)


@pytest.mark.parametrize("name", SCENARIOS)
def test_scenario(name):
    expected = (SCENARIOS_DIR / name / "expected_stdout").read_text()
    result = subprocess.run(
        [sys.executable, "-m", f"tests.scenarios.{name}"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert result.returncode == 0, (
        f"scenario {name} exited {result.returncode}\n"
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    )
    assert result.stdout == expected, (
        f"scenario {name} output mismatch\n"
        f"expected:\n{expected}\ngot:\n{result.stdout}"
    )


def test_all_scenarios_discovered():
    assert len(SCENARIOS) >= 6
