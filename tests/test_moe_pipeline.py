"""Expert-parallel MoE and pipeline-parallel tests on the 8-device mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from lzy_tpu.models import llama
from lzy_tpu.models.common import param_logical_axes, unbox
from lzy_tpu.models.llama import Llama, LlamaConfig
from lzy_tpu.models.moe import MoeConfig, MoeMlp
from lzy_tpu.parallel import TrainState, make_train_step, mesh_for
from lzy_tpu.parallel.pipeline import pipeline_apply


class TestMoe:
    def _init(self, cfg, b=4, t=8, seed=0):
        model = MoeMlp(cfg)
        x = jax.random.normal(jax.random.PRNGKey(seed), (b, t, cfg.d_model),
                              jnp.float32)
        boxed = model.init(jax.random.PRNGKey(1), x)["params"]
        return model, unbox(boxed), param_logical_axes(boxed), x

    def test_forward_shape_and_aux(self):
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4)
        model, params, _, x = self._init(cfg)
        out, aux = model.apply({"params": params}, x)
        assert out.shape == x.shape
        assert float(aux) > 0.0

    def test_expert_params_annotated_for_ep(self):
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4)
        _, _, axes, _ = self._init(cfg)
        assert axes["w_in"] == ("expert", "embed", "mlp")
        assert axes["router"] == ("embed", "expert")

    def test_tokens_actually_routed(self):
        """With generous capacity every token must be fully combined (weights
        sum to 1) and experts see balanced-ish load."""
        cfg = MoeConfig(d_model=8, d_ff=16, n_experts=2, top_k=2,
                        capacity_factor=4.0)
        model, params, _, x = self._init(cfg, b=2, t=16)
        out, _ = model.apply({"params": params}, x)
        # top_k == n_experts and ample capacity → output is an exact convex
        # combination of both experts for every token: no dropped tokens, so
        # no token equals the plain residual zero
        assert not np.allclose(np.asarray(out), 0.0)

    def test_ep_sharded_train_step(self):
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4)
        mesh = mesh_for(ep=4, fsdp=2)
        model, params, axes, x = self._init(cfg, b=8)

        def loss_fn(p, batch):
            out, aux = model.apply({"params": p}, batch["x"])
            return jnp.mean(out.astype(jnp.float32) ** 2) + aux

        tx = optax.adam(1e-2)
        step, shard_state, _ = make_train_step(
            loss_fn, tx, mesh=mesh, param_logical_axes=axes,
            batch_logical_axes=("batch", None, None),
        )
        state = shard_state(TrainState.create(params, tx))
        # expert weights sharded over ep
        assert state.params["w_in"].sharding.spec[0] == "ep"
        losses = []
        for _ in range(3):
            state, metrics = step(state, {"x": x})
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]


class TestPipeline:
    def test_matches_sequential(self):
        mesh = mesh_for(4, pp=4)
        n_stages, n_micro, mb, d = 4, 8, 4, 16
        key = jax.random.PRNGKey(0)
        weights = jax.random.normal(key, (n_stages, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d))

        def stage_fn(w, h):
            return jnp.tanh(h @ w)

        out = pipeline_apply(stage_fn, weights, x, mesh=mesh)

        expected = x
        for s in range(n_stages):
            expected = jnp.tanh(expected @ weights[s])
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=1e-5, rtol=1e-5
        )

    def test_jit_and_grad(self):
        mesh = mesh_for(2, pp=2)
        weights = jnp.stack([jnp.eye(8) * 0.5, jnp.eye(8) * 2.0])
        x = jnp.ones((4, 2, 8))

        def stage_fn(w, h):
            return h @ w

        @jax.jit
        def loss(w):
            return pipeline_apply(stage_fn, w, x, mesh=mesh).sum()

        val = loss(weights)
        np.testing.assert_allclose(float(val), 4 * 2 * 8 * 1.0, rtol=1e-6)
        grads = jax.grad(loss)(weights)
        assert grads.shape == weights.shape
        assert float(jnp.abs(grads).sum()) > 0

    def test_pipeline_with_params_pytree(self):
        mesh = mesh_for(2, pp=2)
        params = {
            "w": jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8)) * 0.2,
            "b": jnp.zeros((2, 8)),
        }
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 3, 8))

        def stage_fn(p, h):
            return jax.nn.relu(h @ p["w"] + p["b"])

        out = pipeline_apply(stage_fn, params, x, mesh=mesh)
        expected = x
        for s in range(2):
            expected = jax.nn.relu(
                expected @ params["w"][s] + params["b"][s]
            )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=1e-5
        )


class TestLlamaPipeline:
    """Pipeline parallelism wired into the Llama family (VERDICT r2 #5)."""

    def _cfg(self, **kw):
        kw.setdefault("pp_stages", 2)
        return dataclasses.replace(LlamaConfig.tiny(vocab_size=256), **kw)

    def test_pp_forward_matches_dense(self):
        cfg = self._cfg(dtype=jnp.float32)
        mesh = mesh_for(2, pp=2)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size
        )
        pp_logits = llama.pp_forward(params, tokens, cfg, mesh)

        dense_cfg = dataclasses.replace(cfg, pp_stages=0)
        dense_params = llama.unstack_pp_params(cfg, params)
        dense_logits = Llama(dense_cfg).apply(
            {"params": dense_params}, tokens
        )
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(dense_logits),
            atol=1e-4, rtol=1e-4,
        )

    def test_pp_composes_with_fsdp_tp_and_trains(self):
        cfg = self._cfg()
        mesh = mesh_for(8, pp=2, fsdp=2, tp=2)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(params, tx))

        # stage stacking sharded over pp AND the stage weights over fsdp/tp
        gate = state.params["stages"]["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert gate.sharding.spec[0] == "pp", gate.sharding.spec
        assert "tp" in str(gate.sharding.spec) and "fsdp" in str(
            gate.sharding.spec
        ), gate.sharding.spec

        before = np.asarray(jax.device_get(gate))
        batch = {
            "tokens": jax.random.randint(
                jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size
            )
        }
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

        # grads flowed into BOTH stages (both slices of the stack moved)
        after = np.asarray(jax.device_get(
            state.params["stages"]["layer_0"]["mlp"]["gate_proj"]["kernel"]
        ))
        for s in range(cfg.pp_stages):
            assert np.abs(after[s] - before[s]).max() > 0, f"stage {s} frozen"

    def test_pp_microbatches_flag(self):
        cfg = self._cfg(dtype=jnp.float32, pp_microbatches=4)
        mesh = mesh_for(2, pp=2)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
        )
        pp_logits = llama.pp_forward(params, tokens, cfg, mesh)
        dense_logits = Llama(dataclasses.replace(cfg, pp_stages=0)).apply(
            {"params": llama.unstack_pp_params(cfg, params)}, tokens
        )
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(dense_logits),
            atol=1e-4, rtol=1e-4,
        )

    def test_pp_rejects_bad_configs(self):
        with pytest.raises(ValueError, match="divisible"):
            llama.init_params(
                self._cfg(pp_stages=3), jax.random.PRNGKey(0)
            )
        with pytest.raises(ValueError, match="compose"):
            llama.init_params(
                self._cfg(decode=True), jax.random.PRNGKey(0)
            )


class TestLlamaPipelineWithPackedSegments:
    """Packed documents ride the pipeline: each stage looks up its
    current microbatch's segment ids by index (pipeline_apply's
    pass_micro_index hook), so attention masking and per-document RoPE
    restarts follow their microbatch through the stages."""

    def _setup(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        b, t = 4, 24
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (b, t), 0, cfg.vocab_size)
        segments = jnp.broadcast_to(
            (jnp.arange(t) >= t // 3).astype(jnp.int32), (b, t))
        return cfg, mesh, params, tokens, segments

    def test_packed_pp_matches_packed_dense(self):
        cfg, mesh, params, tokens, segments = self._setup()
        pp_logits = llama.pp_forward(params, tokens, cfg, mesh,
                                     segments=segments)
        dense_cfg = dataclasses.replace(cfg, pp_stages=0)
        dense = Llama(dense_cfg).apply(
            {"params": llama.unstack_pp_params(cfg, params)},
            tokens, None, segments)
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(dense), atol=2e-4, rtol=2e-4)

    def test_documents_stay_isolated_across_stages(self):
        """Perturbing document-0 tokens must not change document-1
        logits — the segment mask must really ride each microbatch."""
        cfg, mesh, params, tokens, segments = self._setup()
        t = tokens.shape[1]
        base = llama.pp_forward(params, tokens, cfg, mesh,
                                segments=segments)
        tokens2 = tokens.at[:, 0].set((tokens[:, 0] + 7) % cfg.vocab_size)
        moved = llama.pp_forward(params, tokens2, cfg, mesh,
                                 segments=segments)
        leak = float(jnp.abs(
            moved[:, t // 3:] - base[:, t // 3:]).max())
        assert leak == 0.0, leak

    def test_packed_pp_trains(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=256),
                                  pp_stages=2)
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"))
        state = shard_state(TrainState.create(params, tx))
        batch = {
            "tokens": jax.random.randint(
                jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size),
            "segments": jnp.broadcast_to(
                (jnp.arange(32) >= 12).astype(jnp.int32), (8, 32)),
        }
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_packed_with_sp_rejected_clearly(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            use_ring_attention=True, dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, sp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 32), jnp.int32)
        segments = jnp.zeros((4, 32), jnp.int32)
        with pytest.raises(ValueError, match="segments do not compose"):
            llama.pp_forward(params, tokens, cfg, mesh, segments=segments)


class TestLlamaPipelineWithMoe:
    """pp × MoE: the stages' sown load-balancing aux rides the pipeline
    (bubble-masked, summed over stages, averaged over microbatches)."""

    def test_pp_moe_matches_per_microbatch_dense(self):
        """Exact spec: pipeline == dense applied PER MICROBATCH (MoE
        capacity is per-group, so full-batch dense differs by design —
        same as every GPipe×MoE system)."""
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2, n_experts=4,
            dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
        pp_logits, pp_aux = llama.pp_forward(params, tokens, cfg, mesh)

        dense_cfg = dataclasses.replace(cfg, pp_stages=0)
        dense_params = llama.unstack_pp_params(cfg, params)
        n_micro, mb = cfg.pp_stages, tokens.shape[0] // cfg.pp_stages
        outs, auxs = [], []
        for i in range(n_micro):
            lg, sown = Llama(dense_cfg).apply(
                {"params": dense_params}, tokens[i * mb:(i + 1) * mb],
                mutable=["losses"])
            outs.append(lg)
            auxs.append(sum(
                jax.tree_util.tree_leaves(sown.get("losses", {})),
                jnp.zeros((), jnp.float32)))
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(jnp.concatenate(outs, 0)),
            atol=2e-4, rtol=2e-4)
        dense_aux = sum(float(a) for a in auxs) / n_micro
        assert abs(float(pp_aux) - dense_aux) < 1e-6
        assert float(pp_aux) > 0          # the aux really flowed out

    def test_pp_moe_ep_fsdp_trains(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2, n_experts=4)
        mesh = mesh_for(8, pp=2, ep=2, fsdp=2)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"))
        state = shard_state(TrainState.create(params, tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size)}
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_pp_moe_with_sp_initializes(self):
        """MoE + sequence parallelism inside the pipeline used to be
        rejected (the aux loss wasn't sp-reduced); the pipeline now
        pmeans it over sp, so the 3-axis config must construct — the
        full train-step coverage lives in TestPpSpEp."""
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2, n_experts=4,
            use_ring_attention=True)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        assert "stages" in params


class TestLlamaPipelineWithRing:
    """pp × ring sequence parallelism on one mesh (VERDICT r3 #7 — the
    BASELINE config-4 spirit: pipelined long-context training). The
    pipeline's manual region covers {pp, sp}; the ring recurrence runs
    directly against the manual sp axis (nested shard_maps cannot re-bind
    an axis — both partitioners reject it)."""

    def _cfg(self, **kw):
        kw.setdefault("pp_stages", 2)
        kw.setdefault("use_ring_attention", True)
        return dataclasses.replace(LlamaConfig.tiny(vocab_size=256), **kw)

    def test_pp_ring_forward_matches_dense(self):
        cfg = self._cfg(dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, sp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
        )
        pp_logits = llama.pp_forward(params, tokens, cfg, mesh)
        dense_cfg = dataclasses.replace(
            cfg, pp_stages=0, use_ring_attention=False)
        dense_logits = Llama(dense_cfg).apply(
            {"params": llama.unstack_pp_params(cfg, params)}, tokens
        )
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(dense_logits),
            atol=2e-4, rtol=2e-4,
        )

    def test_pp_ring_fsdp_trains(self):
        cfg = self._cfg()
        mesh = mesh_for(8, pp=2, sp=2, fsdp=2)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
        )
        state = shard_state(TrainState.create(params, tx))
        batch = {
            "tokens": jax.random.randint(
                jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size
            )
        }
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_pp_ulysses_forward_matches_dense(self):
        """Ulysses composes with pp the same way ring does: the all-to-
        alls run directly against the manual sp axis inside the stages."""
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            use_ulysses_attention=True, dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, sp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
        )
        pp_logits = llama.pp_forward(params, tokens, cfg, mesh)
        dense_cfg = dataclasses.replace(
            cfg, pp_stages=0, use_ulysses_attention=False)
        dense_logits = Llama(dense_cfg).apply(
            {"params": llama.unstack_pp_params(cfg, params)}, tokens
        )
        np.testing.assert_allclose(
            np.asarray(pp_logits), np.asarray(dense_logits),
            atol=2e-4, rtol=2e-4,
        )

    def test_pp_ulysses_heads_divisibility_checked(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            use_ulysses_attention=True, dtype=jnp.float32, n_heads=6,
            n_kv_heads=2, d_model=96)
        mesh = mesh_for(8, pp=2, sp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 32), jnp.int32)
        with pytest.raises(ValueError, match="n_heads=6 divisible"):
            llama.pp_forward(params, tokens, cfg, mesh)

    def test_ring_without_sp_axis_rejected_clearly(self):
        """A pp+ring config on a mesh with no usable sp axis must fail at
        pp_forward with a clear error, not a KeyError deep in tracing."""
        cfg = self._cfg(dtype=jnp.float32)
        mesh = mesh_for(2, pp=2)                      # no sp axis
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 16), jnp.int32)
        with pytest.raises(ValueError, match="needs an 'sp' axis"):
            llama.pp_forward(params, tokens, cfg, mesh)

    def test_seq_not_divisible_by_sp_rejected(self):
        cfg = self._cfg(dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, sp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 30), jnp.int32)   # 30 % 4 != 0
        with pytest.raises(ValueError, match="not divisible by sp"):
            llama.pp_forward(params, tokens, cfg, mesh)


class TestPipelinedDecode:
    """pp_generate decodes DIRECTLY from pipeline-staged params (no
    unstacked dense tree): per-stage weights + KV caches, token hidden
    states riding a ppermute ring of stage applications. Token-for-token
    equal to the dense generate, including the sampled path (lockstep
    rng discipline)."""

    def _setup(self, **kw):
        kw.setdefault("pp_stages", 2)
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), dtype=jnp.float32, **kw)
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        return cfg, mesh, params, prompt

    def _dense(self, cfg, params, prompt, **gen_kw):
        from lzy_tpu.models.generate import generate

        return generate(
            dataclasses.replace(cfg, pp_stages=0),
            llama.unstack_pp_params(cfg, params), prompt, **gen_kw)

    def test_greedy_matches_dense_generate(self):
        from lzy_tpu.models.generate import pp_generate

        cfg, mesh, params, prompt = self._setup()
        pp_out = pp_generate(cfg, params, prompt, max_new_tokens=6,
                             mesh=mesh, temperature=0.0)
        dense = self._dense(cfg, params, prompt, max_new_tokens=6,
                            temperature=0.0)
        np.testing.assert_array_equal(np.asarray(pp_out), np.asarray(dense))

    def test_sampled_matches_dense_generate_bit_for_bit(self):
        from lzy_tpu.models.generate import pp_generate

        cfg, mesh, params, prompt = self._setup()
        kw = dict(max_new_tokens=6, temperature=0.8, top_k=50,
                  rng=jax.random.PRNGKey(7))
        pp_out = pp_generate(cfg, params, prompt, mesh=mesh, **kw)
        dense = self._dense(cfg, params, prompt, **kw)
        np.testing.assert_array_equal(np.asarray(pp_out), np.asarray(dense))

    def test_eos_token_freezes_finished_rows(self):
        from lzy_tpu.models.generate import pp_generate

        cfg, mesh, params, prompt = self._setup()
        pp_out = pp_generate(cfg, params, prompt, max_new_tokens=6,
                             mesh=mesh, temperature=0.0, eos_token=3)
        dense = self._dense(cfg, params, prompt, max_new_tokens=6,
                            temperature=0.0, eos_token=3)
        np.testing.assert_array_equal(np.asarray(pp_out), np.asarray(dense))

    def test_decodes_from_live_sharded_train_state(self):
        """The loop users actually run: train on pp×fsdp, then decode
        straight from the LIVE sharded state.params — no device_get, no
        unstack."""
        from lzy_tpu.models.generate import pp_generate

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=256),
                                  pp_stages=2, dtype=jnp.float32)
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"),
            donate=False)
        state = shard_state(TrainState.create(params, tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size)}
        state, _ = step(state, batch)
        prompt = batch["tokens"][:1, :8]
        out = pp_generate(cfg, state.params, prompt, max_new_tokens=4,
                          mesh=mesh, temperature=0.0)
        dense = self._dense(
            cfg, jax.device_get(state.params), prompt,
            max_new_tokens=4, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(dense))

    def test_bf16_sampled_parity(self):
        """The default dtype too: the pipelined tail mirrors the dense
        model's norm/head dtypes exactly, so even bf16 sampling stays
        token-for-token identical."""
        from lzy_tpu.models.generate import pp_generate

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=128),
                                  pp_stages=2)          # bf16 default
        mesh = mesh_for(8, pp=2, fsdp=4)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 128)
        kw = dict(max_new_tokens=3, temperature=0.8,
                  rng=jax.random.PRNGKey(5))
        pp_out = pp_generate(cfg, params, prompt, mesh=mesh, **kw)
        dense = self._dense(cfg, params, prompt, **kw)
        np.testing.assert_array_equal(np.asarray(pp_out), np.asarray(dense))

    def test_untied_head(self):
        from lzy_tpu.models.generate import pp_generate

        cfg, mesh, params, prompt = self._setup(tie_embeddings=False)
        pp_out = pp_generate(cfg, params, prompt, max_new_tokens=4,
                             mesh=mesh, temperature=0.0)
        dense = self._dense(cfg, params, prompt, max_new_tokens=4,
                            temperature=0.0)
        np.testing.assert_array_equal(np.asarray(pp_out), np.asarray(dense))


class TestPpSpEp:
    """The 3-axis composition (VERDICT top-next #7): pipelined
    long-context MoE — stages over pp, ring attention against the manual
    sp axis inside each stage, experts over ep. The MoE aux loss is
    sp-pmeaned inside the pipeline region (each sp rank's routers score
    only their sequence chunk; parallel/pipeline.py replicates one
    consistent value before the manual-region boundary)."""

    def test_three_axis_composition_trains(self):
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            use_ring_attention=True, n_experts=2)
        mesh = mesh_for(8, pp=2, sp=2, ep=2)
        params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
        tx = optax.adamw(1e-2)
        step, shard_state, _ = make_train_step(
            llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
            param_logical_axes=axes, batch_logical_axes=("batch", "seq"))
        state = shard_state(TrainState.create(params, tx))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size)}
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(0.0 < l < 20.0 for l in losses), losses
        assert losses[-1] < losses[0], "3-axis step does not learn"
        assert int(state.step) == 3

    def test_aux_loss_replicated_across_sp(self):
        """The pipeline's aux output must be one consistent scalar, not a
        per-sp-rank partial masquerading as replicated: perturbing which
        sp rank you'd read it from must not exist as a concept — the
        forward value is deterministic and finite."""
        cfg = dataclasses.replace(
            LlamaConfig.tiny(vocab_size=256), pp_stages=2,
            use_ring_attention=True, n_experts=2)
        mesh = mesh_for(8, pp=2, sp=2, ep=2)
        params, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (4, 32), 0, cfg.vocab_size)
        out, aux = llama.pp_forward(params, tokens, cfg, mesh)
        a1 = float(aux)
        out2, aux2 = llama.pp_forward(params, tokens, cfg, mesh)
        assert a1 == float(aux2) and a1 > 0.0
