"""Whiteboards behind IAM (VERDICT r2 #3).

The reference guards every whiteboard call per-tenant
(``WhiteboardService.java:45`` + ``AccessServerInterceptor``). Here the
control plane's whiteboard surface enforces OWNER/READER scoping so that,
over RPC, user B can neither list nor finalize user A's whiteboards.
"""

import dataclasses

import pytest

from lzy_tpu import op, whiteboard
from lzy_tpu.iam import INTERNAL, READER, AuthError
from lzy_tpu.rpc.control import RpcWhiteboardClient
from lzy_tpu.service import InProcessCluster


@pytest.fixture()
def plane(tmp_path):
    c = InProcessCluster(
        db_path=str(tmp_path / "meta.db"),
        storage_uri=f"file://{tmp_path}/storage",
        with_iam=True,
    )
    server = c.serve()
    tokens = {
        "alice": c.iam.create_subject("alice"),
        "bob": c.iam.create_subject("bob"),
        "auditor": c.iam.create_subject("auditor", role=READER),
        "ops": c.iam.create_subject("ops", role=INTERNAL),
    }
    clients = {u: RpcWhiteboardClient(server.address, token=t)
               for u, t in tokens.items()}
    yield c, clients, tokens
    for cl in clients.values():
        cl.close()
    c.shutdown()


def _register_finalized(client, name, tags=()):
    import uuid

    m = client.register(wb_id=f"wb-{name}-{uuid.uuid4().hex[:8]}",
                        name=name, tags=tags)
    client.finalize(m.id, {"metric": {
        "uri": m.base_uri + "/fields/metric", "data_format": "primitive",
        "schema_content": "",
    }})
    return m


class TestWhiteboardIam:
    def test_owner_is_assigned_by_the_plane(self, plane):
        _, clients, _ = plane
        m = clients["alice"].register(wb_id="wb-own", name="own")
        assert m.owner == "alice"

    def test_user_b_cannot_get_or_finalize_user_a_whiteboard(self, plane):
        _, clients, _ = plane
        m = _register_finalized(clients["alice"], "a-board")
        with pytest.raises(AuthError):
            clients["bob"].get(id_=m.id)
        with pytest.raises(AuthError):
            clients["bob"].finalize(m.id, {})
        # alice herself still reads it
        assert clients["alice"].get(id_=m.id).owner == "alice"

    def test_user_b_cannot_list_user_a_whiteboards(self, plane):
        _, clients, _ = plane
        _register_finalized(clients["alice"], "boards", tags=["shared-tag"])
        _register_finalized(clients["bob"], "boards", tags=["shared-tag"])
        alice_sees = clients["alice"].query(name="boards")
        bob_sees = clients["bob"].query(tags=["shared-tag"])
        assert [m.owner for m in alice_sees] == ["alice"]
        assert [m.owner for m in bob_sees] == ["bob"]

    def test_reader_and_internal_see_everything(self, plane):
        _, clients, _ = plane
        _register_finalized(clients["alice"], "boards")
        _register_finalized(clients["bob"], "boards")
        assert len(clients["auditor"].query(name="boards")) == 2
        assert len(clients["ops"].query(name="boards")) == 2
        # but a READER cannot finalize someone else's whiteboard
        m = clients["alice"].register(wb_id="wb-r", name="r-board")
        with pytest.raises(AuthError):
            clients["auditor"].finalize(m.id, {})

    def test_register_cannot_hijack_existing_id(self, plane):
        _, clients, _ = plane
        clients["alice"].register(wb_id="wb-hijack", name="mine")
        with pytest.raises(AuthError, match="owned by another"):
            clients["bob"].register(wb_id="wb-hijack", name="mine-now")
        # alice's own retry of the same id is fine (idempotent re-register)
        again = clients["alice"].register(wb_id="wb-hijack", name="mine")
        assert again.owner == "alice"

    def test_register_cannot_claim_legacy_unowned_board(self, plane):
        """A pre-IAM (unowned) board is a conflict, not a free claim: silent
        takeover would reset its manifest and hand the claimant ownership
        of data they never wrote (ADVICE r3)."""
        c, clients, _ = plane
        # seed an unowned board straight through the index (pre-IAM write)
        c.whiteboard_index.register(wb_id="wb-legacy", name="legacy", tags=())
        with pytest.raises(AuthError, match="unowned"):
            clients["alice"].register(wb_id="wb-legacy", name="legacy")
        # the board is untouched
        m = clients["auditor"].get(id_="wb-legacy")
        assert m.owner == "" and m.name == "legacy"

    def test_duplicate_register_after_finalize_is_a_noop(self, plane):
        """A delayed duplicate register (e.g. a DEADLINE_EXCEEDED retry
        that lands after finalize) replays the manifest instead of
        resetting a FINALIZED board to CREATED (ADVICE r3)."""
        _, clients, _ = plane
        m = _register_finalized(clients["alice"], "dup-final")
        again = clients["alice"].register(wb_id=m.id, name="dup-final")
        assert again.status == "FINALIZED"
        assert "metric" in again.fields

    def test_worker_tokens_rejected(self, plane):
        cluster, _, _ = plane
        from lzy_tpu.iam import WORKER

        worker_token = cluster.iam.create_subject("vm/test-vm", kind=WORKER)
        client = RpcWhiteboardClient(cluster.rpc_server.address,
                                     token=worker_token)
        try:
            with pytest.raises(AuthError, match="worker credentials"):
                client.query()
        finally:
            client.close()

    def test_anonymous_rejected_when_iam_on(self, plane):
        cluster, _, _ = plane
        client = RpcWhiteboardClient(cluster.rpc_server.address)
        try:
            with pytest.raises(AuthError):
                client.register(wb_id="wb-anon", name="anon")
        finally:
            client.close()


@whiteboard("iam_e2e_result")
@dataclasses.dataclass
class Result:
    value: int


@op
def produce(x: int) -> int:
    return x * 3


class TestWorkflowWhiteboardOverRpc:
    def test_workflow_whiteboard_rides_the_guarded_surface(self, plane):
        """The SDK path end to end: Lzy wired with a remote whiteboard
        client — create_whiteboard/finalize/query all via the control
        plane, with ownership from the token."""
        cluster, clients, tokens = plane
        lzy = cluster.lzy(user="alice", token=tokens["alice"])
        lzy._whiteboard_client = clients["alice"]
        with lzy.workflow("wb-wf") as wf:
            wb = wf.create_whiteboard(Result, tags=["iam-e2e"])
            wb.value = produce(7)
        found = clients["alice"].query(tags=["iam-e2e"])
        assert len(found) == 1 and found[0].owner == "alice"
        # bob's view of the same tag is empty
        assert clients["bob"].query(tags=["iam-e2e"]) == []
