"""A fully-cached multi-op graph skips every op on the second run (reference
scenario pylzy/tests/scenarios/fully_cached_graph; server-side CheckCache drops
satisfied ops before execution)."""
from tests.scenarios._base import make_lzy

from lzy_tpu import op

RUNS = []


@op(cache=True, version="1.0")
def square(x: int) -> int:
    RUNS.append(("square", x))
    return x * x


@op(cache=True, version="1.0")
def add(a: int, b: int) -> int:
    RUNS.append(("add", a, b))
    return a + b


def main():
    cluster, lzy = make_lzy()
    try:
        for i in range(2):
            with lzy.workflow("full-cache"):
                total = add(square(3), square(4))
                print(f"run {i}: {int(total)}")
        print(f"executions: {len(RUNS)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
