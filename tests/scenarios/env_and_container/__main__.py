"""Worker-side environment realization + container-boundary execution
(round-2 additions: execution-env parity)."""
from tests.scenarios._base import make_lzy

from lzy_tpu import op
from lzy_tpu.env import DockerContainer, EnvBuildError, ManualPythonEnv


@op
def plain_add(a: int, b: int) -> int:
    return a + b


@op
def boxed_mul(a: int, b: int) -> int:
    return a * b


def main():
    import sys

    from lzy_tpu.env import LocalProcessRuntime
    from lzy_tpu.service import InProcessCluster

    cluster = InProcessCluster(storage_uri="file:///tmp/lzy-scn-env",
                               container_runtime=LocalProcessRuntime())
    lzy = cluster.lzy()
    try:
        pyver = "%d.%d" % sys.version_info[:2]
        # shared-interpreter workers VALIDATE the captured env and fail fast
        # on a mismatch (the silent unpickle-time failure mode is gone)
        bad_env = ManualPythonEnv(python_version=pyver,
                                  packages={"lzy-no-such-pkg": "1.0"})
        try:
            with lzy.workflow("env-validate"):
                int(plain_add.with_python_env(bad_env)(1, 2))
        except Exception as e:
            cause = e.__cause__ or e
            print("env conflict detected:",
                  isinstance(cause, EnvBuildError))

        # containerized op runs through the exchange-dir boundary
        with lzy.workflow("container"):
            r = boxed_mul.with_container(DockerContainer(image="any:img"))(6, 7)
            print("container result:", int(r))
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
