"""Gang-scheduled TPU op sees its slice context (TPU-build addition)."""
from tests.scenarios._base import make_lzy
from lzy_tpu import op
from lzy_tpu.service.worker import current_gang


@op(tpu="v5e-16")
def slice_info() -> dict:
    g = current_gang()
    return {"rank": g["rank"], "hosts": g["size"]}


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("gang"):
            info = slice_info()
            print(f"rank: {info['rank']}")
            print(f"hosts: {info['hosts']}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
