"""Cached op executes once across workflow runs (reference scenarios
repeated_{execs,ops}_use_cache / fully_cached_graph)."""
from tests.scenarios._base import make_lzy
from lzy_tpu import op

RUNS = []


@op(cache=True, version="1.0")
def expensive(x: int) -> int:
    RUNS.append(x)
    return x * x


def main():
    cluster, lzy = make_lzy()
    try:
        for i in range(3):
            with lzy.workflow("cached"):
                print(f"run {i}: {int(expensive(6))}")
        print(f"executions: {len(RUNS)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
