"""Large array values across ops (reference scenario large_input_output —
a multi-million-row frame through one op; here a 64 MiB float32 array through
the binary pytree format and the multipart-capable storage path)."""
import numpy as np

from tests.scenarios._base import make_lzy

from lzy_tpu import op


@op
def normalize(a: np.ndarray) -> np.ndarray:
    return (a - a.mean()) / (a.std() + 1e-8)


def main():
    cluster, lzy = make_lzy()
    try:
        rng = np.random.default_rng(42)
        big = rng.standard_normal((4096, 4096), dtype=np.float32)  # 64 MiB
        with lzy.workflow("large-io"):
            out = normalize(big)
            print(f"size_input: {big.nbytes}")
            print(f"size_output: {out.nbytes}")
            print(f"mean_is_zero: {bool(abs(float(out.mean())) < 1e-5)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
