"""File-typed values across ops (reference file_test scenario)."""
import os

from tests.scenarios._base import make_lzy
from lzy_tpu import File, op


@op
def write_file(text: str) -> File:
    import tempfile

    fd = tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False)
    fd.write(text)
    fd.close()
    return File(fd.name)


@op
def read_file(f: File) -> str:
    return f.read_text()


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("files"):
            f = write_file("file content here")
            text = read_file(f)
            print(f"roundtrip: {str(text)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
