"""An op that re-spawns its own __main__ as a subprocess (reference scenario
subprocess_with_startup: PyTorch-Lightning-style self-replication must not
re-enter the workflow machinery or double-write outputs)."""
import os
import subprocess
import sys

SUBPROCESS_ENV_VAR = "LZY_SCENARIO_SUBPROCESS"

if os.getenv(SUBPROCESS_ENV_VAR):
    # the replicated child takes the guard path: no cluster, no workflow —
    # exactly the reference's main-PID guard semantics
    print("hello from subprocess", flush=True)
    sys.exit(0)

from tests.scenarios._base import make_lzy  # noqa: E402

from lzy_tpu import op  # noqa: E402


@op
def run(num: int) -> int:
    print("hello from main process", flush=True)
    env = os.environ.copy()
    env[SUBPROCESS_ENV_VAR] = "1"
    import __main__

    if getattr(__main__, "__spec__", None) is not None:
        cmd = [sys.executable, "-m", __main__.__spec__.name]
    else:
        cmd = [sys.executable, os.path.abspath(sys.argv[0])]
    sub = subprocess.run(cmd, env=env, stdout=subprocess.PIPE, text=True)
    print(sub.stdout, end="", flush=True)
    print(f"subprocess exit code: {sub.returncode}", flush=True)
    return num * 2


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("subprocess-wf"):
            res = run(21)
            print(f"main process result: {int(res)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
