"""Transparent remote fitting of a third-party estimator (reference scenarios
catboost_integration_cpu/gpu: `fit(provisioning=...)` spawns a one-op
workflow; here via the generic remote_fit + @extend injections)."""
from tests.scenarios._base import make_lzy

from lzy_tpu.injections import extend, remote_fit


class TinyRegressor:
    """Stand-in for catboost/sklearn: mean predictor with sklearn's fit(X,y)
    shape."""

    def __init__(self):
        self.mean_ = None

    def fit(self, X, y):  # noqa: N803 — sklearn convention
        self.mean_ = sum(y) / len(y)
        return self

    def predict(self, X):  # noqa: N803
        return [self.mean_] * len(X)


@extend(TinyRegressor)
def describe(self) -> str:
    return f"mean={self.mean_:.1f}"


def main():
    cluster, lzy = make_lzy()
    try:
        fitted = remote_fit(TinyRegressor(), [[1], [2], [3]], [10, 20, 30],
                            lzy=lzy)
        print(f"prediction: {fitted.predict([[4]])[0]:.1f}")
        print(f"extended: {fitted.describe()}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
