"""Shared scenario bootstrap: an in-process cluster + SDK facade, stdout-only
deterministic output (scenario tier modeled on the reference's
pylzy/tests/scenarios/<name> + expected_stdout diffing, SURVEY.md §4.4)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

from lzy_tpu.utils.compat import request_cpu_devices

jax.config.update("jax_platforms", "cpu")
request_cpu_devices(8)


def make_lzy():
    from lzy_tpu.service import InProcessCluster

    cluster = InProcessCluster(storage_uri="mem://scenario")
    return cluster, cluster.lzy()
