"""Diamond dataflow graph: two parallel branches joined (reference scenario
pylzy/tests/scenarios/complex_graph)."""
from tests.scenarios._base import make_lzy
from lzy_tpu import op


@op
def source() -> int:
    return 10


@op
def left(x: int) -> int:
    return x * 2


@op
def right(x: int) -> int:
    return x + 5


@op
def join(a: int, b: int) -> int:
    return a + b


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("complex"):
            s = source()
            result = join(left(s), right(s))
            print(f"left branch: {int(left(s))}")
            print(f"join result: {int(result)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
