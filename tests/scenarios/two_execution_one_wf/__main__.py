"""Two sequential executions of the same named workflow (reference scenario
pylzy/tests/scenarios/two_execution_one_wf)."""
from tests.scenarios._base import make_lzy

from lzy_tpu import op


@op
def ret42() -> int:
    return 42


@op
def ret13() -> int:
    return 13


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("wf"):
            print(int(ret42()))
        with lzy.workflow("wf"):
            print(int(ret13()))
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
