"""Pipelined training through the workflow layer (TPU-build addition):
an @op builds a pp×fsdp mesh on the worker's devices, trains the pp-
staged Llama a few steps, then greedy-decodes DIRECTLY from the staged
params with pp_generate (each rank keeps only its stage's weights + KV
cache) — the full pp lifecycle riding the ordinary op/channel/snapshot
path. unstack_pp_params remains the dense-tree escape hatch."""
import dataclasses

from tests.scenarios._base import make_lzy
from lzy_tpu import op


@op
def train_pipelined(steps: int) -> dict:
    import jax
    import optax

    from lzy_tpu.models import llama
    from lzy_tpu.models.llama import LlamaConfig
    from lzy_tpu.parallel import TrainState, make_train_step, mesh_for

    cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=128), pp_stages=2)
    mesh = mesh_for(8, pp=2, fsdp=4)
    params, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adamw(1e-2)
    step, shard_state, _ = make_train_step(
        llama.make_loss_fn(cfg, mesh), tx, mesh=mesh,
        param_logical_axes=axes, batch_logical_axes=("batch", "seq"))
    state = shard_state(TrainState.create(params, tx))
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)}
    first = last = None
    for _ in range(steps):
        state, metrics = step(state, batch)
        last = float(metrics["loss"])
        first = first if first is not None else last
    # decode straight from the pipeline-staged params
    from lzy_tpu.models.generate import pp_generate

    prompt = batch["tokens"][:1, :8]
    out = pp_generate(cfg, jax.device_get(state.params), prompt,
                      max_new_tokens=1, mesh=mesh, temperature=0.0)
    next_token = int(out[0, -1])
    return {"improved": last < first, "next_token_in_vocab":
            0 <= next_token < cfg.vocab_size}


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("pp-training"):
            out = train_pipelined(4)
            print(f"improved: {out['improved']}")
            print(f"decoded in vocab: {out['next_token_in_vocab']}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
