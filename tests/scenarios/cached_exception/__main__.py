"""Exceptions are never cached: a failed cacheable op re-executes on the next
run (reference scenario pylzy/tests/scenarios/cached_exception — the op body
prints twice)."""
from tests.scenarios._base import make_lzy

from lzy_tpu import op
from lzy_tpu.core.workflow import RemoteCallError

RUNS = []


@op(cache=True, version="1.0")
def raises(x: int) -> int:
    RUNS.append(x)
    raise ValueError("always fails")


def main():
    cluster, lzy = make_lzy()
    try:
        for _ in range(2):
            try:
                with lzy.workflow("cached-exc"):
                    raises(5)
            except RemoteCallError as e:
                print(f"caught: {type(e.__cause__).__name__}")
        print(f"executions: {len(RUNS)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
