"""Nested workflows: an op hosts its own inner workflow (reference scenario
pylzy/tests/scenarios/nested_workflows — the inner graph is launched from
inside an op's execution context, not from the outer workflow's thread)."""
from tests.scenarios._base import make_lzy

from lzy_tpu import op

CLUSTER = None


@op
def double(x: int) -> int:
    return 2 * x


@op
def run_inner(x: int) -> int:
    # runs on a worker thread: entering a workflow here is legal because the
    # active-workflow slot is per execution context, exactly like the
    # reference where the inner graph runs inside the op's own process
    inner = CLUSTER.lzy()
    with inner.workflow("inner"):
        doubled = int(double(x))
    return doubled + 1


def main():
    global CLUSTER
    cluster, lzy = make_lzy()
    CLUSTER = cluster
    try:
        with lzy.workflow("outer"):
            r = run_inner(20)
            print(f"outer got: {int(r)}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
