"""Train a tiny Llama inside a TPU op, then generate from the returned params
with the KV-cache decoder — the train→serve loop in one workflow."""
import numpy as np

from tests.scenarios._base import make_lzy
from lzy_tpu import op


@op(tpu="v5e-8")
def train_tiny() -> dict:
    import jax
    import optax

    from lzy_tpu.models import llama, unbox
    from lzy_tpu.parallel import TrainState, fsdp_mesh, make_train_step

    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    mesh = fsdp_mesh()
    boxed, axes = llama.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(1e-3)
    step, shard_state, _ = make_train_step(
        llama.make_loss_fn(cfg), tx, mesh=mesh, param_logical_axes=axes,
        batch_logical_axes=("batch", "seq"))
    state = shard_state(TrainState.create(unbox(boxed), tx))
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)}
    first = last = None
    for _ in range(4):
        state, m = step(state, batch)
        last = float(m["loss"])
        if first is None:
            first = last
    return {"params": jax.device_get(state.params),
            "improved": bool(last < first)}


@op
def sample(result: dict) -> str:
    import jax
    import jax.numpy as jnp

    from lzy_tpu.models import LlamaConfig, generate

    cfg = LlamaConfig.tiny(vocab_size=64)
    out = generate(cfg, result["params"], jnp.array([[1, 2, 3]], jnp.int32),
                   max_new_tokens=4)
    return f"{out.shape[1]} tokens, loss improved: {result['improved']}"


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("train-and-generate"):
            print(f"generated: {str(sample(train_tiny()))}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
