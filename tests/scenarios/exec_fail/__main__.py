"""Remote op failure surfaces as the original exception (reference scenario
exec_fail + exception_serialize)."""
from tests.scenarios._base import make_lzy
from lzy_tpu import op
from lzy_tpu.core.workflow import RemoteCallError


@op
def broken(x: int) -> int:
    raise KeyError(f"missing-{x}")


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("failing"):
            r = broken(7)
            print(r + 1)
    except RemoteCallError as e:
        cause = e.__cause__
        print(f"caught: {type(cause).__name__} {cause}")
        has_tb = any("remote traceback" in n for n in getattr(cause, "__notes__", []))
        print(f"remote traceback attached: {has_tb}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
