"""A user-registered serializer carries a custom type through ops (reference
scenario pylzy/tests/scenarios/custom_serializer)."""
from typing import BinaryIO, Optional, Type

from tests.scenarios._base import make_lzy

from lzy_tpu import op
from lzy_tpu.serialization import Serializer

FORMATS_USED = []


class Point:
    def __init__(self, x: int, y: int):
        self.x = x
        self.y = y


class PointSerializer(Serializer):
    """Text format instead of pickle — proves the registry dispatched here."""

    def format_name(self) -> str:
        return "point-csv"

    def supports_type(self, typ: Type) -> bool:
        return typ is Point

    def serialize(self, obj: Point, dest: BinaryIO) -> None:
        FORMATS_USED.append(self.format_name())
        dest.write(f"{obj.x},{obj.y}".encode())

    def deserialize(self, src: BinaryIO, typ: Optional[Type] = None) -> Point:
        x, y = src.read().decode().split(",")
        return Point(int(x), int(y))


@op
def shift(p: Point) -> Point:
    return Point(p.x + 10, p.y + 10)


def main():
    cluster, lzy = make_lzy()
    lzy.serializer_registry.register(PointSerializer(), priority=0)
    try:
        with lzy.workflow("custom-ser"):
            q = shift(Point(1, 2))
            print(f"shifted: {q.x} {q.y}")
        print(f"custom format used: {'point-csv' in FORMATS_USED}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
