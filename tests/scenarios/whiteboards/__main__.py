"""Whiteboard write/finalize/query (reference whiteboards scenario)."""
import dataclasses

from tests.scenarios._base import make_lzy
from lzy_tpu import op, whiteboard


@whiteboard("scenario_model")
@dataclasses.dataclass
class Model:
    accuracy: float
    weights: dict


@op
def train() -> dict:
    return {"w0": 0.5, "w1": -0.25}


def main():
    cluster, lzy = make_lzy()
    try:
        with lzy.workflow("wb") as wf:
            wb = wf.create_whiteboard(Model, tags=["best", "v1"])
            wb.weights = train()
            wb.accuracy = 0.93

        found = lzy.whiteboards(name="scenario_model", tags=["best"])
        print(f"found: {len(found)}")
        print(f"accuracy: {found[0].accuracy}")
        print(f"weights: {sorted(found[0].weights.items())}")
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
