"""Checkpoint + resumable data: a 'crash' mid-training resumes at the exact
next batch with the restored model state, matching an uninterrupted run
bit for bit (round-2 additions)."""
import numpy as np

from lzy_tpu.data import array_source
from lzy_tpu.parallel import CheckpointManager
from lzy_tpu.storage.mem import MemStorageClient


def main():
    data = {"x": np.arange(64, dtype=np.float32)}
    mgr = CheckpointManager(MemStorageClient(), "mem://scn-ckpt", "run")

    # train 5 batches, checkpoint model + data position, then keep going —
    # the uninterrupted run is the ground truth
    src = array_source(data, batch_size=8, seed=3)
    it = iter(src)
    w = 0.0
    for _ in range(5):
        w += float(next(it)["x"].sum())
    mgr.save({"w": np.float32(w)}, 5, data_state=src.state())
    truth = w
    for _ in range(3):
        truth += float(next(it)["x"].sum())

    # "crashed" process: restore model AND data position, train the same 3
    restored = float(np.asarray(mgr.restore()["w"]))
    resumed = array_source(data, batch_size=8, seed=3,
                           state=mgr.data_state())
    rit = iter(resumed)
    w2 = restored
    for _ in range(3):
        w2 += float(next(rit)["x"].sum())

    print("resume step:", mgr.latest_step())
    print("resumed equals uninterrupted:", w2 == truth)


if __name__ == "__main__":
    main()
