"""Multi-tenant SLO serving: WFQ, rate limits, quotas, chunked prefill.

The isolation contract under test: whatever an aggressor tenant does —
saturating its rate limit, flooding the queue, dragging 100+-token
prompts through prefill, pinning KV blocks up to its quota — a victim
tenant's requests still admit, reach their first token within a bounded
number of engine rounds, and decode BIT-IDENTICALLY to an uncontended
``generate()`` run. Engine tests drive ``step()`` synchronously so every
fairness/interleaving assertion is deterministic (counted in scheduling
rounds, not wall time); the gateway test layers the token-bucket front
on top.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.chaos.invariants import audit_engine
from lzy_tpu.gateway import GatewayService, PrefixAffinityRouter, ReplicaFleet
from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import (
    AdmissionError, InferenceEngine, PagedInferenceEngine, PromptTooLong,
    QuotaExceeded, Request, RequestQueue, SloLimiter, TenantPolicy,
    TenantTable, TokenBucket)

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle_tokens(cfg, params, prompt_ids, n, **kw):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n, **kw)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _req(tenant="default", priority=None, cost=10):
    return Request([1] * (cost - 4), 4, tenant=tenant, priority=priority)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# token buckets


class TestTokenBucket:
    def test_burst_then_rate(self):
        clock = FakeClock()
        b = TokenBucket(10.0, 20.0, clock=clock)
        for _ in range(20):
            assert b.try_take(1) is None        # the full burst passes
        wait = b.try_take(1)
        assert wait == pytest.approx(0.1)       # 1 token at 10/s
        clock.advance(0.1)
        assert b.try_take(1) is None
        clock.advance(10.0)
        assert b.level() == pytest.approx(20.0)  # capped at burst

    def test_oversize_take_runs_a_debt(self):
        clock = FakeClock()
        b = TokenBucket(100.0, 200.0, clock=clock)
        # a single take larger than the burst is allowed from a full
        # bucket (a long prompt is not a hard cap) but drives the level
        # negative: the tenant then waits out the debt at its rate
        assert b.try_take(500.0) is None
        assert b.level() == pytest.approx(-300.0)
        wait = b.try_take(1.0)
        assert wait == pytest.approx((1 + 300) / 100.0)
        clock.advance(3.02)
        assert b.try_take(1.0) is None

    def test_give_back_refunds(self):
        clock = FakeClock()
        b = TokenBucket(1.0, 2.0, clock=clock)
        assert b.try_take(2) is None
        assert b.try_take(1) is not None
        b.give_back(2)
        assert b.try_take(2) is None


class TestPolicyTable:
    def test_priority_maps_to_weight_and_only_downgrades(self):
        p = TenantPolicy(tenant="t", priority=0)
        assert p.effective_weight() == 4.0
        assert p.effective_priority(None) == 0
        # a client may volunteer DOWN to batch tier, never up
        assert p.effective_priority(2) == 2
        low = TenantPolicy(tenant="t", priority=2)
        assert low.effective_priority(0) == 2
        assert low.effective_weight(0) == 1.0

    def test_explicit_weight_is_a_ceiling_under_downgrade(self):
        # an operator-throttled weight must not be ESCAPABLE by a client
        # volunteering for a lower tier whose tier weight is larger
        throttled = TenantPolicy(tenant="t", priority=1, weight=0.5)
        assert throttled.effective_weight() == 0.5
        assert throttled.effective_weight(2) == 0.5      # not tier 2's 1.0
        # a downgrade may still SHRINK a generous weight to the tier's
        boosted = TenantPolicy(tenant="t", priority=0, weight=8.0)
        assert boosted.effective_weight() == 8.0
        assert boosted.effective_weight(2) == 1.0
        # and a requested upgrade never dislodges the configured weight
        assert throttled.effective_weight(0) == 0.5

    def test_resolve_unknown_tenant_gets_default(self):
        table = TenantTable(default=TenantPolicy(requests_per_s=5.0))
        p = table.resolve("newcomer")
        assert p.tenant == "newcomer" and p.requests_per_s == 5.0
        table.set_policy(TenantPolicy(tenant="vip", priority=0))
        assert table.resolve("vip").priority == 0

    def test_from_doc_rejects_unknown_fields(self):
        with pytest.raises(ValueError, match="unknown policy fields"):
            TenantTable.from_doc({"a": {"requets_per_s": 3}})
        table = TenantTable.from_doc(
            {"a": {"priority": 0, "kv_block_quota": 8}})
        assert table.resolve("a").kv_block_quota == 8


# ---------------------------------------------------------------------------
# the WFQ request queue (no model needed)


class TestWfqQueue:
    def test_single_tenant_is_fifo(self):
        q = RequestQueue(max_depth=16)
        reqs = [_req() for _ in range(6)]
        for r in reqs:
            q.submit(r)
        assert [q.pop() for _ in range(6)] == reqs

    def test_weighted_interleave_favors_high_tier(self):
        table = TenantTable()
        table.set_policy(TenantPolicy(tenant="hi", priority=0))   # w=4
        table.set_policy(TenantPolicy(tenant="lo", priority=2))   # w=1
        q = RequestQueue(max_depth=32, policies=table)
        for _ in range(8):
            q.submit(_req("hi"))
        for _ in range(8):
            q.submit(_req("lo"))
        first8 = [q.pop().tenant for _ in range(8)]
        # 4:1 weights -> the first window is dominated by the high tier
        assert first8.count("hi") >= 6
        # nothing is lost: all 16 drain
        assert sum(1 for _ in range(8) if q.pop() is not None) == 8

    def test_starved_tenant_ages_to_front(self):
        table = TenantTable()
        table.set_policy(TenantPolicy(tenant="heavy", priority=0))
        table.set_policy(TenantPolicy(tenant="late", priority=2))
        q = RequestQueue(max_depth=64, policies=table)
        for _ in range(20):
            q.submit(_req("heavy"))
        for _ in range(10):      # advance virtual time
            q.pop()
        late = _req("late")
        q.submit(late)
        # despite the worst weight and 10 queued heavy requests, the
        # newcomer's start tag clamps to the advanced virtual time: it
        # pops within a handful of dispatches (bounded by the weight
        # ratio), not after the backlog
        pops = [q.pop() for _ in range(5)]
        assert late in pops

    def test_per_tenant_cap_sheds_only_that_tenant(self):
        table = TenantTable(default=TenantPolicy(max_queued=2))
        q = RequestQueue(max_depth=64, policies=table)
        q.submit(_req("agg"))
        q.submit(_req("agg"))
        with pytest.raises(QuotaExceeded) as ei:
            q.submit(_req("agg"))
        assert ei.value.tenant == "agg"
        assert ei.value.reason == "max_queued"
        assert ei.value.retry_after_s is not None
        assert isinstance(ei.value, AdmissionError)
        # the victim is untouched by the aggressor's cap
        q.submit(_req("vic"))
        assert q.depth_of("vic") == 1

    def test_global_cap_still_applies(self):
        q = RequestQueue(max_depth=2)
        q.submit(_req("a"))
        q.submit(_req("b"))
        with pytest.raises(AdmissionError) as ei:
            q.submit(_req("c"))
        assert not isinstance(ei.value, QuotaExceeded)
        assert ei.value.retry_after_s is not None

    def test_peek_pins_the_head_across_cross_tenant_submits(self):
        table = TenantTable()
        table.set_policy(TenantPolicy(tenant="lo", priority=2))
        table.set_policy(TenantPolicy(tenant="hi", priority=0))
        q = RequestQueue(max_depth=8, policies=table)
        lo = _req("lo")
        q.submit(lo)
        assert q.peek() is lo
        q.submit(_req("hi"))     # earlier virtual finish than lo's
        # the peeked head is pinned: budget-then-commit admission must
        # pop what it budgeted for
        assert q.pop() is lo

    def test_candidates_order_and_pop_request(self):
        table = TenantTable()
        table.set_policy(TenantPolicy(tenant="hi", priority=0))
        q = RequestQueue(max_depth=8, policies=table)
        a = _req("std")
        b = _req("hi")
        q.submit(a)
        q.submit(b)
        cands = q.candidates()
        assert set(cands) == {a, b}
        assert q.pop_request(cands[-1])
        assert not q.pop_request(cands[-1])     # already removed
        assert q.pop() is cands[0]

    def test_reap_dead_spans_tenants(self):
        q = RequestQueue(max_depth=8)
        a, b = _req("a"), _req("b")
        q.submit(a)
        q.submit(b)
        a.cancel()
        b.cancel()
        assert set(q.reap_dead()) == {a, b}
        assert q.depth() == 0

    def test_finish_tags_swept_for_drained_tenants(self):
        # with IAM on, tenant ids are subject ids: the virtual-time tag
        # map must stay bounded by ACTIVE tenants, not by every tenant
        # ever seen. Tags are swept once the clock passes them, so after
        # enough foreground traffic the drained tenants are gone.
        q = RequestQueue(max_depth=256)
        for i in range(20):
            q.submit(_req(f"one-shot-{i}"))
        while q.pop() is not None:
            pass
        for _ in range(4):          # ongoing traffic advances vtime
            q.submit(_req("steady", cost=200))
        while q.pop() is not None:
            pass
        assert len(q._finish_tag) <= 1, sorted(q._finish_tag)


# ---------------------------------------------------------------------------
# the SLO limiter (rate buckets at the serving front)


class TestSloLimiter:
    def test_aggressor_saturates_without_touching_victim(self):
        clock = FakeClock()
        table = TenantTable(default=TenantPolicy(requests_per_s=2.0,
                                                 burst_s=1.0))
        slo = SloLimiter(table, clock=clock)
        slo.admit("agg", 4)
        slo.admit("agg", 4)
        with pytest.raises(QuotaExceeded) as ei:
            slo.admit("agg", 4)
        assert ei.value.tenant == "agg"
        assert ei.value.reason == "requests_per_s"
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        assert f"retry_after_s={ei.value.retry_after_s:.2f}" in str(ei.value)
        # the victim's buckets are its own
        slo.admit("vic", 4)
        clock.advance(1.0)
        slo.admit("agg", 4)     # refilled on the aggressor's clock

    def test_token_refusal_refunds_the_request_take(self):
        clock = FakeClock()
        table = TenantTable(default=TenantPolicy(
            requests_per_s=100.0, prompt_tokens_per_s=10.0, burst_s=1.0))
        slo = SloLimiter(table, clock=clock)
        slo.admit("t", 1000)     # oversize passes ONCE on a full bucket
        with pytest.raises(QuotaExceeded) as ei:
            slo.admit("t", 5)    # then the debt refuses further tokens
        assert ei.value.reason == "prompt_tokens_per_s"
        # ...but the refusal refunded its request-bucket take: only the
        # one admitted request was ever charged there
        req_bucket = slo._buckets["t"][0]
        assert req_bucket.level() == pytest.approx(99.0)

    def test_enforced_backoff_punishes_hammering(self):
        """Load-harness finding (ISSUE 13): with an ADVISORY hint, a
        client polling the bucket every few ms grabs each refilled token
        ahead of everyone who honored the hint — misbehavior won
        throughput. With ``enforce_backoff=True`` an early return is
        refused AND extends the tenant's window, so hammering starves
        itself while the hint-honoring schedule is served on time."""
        clock = FakeClock()
        table = TenantTable(default=TenantPolicy(requests_per_s=1.0,
                                                 burst_s=1.0))
        slo = SloLimiter(table, clock=clock, enforce_backoff=True,
                         backoff_step_s=0.05)
        slo.admit("ham", 4)
        with pytest.raises(QuotaExceeded) as ei:
            slo.admit("ham", 4)
        hint = ei.value.retry_after_s
        assert hint and hint > 0
        # hammer: returns every 10 ms ignoring the hint — every poll is
        # refused with reason="backoff" and pushes the window out, so
        # even past the ORIGINAL hint the tenant stays refused
        polls = 0
        for _ in range(200):
            clock.advance(0.01)
            with pytest.raises(QuotaExceeded) as ei2:
                slo.admit("ham", 4)
            polls += 1
            if clock.t - 1000.0 > hint + 0.5:
                break
        assert ei2.value.reason == "backoff"
        assert polls > 10
        # a polite tenant with the same policy: refused once, waits out
        # ITS hint, admitted on schedule
        slo.admit("pol", 4)
        with pytest.raises(QuotaExceeded) as ei3:
            slo.admit("pol", 4)
        clock.advance(ei3.value.retry_after_s + 0.001)
        slo.admit("pol", 4)     # honoring the hint still wins service

    def test_backoff_enforcement_off_by_default(self):
        clock = FakeClock()
        table = TenantTable(default=TenantPolicy(requests_per_s=1.0,
                                                 burst_s=1.0))
        slo = SloLimiter(table, clock=clock)
        slo.admit("t", 4)
        with pytest.raises(QuotaExceeded):
            slo.admit("t", 4)
        clock.advance(1.0)      # refilled: advisory mode admits again
        slo.admit("t", 4)


# ---------------------------------------------------------------------------
# chunked prefill: decode interleave + bit identity


class TestChunkedPrefill:
    @pytest.mark.parametrize("paged", [False, True])
    def test_long_prompt_interleaves_with_decode(self, tiny_model, paged):
        """A resident request keeps emitting tokens BETWEEN a long
        prompt's prefill rounds — the decode-steps-between-prefill-chunks
        assertion — and both outputs stay bit-identical to the oracle."""
        cfg, params = tiny_model
        kw = dict(slots=2, prefill_chunk=16, prefill_budget=16)
        if paged:
            engine = PagedInferenceEngine(cfg, params, page_size=PAGE, **kw)
        else:
            engine = InferenceEngine(cfg, params, **kw)
        short = [3, 5, 7]
        long = [(7 * i) % 60 + 1 for i in range(120)]
        r_short = engine.submit(short, max_new_tokens=40)
        engine.step()                       # short resident and decoding
        assert len(r_short.tokens) >= 1
        r_long = engine.submit(long, max_new_tokens=8)
        engine.step()                       # stage + first budget round
        assert engine._prefill_jobs
        interleaved = 0
        rounds = 1
        while engine._prefill_jobs and rounds < 50:
            before = len(r_short.tokens)
            done_before = engine._prefill_jobs[0].done
            engine.step()
            rounds += 1
            if engine._prefill_jobs:
                # bounded advance per round: at most the budget (one
                # chunk here) of prompt tokens moved
                assert engine._prefill_jobs[0].done - done_before <= 16
            if len(r_short.tokens) > before:
                interleaved += 1
        # the 120-token prompt must have taken several rounds, and the
        # resident stream advanced during (not after) them
        assert rounds >= 6
        assert interleaved >= 5
        while not (r_short.done and r_long.done):
            engine.step()
        assert r_short.tokens == _oracle_tokens(cfg, params, short, 40)
        assert r_long.tokens == _oracle_tokens(cfg, params, long, 8)
        if paged:
            audit_engine(engine)
        engine.close()

    def test_victim_ttft_bounded_in_rounds(self, tiny_model):
        """A short prompt staged behind a long one reaches its first
        token in O(1) engine rounds (round-robin job advance), NOT after
        the aggressor's whole prefill — the structural TTFT bound."""
        cfg, params = tiny_model
        engine = PagedInferenceEngine(
            cfg, params, slots=2, page_size=PAGE, prefill_chunk=16,
            prefill_budget=16)
        aggressor = [(3 * i) % 50 + 1 for i in range(160)]  # 10 rounds
        victim = [9, 2, 4]
        r_agg = engine.submit(aggressor, max_new_tokens=4)
        engine.step()       # aggressor staged + first chunk
        r_vic = engine.submit(victim, max_new_tokens=6)
        rounds_to_first = 0
        while r_vic.first_token_at is None:
            engine.step()
            rounds_to_first += 1
            assert rounds_to_first < 8, \
                "victim TTFT grew with the aggressor's prompt length"
        # victim decodes bit-identically while the aggressor still
        # prefills; aggressor finishes later, also bit-identical
        while not (r_vic.done and r_agg.done):
            engine.step()
        assert r_vic.tokens == _oracle_tokens(cfg, params, victim, 6)
        assert r_agg.tokens == _oracle_tokens(cfg, params, aggressor, 4)
        audit_engine(engine)
        engine.close()

    def test_prefix_reuse_still_bit_identical_when_chunked(self, tiny_model):
        cfg, params = tiny_model
        engine = PagedInferenceEngine(
            cfg, params, slots=2, page_size=PAGE, prefill_chunk=16,
            prefill_budget=16)
        header = list(range(1, 3 * PAGE + 1))
        p1 = header + [40]
        p2 = header + [41, 42]
        r1 = engine.submit(p1, max_new_tokens=6)
        while not r1.done:
            engine.step()
        saved_before = engine.kv.hit_tokens
        r2 = engine.submit(p2, max_new_tokens=6)
        while not r2.done:
            engine.step()
        assert engine.kv.hit_tokens > saved_before      # prefix was reused
        assert r1.tokens == _oracle_tokens(cfg, params, p1, 6)
        assert r2.tokens == _oracle_tokens(cfg, params, p2, 6)
        audit_engine(engine)
        engine.close()

    def test_cancel_mid_prefill_releases_staged_blocks(self, tiny_model):
        cfg, params = tiny_model
        engine = PagedInferenceEngine(
            cfg, params, slots=2, page_size=PAGE, prefill_chunk=16,
            prefill_budget=16)
        free0 = engine.kv.pool.free_count()
        r = engine.submit([(5 * i) % 60 + 1 for i in range(120)],
                          max_new_tokens=4)
        engine.step()                      # staged, first chunk run
        assert engine._prefill_jobs
        r.cancel()
        engine.step()
        assert not engine._prefill_jobs
        assert r.status == "cancelled"
        assert engine.kv.pool.free_count() == free0
        audit_engine(engine)
        engine.close()


# ---------------------------------------------------------------------------
# per-tenant KV quotas (paged admission)


class TestKvQuota:
    def test_quota_skips_tenant_without_blocking_others(self, tiny_model):
        cfg, params = tiny_model
        table = TenantTable()
        # agg may hold at most 3 blocks (= 24 tokens incl. decode room)
        table.set_policy(TenantPolicy(tenant="agg", kv_block_quota=3))
        engine = PagedInferenceEngine(
            cfg, params, slots=3, page_size=PAGE, prefill_chunk=16,
            tenants=table)
        a1 = engine.submit([1] * 17, max_new_tokens=4, tenant="agg")
        engine.step()
        assert a1.first_token_at is not None    # 3 blocks: at quota
        # agg's second request cannot admit (quota), but the later-queued
        # victim admits right past it
        a2 = engine.submit([2] * 17, max_new_tokens=4, tenant="agg")
        v = engine.submit([3, 4, 5], max_new_tokens=4, tenant="vic")
        engine.step()
        assert v.first_token_at is not None
        assert a2.first_token_at is None
        assert engine.queue.depth_of("agg") == 1
        # quota frees with agg's own completions; a2 then admits
        while not a1.done:
            engine.step()
        for _ in range(30):
            engine.step()
            if a2.done:
                break
        assert a2.done and a2.error is None
        while not v.done:
            engine.step()
        assert v.tokens == _oracle_tokens(cfg, params, [3, 4, 5], 4)
        audit_engine(engine)
        engine.close()

    def test_prompt_over_quota_rejected_at_submit(self, tiny_model):
        cfg, params = tiny_model
        table = TenantTable(default=TenantPolicy(kv_block_quota=2))
        engine = PagedInferenceEngine(
            cfg, params, slots=2, page_size=PAGE, tenants=table)
        with pytest.raises(PromptTooLong, match="kv_block_quota"):
            engine.submit([1] * (3 * PAGE), max_new_tokens=2, tenant="t")
        engine.close()


# ---------------------------------------------------------------------------
# over-long prompts: clear AdmissionError at admission, everywhere


class TestPromptTooLongAdmission:
    def test_dense_and_paged_reject_at_submit(self, tiny_model):
        cfg, params = tiny_model
        too_long = [1] * (cfg.max_seq_len - 4)
        for engine in (InferenceEngine(cfg, params, slots=1),
                       PagedInferenceEngine(cfg, params, slots=1,
                                            page_size=PAGE)):
            with pytest.raises(PromptTooLong, match="max_seq_len"):
                engine.submit(too_long, max_new_tokens=16)
            # the typed rejection is BOTH a retol-safe AdmissionError and
            # a ValueError (INVALID_ARGUMENT on the wire)
            with pytest.raises(AdmissionError):
                engine.submit(too_long, max_new_tokens=16)
            with pytest.raises(ValueError):
                engine.submit(too_long, max_new_tokens=16)
            engine.close()

    def test_gateway_rejects_before_routing_without_health_damage(
            self, tiny_model):
        cfg, params = tiny_model

        fleet = ReplicaFleet(
            lambda: InferenceEngine(cfg, params, slots=1))
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny")
        try:
            fleet.add_replica()
            with pytest.raises(PromptTooLong, match="max_seq_len"):
                gw.generate([1] * cfg.max_seq_len, max_new_tokens=16,
                            timeout_s=10)
            stats = gw.stats()
            assert stats["failovers"] == 0
            for replica in fleet.replicas():
                assert fleet.health.failures(replica.id) == 0
            # the plane still serves fine afterwards
            res = gw.generate([5, 6], max_new_tokens=4, timeout_s=30)
            assert res["status"] == "ok"
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# the isolation acceptance test: aggressor vs victim through the gateway


class TestMultiTenantIsolation:
    def test_aggressor_cannot_starve_victim(self, tiny_model):
        """Aggressor saturates its rate limit + KV quota with long
        prompts; the victim's short requests all admit, decode
        bit-identically to the oracle, and keep a bounded TTFT; the
        aggressor's rejections carry its own retry_after_s."""
        cfg, params = tiny_model
        table = TenantTable()
        table.set_policy(TenantPolicy(
            tenant="agg", priority=2, requests_per_s=4.0, burst_s=1.0,
            kv_block_quota=20, max_queued=2))
        table.set_policy(TenantPolicy(tenant="vic", priority=0))
        fleet = ReplicaFleet(
            lambda: PagedInferenceEngine(
                cfg, params, slots=4, page_size=PAGE, prefill_chunk=16,
                prefill_budget=16, tenants=table).start())
        gw = GatewayService(
            fleet, router=PrefixAffinityRouter(PAGE), model_name="tiny",
            slo=SloLimiter(table), max_waiters=8)
        victim_prompts = [[9, i % 40 + 2, 3] for i in range(6)]
        try:
            fleet.add_replica()
            # uncontended victim TTFT baseline (post-compile)
            gw.generate(victim_prompts[0], max_new_tokens=4, timeout_s=60)
            base = [gw.generate(p, max_new_tokens=6, timeout_s=60)
                    for p in victim_prompts]
            base_ttft = max(r["ttft_ms"] for r in base)

            stop = threading.Event()
            quota_errors = []

            def aggress():
                i = 0
                while not stop.is_set():
                    prompt = [(i + 3 * j) % 50 + 1 for j in range(120)]
                    try:
                        gw.generate(prompt, max_new_tokens=4,
                                    timeout_s=60, tenant="agg")
                    except QuotaExceeded as e:
                        quota_errors.append(e)
                        time.sleep(0.01)
                    i += 1

            threads = [threading.Thread(target=aggress, daemon=True)
                       for _ in range(3)]
            for t in threads:
                t.start()
            try:
                time.sleep(0.2)          # let the aggressors saturate
                contended = [gw.generate(p, max_new_tokens=6,
                                         timeout_s=60, tenant="vic")
                             for p in victim_prompts]
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            # every victim request admitted and finished clean
            assert all(r["status"] == "ok" for r in contended)
            # bit-identical to the uncontended oracle, aggressors be
            # damned (greedy engine-wide: temperature 0)
            for p, r in zip(victim_prompts, contended):
                assert r["tokens"] == _oracle_tokens(cfg, params, p, 6)
            # TTFT stays within a bounded factor of uncontended (the
            # bound is generous — CI wall clocks are noisy — but it
            # catches the failure mode: waiting out a full long-prompt
            # prefill or the aggressor's queue backlog)
            worst = max(r["ttft_ms"] for r in contended)
            assert worst <= max(40.0 * base_ttft, 2000.0), \
                f"victim TTFT p99 {worst}ms vs uncontended {base_ttft}ms"
            # the aggressor actually hit its limits, with usable hints
            assert quota_errors, "aggressor never got rate-limited"
            assert all(e.tenant == "agg" for e in quota_errors)
            assert any(e.retry_after_s for e in quota_errors)
            # per-tenant stats kept the books for both
            tenants = gw.stats()["tenants"]
            assert tenants["vic"]["requests_finished"] >= len(contended)
            for replica in fleet.replicas():
                audit_engine(replica.engine)
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# IAM-scoped serving: tenant identity from the bearer token


class TestIamScopedServing:
    @pytest.fixture()
    def iam(self):
        from lzy_tpu.durable.store import OperationStore
        from lzy_tpu.iam import INTERNAL, IamService

        iam = IamService(OperationStore(":memory:"))
        tokens = {
            "vic": iam.create_subject("vic"),
            "agg": iam.create_subject("agg"),
            "ops": iam.create_subject("ops", role=INTERNAL),
        }
        return iam, tokens

    def _service(self, tiny_model, iam, **engine_kw):
        from lzy_tpu.service.inference import InferenceService

        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2, **engine_kw).start()
        return InferenceService(engine, model_name="tiny", iam=iam)

    def test_tenant_is_the_authenticated_subject(self, tiny_model, iam):
        iam, tokens = iam
        svc = self._service(tiny_model, iam)
        try:
            res = svc.generate([3, 4], max_new_tokens=4,
                               token=tokens["vic"], timeout_s=60)
            assert res["status"] == "ok"
            rows = svc.engine.stats_by_tenant()
            assert rows["vic"]["requests_finished"] == 1
            assert "default" not in rows
        finally:
            svc.close()

    def test_subject_cannot_masquerade_but_operator_can(
            self, tiny_model, iam):
        from lzy_tpu.iam import AuthError

        iam, tokens = iam
        svc = self._service(tiny_model, iam)
        try:
            with pytest.raises(AuthError, match="may not submit as"):
                svc.generate([3, 4], max_new_tokens=2,
                             token=tokens["vic"], tenant="agg",
                             timeout_s=60)
            # the INTERNAL role may act on a tenant's behalf (ops tooling)
            res = svc.generate([3, 4], max_new_tokens=2,
                               token=tokens["ops"], tenant="agg",
                               timeout_s=60)
            assert res["status"] == "ok"
            assert svc.engine.stats_by_tenant()["agg"][
                "requests_finished"] == 1
        finally:
            svc.close()

    def test_stats_scoped_per_subject(self, tiny_model, iam):
        iam, tokens = iam
        svc = self._service(tiny_model, iam)
        try:
            svc.generate([3, 4], max_new_tokens=2, token=tokens["vic"],
                         timeout_s=60)
            svc.generate([5, 6], max_new_tokens=2, token=tokens["agg"],
                         timeout_s=60)
            # a tenant sees ITS OWN counters, nothing else
            mine = svc.stats(token=tokens["vic"])
            assert mine["tenant"] == "vic"
            assert mine["requests_finished"] == 1
            assert "tenants" not in mine and "slots" not in mine
            # the operator sees the engine plus every tenant's row
            ops = svc.stats(token=tokens["ops"])
            assert ops["slots"] == 2
            assert set(ops["tenants"]) == {"vic", "agg"}
        finally:
            svc.close()

    def test_gateway_stats_and_fleet_stats_scoping(self, tiny_model, iam):
        from lzy_tpu.iam import AuthError

        iam, tokens = iam
        cfg, params = tiny_model
        fleet = ReplicaFleet(lambda: InferenceEngine(cfg, params, slots=2))
        gw = GatewayService(fleet, router=PrefixAffinityRouter(PAGE),
                            model_name="tiny", iam=iam)
        try:
            fleet.add_replica()
            gw.generate([3, 4], max_new_tokens=2, token=tokens["vic"],
                        timeout_s=60)
            mine = gw.stats(token=tokens["vic"])
            assert mine["tenant"] == "vic"
            assert mine["requests_finished"] == 1
            assert "replicas" not in mine
            ops = gw.stats(token=tokens["ops"])
            assert ops["replicas"] == 1
            assert ops["tenants"]["vic"]["requests_finished"] == 1
            with pytest.raises(AuthError, match="operator-only"):
                gw.fleet_stats(token=tokens["vic"])
            assert gw.fleet_stats(token=tokens["ops"])["replicas"]
        finally:
            gw.close()

    def test_token_rotation_mid_stream(self, tiny_model, iam):
        from lzy_tpu.iam import AuthError

        iam, tokens = iam
        svc = self._service(tiny_model, iam)
        try:
            results = {}

            def run():
                results["res"] = svc.generate(
                    [7, 8], max_new_tokens=48, token=tokens["vic"],
                    timeout_s=60)

            t = threading.Thread(target=run)
            t.start()
            # rotate the subject while (most likely) mid-decode: the
            # IN-FLIGHT stream finishes — auth happens at admission —
            # but the stale token admits nothing new
            iam.rotate_subject("vic")
            with pytest.raises(AuthError, match="revoked"):
                svc.generate([9], max_new_tokens=2, token=tokens["vic"],
                             timeout_s=60)
            t.join(timeout=60)
            assert results["res"]["status"] == "ok"
            assert len(results["res"]["tokens"]) == 48
            # a re-issued token works again
            fresh = iam.issue_token("vic")
            assert svc.generate([9], max_new_tokens=2, token=fresh,
                                timeout_s=60)["status"] == "ok"
        finally:
            svc.close()

    def test_unauthenticated_rejection_on_every_new_field(
            self, tiny_model, iam):
        """Every new RPC field rides InferGenerate/InferStats, which
        refuse before reading them: no token, bad token, and legacy
        formats are all rejected regardless of tenant/priority args."""
        from lzy_tpu.iam import AuthError

        iam, tokens = iam
        svc = self._service(tiny_model, iam)
        try:
            for bad in (None, "garbage", "a:b:c", tokens["vic"] + "x"):
                with pytest.raises(AuthError):
                    svc.generate([1, 2], max_new_tokens=2, token=bad,
                                 tenant="vic", priority=0, timeout_s=5)
                with pytest.raises(AuthError):
                    svc.stats(token=bad)
        finally:
            svc.close()

    def test_wire_schema_validates_new_fields(self):
        from lzy_tpu.rpc.schema import REQUESTS, SchemaError

        schema = REQUESTS["InferGenerate"]
        schema.validate({"prompt": [1], "tenant": "t", "priority": 1})
        with pytest.raises(SchemaError):
            schema.validate({"prompt": [1], "tenant": 7})
        with pytest.raises(SchemaError):
            schema.validate({"prompt": [1], "priority": "high"})
