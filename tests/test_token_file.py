"""Native token-file data loader: format, gather, fallback parity, resume."""

import numpy as np
import pytest

from lzy_tpu.data import DataPipeline
from lzy_tpu.data.token_file import TokenFile, write_token_file


@pytest.fixture(scope="module")
def token_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("tok") / "corpus.bin"
    write_token_file(path, np.arange(10_000, dtype=np.int64) % 50_000)
    return path


def test_write_picks_compact_dtype(tmp_path):
    small = tmp_path / "small.bin"
    write_token_file(small, np.array([0, 1, 65_535]))
    assert TokenFile(small, native=False)._token_bytes == 2
    big = tmp_path / "big.bin"
    write_token_file(big, np.array([0, 70_000]))
    assert TokenFile(big, native=False)._token_bytes == 4


def test_rejects_bad_inputs(tmp_path):
    with pytest.raises(ValueError):
        write_token_file(tmp_path / "x.bin", np.array([], dtype=np.int32))
    with pytest.raises(ValueError):
        write_token_file(tmp_path / "x.bin", np.array([-1, 2]))
    junk = tmp_path / "junk.bin"
    junk.write_bytes(b"not a token file, definitely long enough")
    with pytest.raises(ValueError, match="LZYTOK1|magic"):
        TokenFile(junk)


def test_gather_native_matches_numpy_fallback(token_path):
    starts = np.array([0, 17, 9_000, 10_000 - 64])
    with TokenFile(token_path) as native, \
            TokenFile(token_path, native=False) as fallback:
        a = native.gather(starts, 64)
        b = fallback.gather(starts, 64)
        assert a.dtype == np.int32 and a.shape == (4, 64)
        np.testing.assert_array_equal(a, b)
        # multithreaded path agrees too
        np.testing.assert_array_equal(
            native.gather(starts, 64, n_threads=3), b)


@pytest.mark.parametrize("native", [True, False])
def test_gather_bounds_checked(token_path, native):
    tf = TokenFile(token_path, native=native)
    try:
        with pytest.raises(IndexError):
            tf.gather(np.array([10_000 - 63]), 64)
        with pytest.raises(IndexError):
            tf.gather(np.array([-1]), 64)
    finally:
        tf.close()


def test_lm_source_covers_file_and_resumes(token_path):
    with TokenFile(token_path) as tf:
        src = tf.lm_source(batch_size=4, seq_len=128, shuffle=True, seed=3,
                           epochs=1)
        seen = []
        for batch in src:
            assert batch["tokens"].shape == (4, 128)
            seen.append(batch["tokens"][:, 0].copy())
        # 10_000 // 128 = 78 windows -> 19 full batches of 4
        assert len(seen) == 19
        firsts = np.concatenate(seen)
        assert len(np.unique(firsts)) == len(firsts)  # no window repeats

        # resume: state taken mid-epoch continues with the exact next batch
        src2 = tf.lm_source(batch_size=4, seq_len=128, shuffle=True, seed=3)
        it = iter(src2)
        for _ in range(7):
            next(it)
        state = src2.state()
        expected = next(it)
        resumed = tf.lm_source(batch_size=4, seq_len=128, shuffle=True,
                               seed=3, state=state)
        got = next(iter(resumed))
        np.testing.assert_array_equal(got["tokens"], expected["tokens"])


def test_lm_source_emits_packed_segments(tmp_path):
    eos = 99
    docs = [3, 4, eos, 7, eos, 1, 2, 3, 4, eos, 5, 6]
    write_token_file(tmp_path / "docs.bin", np.array(docs * 20))
    with TokenFile(tmp_path / "docs.bin") as tf:
        src = tf.lm_source(batch_size=1, seq_len=12, shuffle=False,
                           eos_id=eos)
        batch = next(iter(src))
        seg = batch["segments"][0]
        # the EOS token closes its document; ids are non-decreasing
        np.testing.assert_array_equal(
            seg, [0, 0, 0, 1, 1, 2, 2, 2, 2, 2, 3, 3]
        )
        assert (np.diff(batch["segments"], axis=1) >= 0).all()


def test_lm_source_sharded_hosts_disjoint(token_path):
    with TokenFile(token_path) as tf:
        per_host = [
            next(iter(tf.lm_source(batch_size=4, seq_len=128, seed=1,
                                   shard_index=i, shard_count=2)))
            for i in range(2)
        ]
        a = set(per_host[0]["tokens"][:, 0].tolist())
        b = set(per_host[1]["tokens"][:, 0].tolist())
        assert not (a & b)


def test_pipeline_integration_device_batches(token_path):
    import jax

    with TokenFile(token_path) as tf:
        src = tf.lm_source(batch_size=2, seq_len=64, shuffle=False, epochs=1)
        sharding = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])
        pipe = DataPipeline(src, sharding, prefetch=2)
        n = 0
        for batch in pipe:
            assert isinstance(batch["tokens"], jax.Array)
            n += 1
            if n == 5:
                break
        assert pipe.data_state() is not None


class TestTokenizeCorpus:
    """Raw text → token file through any HF-style tokenizer; EOS after
    every document so the loader's packing picks up the boundaries."""

    class FakeTokenizer:
        eos_token_id = 0

        def encode(self, text):
            return [ord(c) % 250 + 1 for c in text]

    def test_corpus_round_trips_with_document_boundaries(self, tmp_path):
        from lzy_tpu.data import TokenFile
        from lzy_tpu.data.tokenize import tokenize_corpus

        docs = ["hello world", "a second document", "x"]
        path = tmp_path / "corpus.bin"
        n = tokenize_corpus(iter(docs), self.FakeTokenizer(), path)
        assert n == sum(len(d) for d in docs) + len(docs)   # + one EOS each
        with TokenFile(str(path)) as tf:
            tokens = tf.gather(np.array([0]), n)[0]
        # EOS lands exactly at each document boundary
        eos_positions = np.where(tokens == 0)[0].tolist()
        expect = np.cumsum([len(d) + 1 for d in docs]) - 1
        assert eos_positions == expect.tolist()

    def test_real_transformers_tokenizer(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        from lzy_tpu.data import TokenFile
        from lzy_tpu.data.tokenize import tokenize_corpus

        # offline: build a tiny WordLevel-style tokenizer from scratch
        tok = transformers.PreTrainedTokenizerFast(
            tokenizer_object=self._tiny_tokenizer(), eos_token="</s>")
        path = tmp_path / "c.bin"
        n = tokenize_corpus(["the cat sat", "the dog"], tok, path)
        assert n > 0
        with TokenFile(str(path)) as tf:
            assert tf.gather(np.array([0]), n).shape == (1, n)

    @staticmethod
    def _tiny_tokenizer():
        from tokenizers import Tokenizer, models, pre_tokenizers

        vocab = {"</s>": 0, "the": 1, "cat": 2, "sat": 3, "dog": 4,
                 "[UNK]": 5}
        t = Tokenizer(models.WordLevel(vocab, unk_token="[UNK]"))
        t.pre_tokenizer = pre_tokenizers.Whitespace()
        return t

    def test_missing_eos_is_a_clear_error(self, tmp_path):
        from lzy_tpu.data.tokenize import tokenize_corpus

        class NoEos:
            def encode(self, text):
                return [1, 2]

        with pytest.raises(ValueError, match="eos"):
            tokenize_corpus(["x"], NoEos(), tmp_path / "c.bin")
