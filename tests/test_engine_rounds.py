"""Decode-round fence contract (engine.py one-sync-per-round).

The restructured round scheduler promises exactly ONE device→host
transfer per decode round: every per-row read — next-token ids, EOS
decisions, spec acceptance lengths — rides a single fused program whose
one output crosses the fence via ``InferenceEngine._fetch``. These tests
pin that contract two ways:

- ``host_fetches`` (the engine's own fence counter) must advance by
  exactly 1 per steady-state decode round, dense / paged / spec-verify;
- a counting transfer shim swapped in for the engine module's ``np``
  must see every device→host conversion go through ``_fetch`` — a
  regression that fetches device data outside the fence (per-row
  ``np.asarray``, the pre-restructure shape) trips the shim even though
  it never touches ``host_fetches``.

Bit-identity rides along: the same restructured loop must still equal
the ``generate()`` oracle under forced full-acceptance and
full-rejection proposers (the dense twins of the paged cases in
test_spec_decode.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import InferenceEngine, PagedInferenceEngine
from lzy_tpu.serving import engine as engine_mod

VOCAB = 64

PROMPTS = [
    [5, 9, 3, 7, 2],
    [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3, 4],
]


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=VOCAB)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


def _oracle(cfg, params, prompt_ids, n):
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


def _drain(engine, reqs, rounds=800):
    for _ in range(rounds):
        if all(r.done for r in reqs):
            return
        engine.step()
    raise AssertionError("engine did not finish its requests")


def _reach_steady_decode(eng, reqs, rounds=200):
    """Step until every request is resident in a slot (prefill done,
    queue empty) — from here on each step() is exactly one decode
    round."""
    for _ in range(rounds):
        if (not eng._prefill_jobs and eng.queue.depth() == 0
                and sum(r is not None for r in eng._active) == len(reqs)):
            return
        eng.step()
    raise AssertionError("requests never reached steady decode")


class _OracleProposer:
    """Drafts the model's actual greedy continuation: full acceptance."""

    def __init__(self, seqs, gamma):
        self.seqs = [list(map(int, s)) for s in seqs]
        self.gamma = gamma

    def propose(self, tokens):
        t = list(tokens)
        for s in self.seqs:
            if len(s) > len(t) and s[:len(t)] == t:
                return s[len(t):len(t) + self.gamma]
        return []


class _AdversarialProposer(_OracleProposer):
    """Drafts tokens guaranteed wrong: full rejection every round."""

    def propose(self, tokens):
        return [(t + 1) % VOCAB for t in super().propose(tokens)]


class _CountingNp:
    """Transfer shim: proxies the engine module's ``np`` and counts
    ``asarray``/``array`` calls whose argument is a device array — i.e.
    every device→host conversion the engine code performs."""

    def __init__(self, real):
        self._real = real
        self.device_fetches = 0

    def __getattr__(self, name):
        return getattr(self._real, name)

    def _counting(self, fn, a, *args, **kw):
        if isinstance(a, jax.Array):
            self.device_fetches += 1
        return fn(a, *args, **kw)

    def asarray(self, a, *args, **kw):
        return self._counting(self._real.asarray, a, *args, **kw)

    def array(self, a, *args, **kw):
        return self._counting(self._real.array, a, *args, **kw)


def _build(cfg, params, *, paged, spec=0, proposer=None):
    kw = dict(slots=2, spec_tokens=spec)
    if proposer is not None:
        kw["proposer"] = proposer
    if paged:
        return PagedInferenceEngine(cfg, params, page_size=16, **kw)
    return InferenceEngine(cfg, params, **kw)


class TestOneFencePerRound:
    @pytest.mark.parametrize("paged", [False, True])
    def test_plain_decode_one_fetch_per_round(self, tiny_model, paged):
        cfg, params = tiny_model
        eng = _build(cfg, params, paged=paged)
        reqs = [eng.submit(p, max_new_tokens=40) for p in PROMPTS]
        _reach_steady_decode(eng, reqs)
        for _ in range(8):
            before = eng.host_fetches
            assert eng.step()
            assert eng.host_fetches == before + 1
        eng.close()

    @pytest.mark.parametrize("accept", [True, False])
    def test_spec_verify_one_fetch_per_round(self, tiny_model, accept):
        cfg, params = tiny_model
        n, gamma = 30, 3
        prompt = PROMPTS[1]
        exp = _oracle(cfg, params, prompt, n)
        cls = _OracleProposer if accept else _AdversarialProposer
        eng = _build(cfg, params, paged=True, spec=gamma,
                     proposer=cls([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=n)
        _reach_steady_decode(eng, [req])
        rounds = 0
        while not req.done and rounds < 100:
            before = eng.host_fetches
            eng.step()
            rounds += 1
            assert eng.host_fetches == before + 1
        assert req.done and req.result() == exp
        if accept:
            # the fence budget is per ROUND, so full acceptance buys
            # tokens without buying transfers: far fewer fetches than
            # emitted tokens
            assert eng.decode_steps < n - 1
        eng.close()

    @pytest.mark.parametrize("paged", [False, True])
    def test_shim_sees_no_fetch_outside_the_fence(
            self, tiny_model, paged, monkeypatch):
        cfg, params = tiny_model
        eng = _build(cfg, params, paged=paged)
        reqs = [eng.submit(p, max_new_tokens=40) for p in PROMPTS]
        _reach_steady_decode(eng, reqs)
        shim = _CountingNp(np)
        monkeypatch.setattr(engine_mod, "np", shim)
        rounds = 8
        before = eng.host_fetches
        for _ in range(rounds):
            assert eng.step()
        # every device→host conversion the engine performed went
        # through _fetch: shim total == fence counter delta == rounds
        assert eng.host_fetches - before == rounds
        assert shim.device_fetches == rounds
        eng.close()


class TestDenseBitIdentityUnderForcedProposers:
    def test_full_acceptance_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        n, gamma = 16, 4
        prompt = PROMPTS[0]
        exp = _oracle(cfg, params, prompt, n)
        eng = _build(cfg, params, paged=False, spec=gamma,
                     proposer=_OracleProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [req])
        assert req.result() == exp
        s = eng.stats()
        assert s.spec_acceptance_rate == 1.0
        assert eng.decode_steps < n - 1
        eng.close()

    def test_full_rejection_matches_oracle(self, tiny_model):
        cfg, params = tiny_model
        n, gamma = 12, 3
        prompt = PROMPTS[1]
        exp = _oracle(cfg, params, prompt, n)
        eng = _build(cfg, params, paged=False, spec=gamma,
                     proposer=_AdversarialProposer([prompt + exp], gamma))
        req = eng.submit(prompt, max_new_tokens=n)
        _drain(eng, [req])
        assert req.result() == exp
        s = eng.stats()
        assert s.spec_proposed_tokens > 0
        assert s.spec_accepted_tokens == 0
        eng.close()
