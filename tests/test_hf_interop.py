"""HF → lzy_tpu Llama weight import (pretrained-checkpoint on-ramp).

Beyond the loading feature, this is the architecture cross-check: our
forward must match ``transformers.LlamaForCausalLM`` on the SAME weights
— RoPE convention, GQA grouping, RMSNorm placement, SwiGLU order all
have to agree for the logits to agree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from lzy_tpu.models.hf_interop import (  # noqa: E402
    config_from_hf, load_hf, params_from_hf)
from lzy_tpu.models.llama import Llama  # noqa: E402


def tiny_hf(tie=False, seed=0):
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=500_000.0,
        tie_word_embeddings=tie, attn_implementation="eager",
    )
    torch.manual_seed(seed)
    return LlamaForCausalLM(cfg).eval()


def hf_logits(hf, tokens_np):
    with torch.no_grad():
        return hf(torch.tensor(tokens_np)).logits.numpy()


class TestHfParity:
    @pytest.mark.parametrize("tie", [False, True],
                             ids=["untied-head", "tied-embeddings"])
    def test_logits_match_canonical_implementation(self, tie):
        hf = tiny_hf(tie=tie)
        cfg = dataclasses.replace(config_from_hf(hf.config),
                                  dtype=jnp.float32)
        assert cfg.tie_embeddings == tie
        params = params_from_hf(hf, cfg)
        tokens = np.random.RandomState(1).randint(0, 256, (2, 16))
        ours = np.asarray(Llama(cfg).apply(
            {"params": params}, jnp.asarray(tokens)))
        theirs = hf_logits(hf, tokens)
        np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-4)

    def test_load_hf_one_call(self):
        hf = tiny_hf()
        cfg, params = load_hf(hf)
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        tokens = np.random.RandomState(2).randint(0, 256, (1, 8))
        ours = np.asarray(Llama(cfg).apply(
            {"params": params}, jnp.asarray(tokens)))
        np.testing.assert_allclose(ours, hf_logits(hf, tokens),
                                   atol=5e-4, rtol=5e-4)

    def test_imported_weights_generate(self):
        """The converted tree drives the framework's own decode path."""
        from lzy_tpu.models.generate import generate

        hf = tiny_hf()
        cfg, params = load_hf(hf)
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, 256, (1, 8)))
        out = generate(cfg, params, prompt, max_new_tokens=4,
                       temperature=0.0)
        assert out.shape == (1, 12)
        assert int(out.max()) < cfg.vocab_size

    def test_imported_weights_shard_onto_a_mesh(self):
        """The tree carries the same names/shapes init_params produces,
        so the standard logical-axis sharding applies unchanged."""
        from lzy_tpu.models import llama as llama_mod
        from lzy_tpu.models.common import param_logical_axes, unbox
        from lzy_tpu.parallel import mesh_for
        from lzy_tpu.parallel.sharding import shard_tree

        hf = tiny_hf()
        cfg, params = load_hf(hf)
        boxed, axes = llama_mod.init_params(
            dataclasses.replace(cfg, dtype=jnp.float32),
            jax.random.PRNGKey(0))
        ref_shapes = jax.tree_util.tree_map(jnp.shape, unbox(boxed))
        got_shapes = jax.tree_util.tree_map(jnp.shape, params)
        assert ref_shapes == got_shapes
        mesh = mesh_for(8, fsdp=4, tp=2)
        sharded = shard_tree(params, mesh, axes)
        gate = sharded["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert "fsdp" in str(gate.sharding.spec) or "tp" in str(
            gate.sharding.spec)


class TestConversionGuards:
    """Checkpoint families the converter would silently get wrong must
    be rejected loudly, not converted approximately."""

    def test_rope_scaling_rejected(self):
        from transformers import LlamaConfig as HFConfig

        cfg = HFConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=1, num_attention_heads=2,
                       num_key_value_heads=2,
                       rope_scaling={"rope_type": "llama3", "factor": 8.0,
                                     "low_freq_factor": 1.0,
                                     "high_freq_factor": 4.0,
                                     "original_max_position_embeddings": 8192})
        with pytest.raises(ValueError, match="rope_scaling"):
            config_from_hf(cfg)

    def test_attention_bias_rejected(self):
        from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

        cfg = HFConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                       num_hidden_layers=1, num_attention_heads=2,
                       num_key_value_heads=2, attention_bias=True,
                       attn_implementation="eager")
        torch.manual_seed(0)
        hf = LlamaForCausalLM(cfg)
        with pytest.raises(ValueError, match="unconverted"):
            params_from_hf(hf, config_from_hf(cfg))


class TestBertHfParity:
    """BASELINE config 3's architecture verified against the canonical
    BertForMaskedLM (token-type-0 folded into positions; tied decoder
    bias mapped to mlm_bias)."""

    def _tiny(self):
        from transformers import BertConfig as HFBertConfig, BertForMaskedLM

        cfg = HFBertConfig(
            vocab_size=256, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64, type_vocab_size=2,
            layer_norm_eps=1e-12, attn_implementation="eager")
        torch.manual_seed(0)
        return BertForMaskedLM(cfg).eval()

    def test_logits_match_canonical_implementation(self):
        from lzy_tpu.models.bert import BertMlm
        from lzy_tpu.models.hf_interop import (
            bert_config_from_hf, bert_params_from_hf)

        hf = self._tiny()
        cfg = dataclasses.replace(bert_config_from_hf(hf.config),
                                  dtype=jnp.float32)
        params = bert_params_from_hf(hf, cfg)
        tokens = np.random.RandomState(1).randint(0, 256, (2, 16))
        ours = np.asarray(BertMlm(cfg).apply(
            {"params": params}, jnp.asarray(tokens)))
        with torch.no_grad():
            theirs = hf(torch.tensor(tokens)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-4)

    def test_padding_mask_semantics_match(self):
        from lzy_tpu.models.bert import BertMlm
        from lzy_tpu.models.hf_interop import (
            bert_config_from_hf, bert_params_from_hf)

        hf = self._tiny()
        cfg = dataclasses.replace(bert_config_from_hf(hf.config),
                                  dtype=jnp.float32)
        params = bert_params_from_hf(hf, cfg)
        tokens = np.random.RandomState(2).randint(0, 256, (1, 12))
        mask = np.ones((1, 12), np.int64)
        mask[:, 9:] = 0                      # padded tail
        ours = np.asarray(BertMlm(cfg).apply(
            {"params": params}, jnp.asarray(tokens),
            jnp.asarray(mask.astype(bool))))
        with torch.no_grad():
            theirs = hf(torch.tensor(tokens),
                        attention_mask=torch.tensor(mask)).logits.numpy()
        # compare the REAL positions (HF still computes padded ones)
        np.testing.assert_allclose(ours[:, :9], theirs[:, :9],
                                   atol=5e-4, rtol=5e-4)
