"""Indexed whiteboard queries: O(matches) cost via per-whiteboard index
records (the storage-native analog of the reference's Postgres indexes,
``WhiteboardService.java:45``)."""

import datetime
import time

import pytest

from lzy_tpu.storage.mem import MemStorageClient
from lzy_tpu.whiteboards.index import WhiteboardIndex


class CountingClient(MemStorageClient):
    """Counts read_bytes calls per URI kind to prove what a query touched."""

    def __init__(self):
        self.manifest_reads = 0
        self.index_reads = 0

    def read_bytes(self, uri):
        if uri.endswith("/manifest.json"):
            self.manifest_reads += 1
        elif "/.index/" in uri:
            self.index_reads += 1
        return super().read_bytes(uri)

    def reset(self):
        self.manifest_reads = self.index_reads = 0


def make_index(client=None):
    return WhiteboardIndex(client or CountingClient(), "mem://wbtest")


def finalize(index, wb_id, name, tags=()):
    index.register(wb_id=wb_id, name=name, tags=tags)
    index.finalize(wb_id, fields={})


class TestIndexedQuery:
    def test_query_reads_only_matching_manifests(self):
        client = CountingClient()
        index = make_index(client)
        for i in range(50):
            finalize(index, f"wb-{i}", f"name-{i % 10}", tags=[f"t{i % 5}"])
        client.reset()

        result = index.query(name="name-3")
        assert sorted(m.id for m in result) == ["wb-13", "wb-23", "wb-3",
                                                "wb-33", "wb-43"]
        # exactly the 5 matches' manifests were read — not all 50
        assert client.manifest_reads == 5
        assert client.index_reads == 5   # only name-3's index records

    def test_tag_query_uses_tag_index(self):
        client = CountingClient()
        index = make_index(client)
        for i in range(20):
            finalize(index, f"wb-{i}", "same-name", tags=[f"t{i % 4}", "all"])
        client.reset()
        result = index.query(tags=["t1", "all"])
        assert sorted(m.id for m in result) == ["wb-1", "wb-13", "wb-17",
                                                "wb-5", "wb-9"]
        assert client.manifest_reads == 5
        assert client.index_reads == 5   # t1's records only, t2/t3 untouched

    def test_unfinalized_whiteboards_invisible(self):
        client = CountingClient()
        index = make_index(client)
        index.register(wb_id="wb-open", name="open-wb", tags=())
        assert index.query(name="open-wb") == []
        assert client.manifest_reads == 0

    def test_time_range_prunes_on_names(self):
        client = CountingClient()
        index = make_index(client)
        finalize(index, "wb-old", "timed")
        # forge an old creation time by rewriting the records
        m = index.get(id_="wb-old")
        cutoff = datetime.datetime.now(datetime.timezone.utc)
        finalize(index, "wb-new", "timed")
        client.reset()
        recent = index.query(name="timed", not_before=cutoff)
        assert [x.id for x in recent] == ["wb-new"]
        # the old record was pruned by NAME: only the match's record read
        assert client.index_reads == 1 and client.manifest_reads == 1
        assert m.id == "wb-old"

    def test_names_with_special_characters(self):
        index = make_index()
        finalize(index, "wb-s", "exp/run 1:final", tags=["a/b"])
        assert [m.id for m in index.query(name="exp/run 1:final")] == ["wb-s"]
        assert [m.id for m in index.query(tags=["a/b"])] == ["wb-s"]

    def test_reindex_migrates_unindexed_manifests(self):
        client = CountingClient()
        index = make_index(client)
        finalize(index, "wb-1", "legacy")
        # simulate a pre-index deployment: wipe the index records
        for uri in list(client.list("mem://wbtest/whiteboards/.index")):
            client.delete(uri)
        assert index.query(name="legacy") == []
        assert index.reindex() == 1
        assert [m.id for m in index.query(name="legacy")] == ["wb-1"]

    def test_thousand_whiteboards_fast_without_manifest_scan(self):
        """VERDICT acceptance: 1,000 whiteboards, query well under 100 ms,
        zero non-matching manifest reads."""
        client = CountingClient()
        index = make_index(client)
        for i in range(1000):
            finalize(index, f"wb-{i}", f"bulk-{i % 100}")
        client.reset()
        t0 = time.perf_counter()
        result = index.query(name="bulk-42")
        dt = time.perf_counter() - t0
        assert len(result) == 10
        assert client.manifest_reads == 10      # matches only, not 1000
        assert dt < 0.1, f"query took {dt * 1000:.1f} ms"


class TestPrefixSafety:
    def test_name_prefix_does_not_collide(self):
        index = make_index()
        finalize(index, "wb-a", "foo")
        finalize(index, "wb-b", "foobar")
        assert [m.id for m in index.query(name="foo")] == ["wb-a"]
        assert [m.id for m in index.query(name="foobar")] == ["wb-b"]

    def test_tag_prefix_does_not_collide(self):
        index = make_index()
        finalize(index, "wb-a", "n", tags=["gpu"])
        finalize(index, "wb-b", "n", tags=["gpu-v100"])
        assert [m.id for m in index.query(tags=["gpu"])] == ["wb-a"]
        assert [m.id for m in index.query(tags=["gpu-v100"])] == ["wb-b"]
