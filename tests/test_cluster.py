"""Multi-service integration tests over the in-process cluster, the analog of
the reference's ``LzyInThread`` tier (``WorkflowTest``, ``SchedulerTest``,
``CachedGraphExecutionTest``, restart tests — SURVEY.md §4.3)."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu import Lzy, op, tpu
from lzy_tpu.core.workflow import RemoteCallError
from lzy_tpu.durable import InjectedFailures
from lzy_tpu.service import InProcessCluster
from lzy_tpu.service.worker import current_gang


@pytest.fixture()
def cluster(tmp_path):
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _clear_failures():
    yield
    InjectedFailures.clear()


@op
def inc(x: int) -> int:
    return x + 1


@op
def add(a: int, b: int) -> int:
    return a + b


# module-level state shared with workers: functions defined at module level are
# cloudpickled BY REFERENCE, so in-process workers resolve the same module
# objects. Closure-captured state would be copied instead and invisible here.
PROBE_STARTS = []
HEAVY_RUNS = []
CONCURRENCY = {"now": 0, "peak": 0}
CONCURRENCY_LOCK = threading.Lock()


@op
def tracked_sleep(i: int) -> int:
    with CONCURRENCY_LOCK:
        CONCURRENCY["now"] += 1
        CONCURRENCY["peak"] = max(CONCURRENCY["peak"], CONCURRENCY["now"])
    time.sleep(0.15)
    with CONCURRENCY_LOCK:
        CONCURRENCY["now"] -= 1
    return i


@op
def probe(i: int) -> int:
    PROBE_STARTS.append((i, time.time()))
    time.sleep(0.2)
    return i


@op(cache=True, version="1.0")
def heavy(x: int) -> int:
    HEAVY_RUNS.append(x)
    return x * 2


@op(tpu="v5e-16")
def spmd_probe() -> dict:
    g = dict(current_gang())
    g["ok"] = True
    return g


def test_single_op_remote(cluster):
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        r = inc(41)
        assert r == 42


def test_chained_graph_remote(cluster):
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        r = add(inc(1), inc(2))
    assert r == 5


def test_fanout_parallel_ops(cluster):
    """Hyperparameter-sweep shape (BASELINE config 1): independent ops run
    concurrently on separate VMs."""
    PROBE_STARTS.clear()
    lzy = cluster.lzy()
    t0 = time.time()
    with lzy.workflow("sweep"):
        results = [probe(i) for i in range(4)]
        total = sum(int(r) for r in results)
    elapsed = time.time() - t0
    assert total == 6
    assert len(PROBE_STARTS) == 4
    # four 0.2s ops must overlap: well under the 0.8s serial floor
    assert elapsed < 0.75, f"ops did not run in parallel ({elapsed:.2f}s)"


def test_vm_reuse_across_graphs(cluster):
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        a = inc(1)
        assert a == 2          # barrier 1 → allocates a VM
        b = inc(int(a))
        assert b == 3          # barrier 2 → must reuse the idle VM
    vms = cluster.allocator.vms()
    assert len(vms) == 1, f"expected 1 cached VM, got {[v.id for v in vms]}"


def test_remote_exception_propagates(cluster):
    @op
    def explode(x: int) -> int:
        raise ValueError(f"bad value {x}")

    lzy = cluster.lzy()
    with pytest.raises(RemoteCallError) as exc_info:
        with lzy.workflow("wf"):
            r = explode(7)
            _ = r + 1
    cause = exc_info.value.__cause__
    assert isinstance(cause, ValueError)
    assert "bad value 7" in str(cause)
    assert any("remote traceback" in n for n in getattr(cause, "__notes__", []))


def test_jax_array_device_channel(cluster):
    """Producer's jax value reaches the consumer; the device-residency fast
    path serves it without a storage round-trip."""
    @op
    def produce() -> jnp.ndarray:
        return jnp.full((8, 8), 3.0, jnp.bfloat16)

    @op
    def consume(x: jnp.ndarray) -> float:
        return float(jnp.float32(x).sum())

    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        out = consume(produce())
        assert float(out) == 192.0


def test_gang_allocation_for_tpu_pool(cluster):
    """A TPU op allocates the whole slice gang (v5e-16 → 2 hosts) atomically
    and the op sees its gang context."""
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        g = spmd_probe()
        assert g["ok"] is True
        assert g["size"] == 2
        assert g["rank"] == 0
        assert len(g["vm_ids"]) == 2
        vms = cluster.allocator.vms()
        assert len(vms) == 2
        assert all(v.status == "IDLE" for v in vms)  # freed back to cache
        assert len({v.gang_id for v in vms}) == 1


def test_server_side_cache_check(cluster):
    """Second submission of an identical cached graph is fully dropped
    server-side (CheckCache parity)."""
    HEAVY_RUNS.clear()
    lzy = cluster.lzy()
    for _ in range(2):
        with lzy.workflow("wf"):
            assert heavy(5) == 10
    assert HEAVY_RUNS == [5]


def test_std_logs_reach_client(cluster, capfd):
    @op
    def chatty() -> int:
        print("hello from the worker")
        return 1

    lzy = cluster.lzy(stream_logs=True)
    with lzy.workflow("wf"):
        assert chatty() == 1
    err = capfd.readouterr().err
    assert "hello from the worker" in err
    assert "[LZY-REMOTE-" in err


def test_workflow_finish_tears_down(cluster):
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        inc(1)
    exec_docs = cluster.store.kv_list("executions")
    assert len(exec_docs) == 1
    doc = next(iter(exec_docs.values()))
    assert doc["status"] == "FINISHED"
    # session deletion reaps the cached VM
    deadline = time.time() + 5
    while cluster.allocator.vms() and time.time() < deadline:
        time.sleep(0.05)
    assert cluster.allocator.vms() == []


def test_graph_crash_resume(cluster):
    """Kill the graph scheduler mid-flight (injected crash), then restore —
    the graph resumes and completes (RestartExecuteGraphTest parity)."""
    InjectedFailures.arm("exec_graph.schedule")
    lzy = cluster.lzy()

    done = {}

    def run():
        with lzy.workflow("wf"):
            done["result"] = int(add(inc(1), inc(2)))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.6)
    assert "result" not in done       # crashed: graph op parked RUNNING
    resumed = cluster.resume_pending_operations()
    assert resumed >= 1
    t.join(timeout=20)
    assert done.get("result") == 5


def test_crash_during_gang_launch_does_not_leak(cluster):
    """Crash between launching host 0 and host 1 of a gang; resume must finish
    with exactly ONE gang (idempotent launch), not allocate a second one."""
    InjectedFailures.arm("allocate_gang.launch_each", n_hits=2)  # after host 0
    lzy = cluster.lzy()
    done = {}

    def run():
        with lzy.workflow("wf"):
            done["g"] = dict(spmd_probe())
            # snapshot VM state before workflow teardown reaps the session
            done["vms"] = [(v.id, v.gang_id) for v in cluster.allocator.vms()]

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.8)
    assert "g" not in done
    cluster.resume_pending_operations()
    t.join(timeout=20)
    assert done["g"]["size"] == 2
    vms = done["vms"]
    assert len(vms) == 2, f"leaked gang hosts: {vms}"
    assert len({gang for _, gang in vms}) == 1


def test_stop_graph_flag_survives_scheduler_writes(cluster):
    """stop() must not be lost to a concurrent scheduler save_progress."""
    @op
    def slow(x: int) -> int:
        time.sleep(5)
        return x

    lzy = cluster.lzy(poll_period_s=0.05)
    from lzy_tpu.core.workflow import RemoteCallError

    t0 = time.time()
    with pytest.raises((RemoteCallError, TimeoutError)):
        with lzy.workflow("wf"):
            r = slow(1)
            # force barrier with a short client timeout → client stops graph
            lzy.runtime._graph_timeout_s = 0.3
            _ = r + 1
    # graph must terminate promptly (stopped), not run the full 5s op
    assert time.time() - t0 < 4.0


def test_per_user_task_limit(tmp_path):
    """Cross-graph per-user cap (reference TasksSchedulerImpl limits): a user
    with limit 2 never has more than 2 tasks executing at once."""
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
    c.graph_executor.max_running_tasks_per_user = 2
    CONCURRENCY["now"] = 0
    CONCURRENCY["peak"] = 0
    try:
        lzy = c.lzy()
        with lzy.workflow("limited"):
            results = [tracked_sleep(i) for i in range(6)]
            total = sum(int(r) for r in results)
        assert total == 15
        assert CONCURRENCY["peak"] <= 2, CONCURRENCY
    finally:
        c.shutdown()


@op
def read_env_var() -> str:
    import os

    return os.environ.get("LZY_TEST_FLAVOR", "unset")


def test_env_vars_applied_to_op(cluster):
    """Call-level env_vars reach the op's environment and are restored after
    (reference: worker sets the op process env)."""
    import os

    from lzy_tpu import env_vars

    lzy = cluster.lzy()
    with lzy.workflow("env-wf", env=env_vars(LZY_TEST_FLAVOR="vanilla")):
        assert str(read_env_var()) == "vanilla"
    assert os.environ.get("LZY_TEST_FLAVOR") is None  # restored


def test_failed_graph_releases_user_slots(tmp_path):
    """A failed graph must release its admitted per-user slots, or the user
    is pinned at their limit forever."""
    c = InProcessCluster(db_path=str(tmp_path / "meta.db"))
    c.graph_executor.max_running_tasks_per_user = 2
    try:
        lzy = c.lzy()

        @op
        def die() -> int:
            raise RuntimeError("boom")

        from lzy_tpu.core.workflow import RemoteCallError

        with pytest.raises(RemoteCallError):
            with lzy.workflow("fails"):
                r = die()
                _ = r + 1
        # user must be back under the limit: a fresh graph still runs
        with lzy.workflow("after"):
            assert inc(1) == 2
        assert c.graph_executor._user_running.get("test-user", 0) == 0
    finally:
        c.shutdown()


def test_cpu_provisioning_picks_cpu_pool(cluster):
    lzy = cluster.lzy()
    with lzy.workflow("wf"):
        r = inc(1)  # default provisioning
        assert r == 2
        # the VM created for the default op must come from the small CPU pool
        # (waste-minimizing resolve), even with TPU pools available; check
        # before workflow teardown reaps the session's VMs
        pools = {v.pool_label for v in cluster.allocator.vms()}
        assert pools == {"cpu-small"}


def test_background_gc_reaps_idle_vms_and_stale_executions():
    """GarbageCollector-timer parity: a cluster with gc_period_s reaps
    idle-expired VMs and abandoned executions without manual gc_tick calls."""
    cluster = InProcessCluster(
        storage_uri="mem://gc-timer",
        gc_period_s=0.2,
        execution_ttl_s=1.0,
    )
    lzy = cluster.lzy()
    try:
        # a workflow left ACTIVE (no finish) with a short-idle session VM
        with lzy.workflow("gc-wf"):
            assert int(inc(1)) == 2
        # shrink the session idle timeout so the cached VM expires fast
        for session in cluster.allocator._sessions.values():
            session.idle_timeout_s = 0.3
        from lzy_tpu import __version__

        exec_id = cluster.workflow_service.start_workflow(
            "gc-user", "abandoned", "mem://gc-timer",
            client_version=__version__)
        deadline = time.time() + 15
        while time.time() < deadline:
            vms_gone = cluster.allocator.vms() == []
            doc = cluster.store.kv_get("executions", exec_id)
            exec_reaped = doc is not None and doc.get("status") != "ACTIVE"
            if vms_gone and exec_reaped:
                break
            time.sleep(0.1)
        assert cluster.allocator.vms() == []
        doc = cluster.store.kv_get("executions", exec_id)
        assert doc.get("status") != "ACTIVE"
    finally:
        cluster.shutdown()
