"""Serving-path tests: batched prefill parity and continuous batching.

The batched prefill is an optimization with an in-tree oracle — the
original one-device-call-per-token loop is kept as ``prefill="sequential"``
— so parity is asserted token-for-token, greedy AND sampled (the batched
path must advance the rng stream in lockstep with the oracle's per-token
sample-and-discard). The engine tests drive ``InferenceEngine.step()``
synchronously so admission order is deterministic: requests join a LIVE
decode batch mid-flight, leave on completion, and each one's tokens must
match a solo ``generate()`` run bit-for-bit (any cross-request leakage
through the shared slot cache would break that).
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lzy_tpu.models import llama, unbox
from lzy_tpu.models.generate import generate, prefill_plan
from lzy_tpu.models.llama import LlamaConfig
from lzy_tpu.serving import AdmissionError, InferenceEngine


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny(vocab_size=64)
    boxed, _ = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, unbox(boxed)


class TestPrefillPlan:
    def test_pass_count_and_coverage(self):
        for t0 in (1, 5, 8, 13, 64, 200):
            plan = prefill_plan(t0, chunk=64, max_seq_len=256)
            assert len(plan) <= math.ceil(t0 / 64)
            assert sum(take for _, take, _ in plan) == t0
            starts = [start for start, _, _ in plan]
            assert starts == sorted(starts)

    def test_padded_write_never_spills_past_max_seq_len(self):
        # a prompt ending near the cache edge must not pad past it:
        # dynamic_update_slice would clamp the start and clobber real rows
        plan = prefill_plan(250, chunk=64, max_seq_len=256)
        for start, take, width in plan:
            assert take <= width
            assert start + width <= 256

    def test_bounded_shape_set(self):
        widths = {w for t0 in range(1, 200)
                  for _, _, w in prefill_plan(t0, chunk=64, max_seq_len=512)}
        assert len(widths) <= 5  # buckets 8/16/32/64 — not one shape per t0


class TestBatchedPrefillParity:
    @pytest.mark.parametrize("t0", [1, 3, 11, 40])
    def test_greedy_matches_sequential_oracle(self, tiny_model, t0):
        cfg, params = tiny_model
        prompt = jax.random.randint(
            jax.random.PRNGKey(3), (2, t0), 0, cfg.vocab_size)
        ref = generate(cfg, params, prompt, max_new_tokens=6,
                       prefill="sequential")
        out = generate(cfg, params, prompt, max_new_tokens=6,
                       prefill="batched", prefill_chunk=8)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    @pytest.mark.parametrize("t0", [3, 11])
    def test_sampled_matches_sequential_oracle(self, tiny_model, t0):
        cfg, params = tiny_model
        prompt = jax.random.randint(
            jax.random.PRNGKey(4), (2, t0), 0, cfg.vocab_size)
        kw = dict(max_new_tokens=6, temperature=0.8, top_k=20)
        ref = generate(cfg, params, prompt, rng=jax.random.PRNGKey(7),
                       prefill="sequential", **kw)
        out = generate(cfg, params, prompt, rng=jax.random.PRNGKey(7),
                       prefill="batched", prefill_chunk=8, **kw)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_rejects_unknown_prefill_mode(self, tiny_model):
        cfg, params = tiny_model
        with pytest.raises(ValueError, match="prefill"):
            generate(cfg, params, jnp.zeros((1, 2), jnp.int32),
                     max_new_tokens=1, prefill="turbo")


class TestEarlyExit:
    def test_early_exit_output_identical(self, tiny_model):
        """Pick whatever token greedy decode emits first and declare it
        eos: every sequence is then done after one step, and the
        early-exit path must still return the exact padded output the
        full-length loop does."""
        cfg, params = tiny_model
        prompt = jnp.array([[5, 9, 3], [7, 2, 8]], jnp.int32)
        probe = generate(cfg, params, prompt, max_new_tokens=1)
        eos = int(probe[0, -1])
        full = generate(cfg, params, prompt, max_new_tokens=32,
                        eos_token=eos, eos_check_every=0)
        early = generate(cfg, params, prompt, max_new_tokens=32,
                         eos_token=eos, eos_check_every=4)
        np.testing.assert_array_equal(np.asarray(early), np.asarray(full))
        # row 0 hit eos immediately, so its tail is pure eos padding
        assert np.all(np.asarray(early)[0, 3:] == eos)

    def test_early_exit_skips_device_steps(self, tiny_model, monkeypatch):
        cfg, params = tiny_model
        prompt = jnp.array([[5, 9, 3]], jnp.int32)
        eos = int(generate(cfg, params, prompt, max_new_tokens=1)[0, -1])
        calls = {"n": 0}
        orig = jax.jit

        def counting_jit(fn, **kw):
            jitted = orig(fn, **kw)

            def wrapper(*a, **k):
                calls["n"] += 1
                return jitted(*a, **k)

            return wrapper

        monkeypatch.setattr(jax, "jit", counting_jit)
        out = generate(cfg, params, prompt, max_new_tokens=64,
                       eos_token=eos, eos_check_every=2)
        assert out.shape == (1, 3 + 64)
        # prefill chunk + the eos check window — nowhere near 64 steps
        assert calls["n"] < 16


def _oracle_tokens(cfg, params, prompt_ids, n):
    """Solo greedy generate() continuation for one prompt (generated ids
    only) — what the engine must reproduce for that request regardless of
    what else shares the decode batch."""
    out = generate(cfg, params, jnp.asarray([prompt_ids], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0, len(prompt_ids):].tolist()


class TestInferenceEngine:
    def test_staggered_requests_share_the_decode_batch(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=2)
        a = eng.submit([5, 9, 3], max_new_tokens=12)
        eng.step()            # admits A (prefill emits token 1) + 1 decode
        eng.step()
        assert not a.done and len(a.tokens) >= 2
        # B arrives mid-decode: it must start generating on the very next
        # step, not after A drains
        b = eng.submit([7, 2, 8, 1, 4], max_new_tokens=4)
        eng.step()
        assert len(b.tokens) >= 1, "B waited for the running batch to drain"
        assert not a.done, "A should still be mid-flight when B joins"
        for _ in range(40):
            if a.done and b.done:
                break
            eng.step()
        assert a.result(0) == _oracle_tokens(cfg, params, a.prompt, 12)
        assert b.result(0) == _oracle_tokens(cfg, params, b.prompt, 4)

    def test_freed_slot_is_reused_without_leakage(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        a = eng.submit([5, 9, 3], max_new_tokens=3)
        for _ in range(10):
            if a.done:
                break
            eng.step()
        assert a.done
        # C lands in the slot A just vacated; a stale index or unmasked
        # cache row from A would corrupt C's continuation
        c = eng.submit([7, 2, 8, 1], max_new_tokens=5)
        for _ in range(10):
            if c.done:
                break
            eng.step()
        assert c.result(0) == _oracle_tokens(cfg, params, c.prompt, 5)

    def test_eos_frees_the_slot(self, tiny_model):
        cfg, params = tiny_model
        prompt = [5, 9, 3]
        first = _oracle_tokens(cfg, params, prompt, 1)[0]
        eng = InferenceEngine(cfg, params, slots=2, eos_token=first)
        r = eng.submit(prompt, max_new_tokens=16)
        eng.step()
        assert r.done and r.result(0) == [first]
        assert eng.stats().busy == 0

    def test_cancelled_request_frees_its_slot(self, tiny_model):
        """An abandoned waiter (client timeout) must not keep burning
        decode steps: a cancelled slot-resident request is reaped at the
        next scheduling round, a cancelled queued one is dropped at pop."""
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        a = eng.submit([5, 9, 3], max_new_tokens=50)
        queued = eng.submit([1, 2], max_new_tokens=50)
        eng.step()
        assert eng.stats().busy == 1
        a.cancel()
        queued.cancel()
        live = eng.submit([7, 2, 8], max_new_tokens=4)
        for _ in range(10):
            if live.done:
                break
            eng.step()
        assert a.done and a.error == "cancelled"
        assert queued.done and queued.error == "cancelled"
        n_before = len(a.tokens)
        eng.step()
        assert len(a.tokens) == n_before  # no tokens after cancellation
        assert live.result(0) == _oracle_tokens(cfg, params, live.prompt, 4)

    def test_admission_backpressure(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1, max_queue=1)
        eng.submit([1, 2], max_new_tokens=2)
        with pytest.raises(AdmissionError):
            eng.submit([3, 4], max_new_tokens=2)

    def test_invalid_requests_rejected(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        with pytest.raises(ValueError, match="non-empty|empty"):
            eng.submit([], max_new_tokens=2)
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit([1] * 10, max_new_tokens=cfg.max_seq_len)

    def test_background_loop_and_stats(self, tiny_model):
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=2).start()
        try:
            reqs = [eng.submit([3 + i, 5, 7], max_new_tokens=4)
                    for i in range(3)]
            outs = [r.result(timeout=60) for r in reqs]
        finally:
            eng.close()
        for i, out in enumerate(outs):
            assert out == _oracle_tokens(cfg, params, [3 + i, 5, 7], 4)
        s = eng.stats()
        assert s.requests_finished == 3
        assert s.tokens_generated == 12

    def test_submit_after_close_fails_fast(self, tiny_model):
        """Shutdown stops the engine before the RPC server, so a submit can
        arrive in the gap: it must get retryable backpressure immediately,
        not sit in a queue no loop will ever drain until the RPC timeout."""
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1).start()
        eng.close()
        with pytest.raises(AdmissionError, match="shut down"):
            eng.submit([1, 2], max_new_tokens=2)

    def test_loop_death_fails_outstanding_requests(self, tiny_model,
                                                   monkeypatch):
        """An engine-fatal step() error (device OOM, poisoned compile) must
        fail every outstanding request and refuse new admissions — not die
        silently while waiters burn their full timeouts."""
        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=1)
        req = eng.submit([5, 9, 3], max_new_tokens=8)
        monkeypatch.setattr(
            eng, "step",
            lambda: (_ for _ in ()).throw(RuntimeError("device on fire")))
        eng.start()
        with pytest.raises(RuntimeError, match="engine loop died"):
            req.result(timeout=30)
        with pytest.raises(AdmissionError):
            eng.submit([1], max_new_tokens=1)

    def test_metrics_exported_in_registry(self, tiny_model):
        from lzy_tpu.utils.metrics import REGISTRY

        cfg, params = tiny_model
        eng = InferenceEngine(cfg, params, slots=2)
        r = eng.submit([5, 9], max_new_tokens=3)
        while not r.done:
            eng.step()
        text = REGISTRY.exposition()
        for name in ("lzy_inference_ttft_seconds",
                     "lzy_inference_tokens_total",
                     "lzy_inference_slots_busy",
                     "lzy_inference_queue_depth",
                     "lzy_inference_tokens_per_s"):
            assert name in text


class TestInferenceRpc:
    def test_generate_and_stats_over_the_control_plane(
            self, tiny_model, tmp_path):
        from lzy_tpu.rpc import RpcInferenceClient
        from lzy_tpu.service import InProcessCluster
        from lzy_tpu.service.inference import InferenceService

        cfg, params = tiny_model
        engine = InferenceEngine(cfg, params, slots=2).start()
        cluster = InProcessCluster(
            db_path=str(tmp_path / "meta.db"),
            storage_uri=f"file://{tmp_path}/storage",
            worker_mode="process",
            inference_service=InferenceService(engine, model_name="tiny"),
        )
        try:
            client = RpcInferenceClient(cluster.rpc_server.address)
            try:
                res = client.generate([5, 9, 3], max_new_tokens=4,
                                      timeout_s=60)
                assert res["model"] == "tiny"
                assert res["tokens"] == _oracle_tokens(
                    cfg, params, [5, 9, 3], 4)
                assert res["ttft_ms"] is not None
                stats = client.stats()
                assert stats["requests_finished"] >= 1
            finally:
                client.close()
        finally:
            cluster.shutdown()
