"""Control-plane leader lease over the shared metadata store (VERDICT r3
#9 / missing #3).

The reference runs every service replicated against Postgres with
leader-leased GC (``lzy/lzy-service/.../gc/GarbageCollector.java:21``);
the single-store analog: a CAS lease row makes exactly one control-plane
process the writer. A second plane on the same store fails loudly at
boot (never corrupts), takes over after a crash once the lease lapses,
and immediately after a clean shutdown (release on exit).
"""

import time

import pytest

from lzy_tpu.durable.store import OperationStore
from lzy_tpu.service import InProcessCluster
from lzy_tpu.service.harness import LeaderLeaseHeld


from conftest import durable_store_backends, make_durable_store


@pytest.fixture(params=durable_store_backends())
def lease_backend(request):
    return request.param


class TestLeaseStore:
    def test_acquire_renew_release(self, tmp_path, lease_backend):
        s = make_durable_store(lease_backend, str(tmp_path / "m.db"))
        assert s.try_acquire_lease("gc", "a", 30)
        assert s.lease_holder("gc")[0] == "a"
        assert not s.try_acquire_lease("gc", "b", 30)   # held by a
        assert s.try_acquire_lease("gc", "a", 30)        # re-entrant for a
        assert s.renew_lease("gc", "a", 30)
        assert not s.renew_lease("gc", "b", 30)          # b never owned it
        s.release_lease("gc", "a")
        assert s.lease_holder("gc") is None
        assert s.try_acquire_lease("gc", "b", 30)
        s.close()

    def test_expired_lease_is_taken_over(self, tmp_path, lease_backend):
        s = make_durable_store(lease_backend, str(tmp_path / "m.db"))
        assert s.try_acquire_lease("gc", "a", 0.05)
        time.sleep(0.1)
        assert s.lease_holder("gc") is None              # lapsed
        assert s.try_acquire_lease("gc", "b", 30)        # crash takeover
        assert not s.renew_lease("gc", "a", 30)          # a lost it
        s.close()

    def test_cross_process_visibility(self, tmp_path, lease_backend):
        """Two store handles on one file (the two-process topology)."""
        path = str(tmp_path / "m.db")
        s1 = make_durable_store(lease_backend, path)
        s2 = make_durable_store(lease_backend, path, fresh=False)
        assert s1.try_acquire_lease("gc", "a", 30)
        assert not s2.try_acquire_lease("gc", "b", 30)
        assert s2.lease_holder("gc")[0] == "a"
        s1.close()
        s2.close()


class TestControlPlaneSingleWriter:
    def test_second_plane_on_same_store_fails_loudly(self, tmp_path):
        db = str(tmp_path / "meta.db")
        first = InProcessCluster(db_path=db)
        try:
            with pytest.raises(LeaderLeaseHeld, match="already driven"):
                InProcessCluster(db_path=db)
        finally:
            first.shutdown()

    def test_clean_shutdown_hands_over_immediately(self, tmp_path):
        db = str(tmp_path / "meta.db")
        first = InProcessCluster(db_path=db)
        first.shutdown()                    # releases the lease
        second = InProcessCluster(db_path=db)
        second.shutdown()

    def test_crashed_plane_is_replaced_after_ttl(self, tmp_path):
        db = str(tmp_path / "meta.db")
        first = InProcessCluster(db_path=db, leader_lease_ttl_s=0.2)
        # simulate a crash: kill the renewal without releasing
        first._lease_stop.set()
        first._lease_thread.join(2)
        time.sleep(0.3)                     # let the lease lapse
        second = InProcessCluster(db_path=db)
        try:
            # the dead plane's renewal would now fail (CAS lost)
            assert not first.store.renew_lease(
                "control-plane", first._lease_owner, 30)
        finally:
            second.shutdown()
            # first was "crashed"; close its store handle directly
            first._lease_stop = None        # already stopped
            first.shutdown()

    def test_memory_stores_are_exempt(self):
        """:memory: stores are process-private — no lease, no conflict."""
        a = InProcessCluster()
        b = InProcessCluster()
        a.shutdown()
        b.shutdown()

    def test_lost_lease_fences_the_plane(self, tmp_path):
        """Detection without enforcement would be split-brain: a plane
        whose renewal loses the CAS must stop mutating (RPC + executor +
        GC go dark), not just log."""
        db = str(tmp_path / "meta.db")
        c = InProcessCluster(db_path=db, leader_lease_ttl_s=0.3)
        try:
            # simulate the stall+takeover: the lease changes hands
            c.store.release_lease("control-plane", c._lease_owner)
            assert c.store.try_acquire_lease("control-plane", "usurper", 30)
            deadline = time.time() + 5
            while time.time() < deadline and not c.fenced:
                time.sleep(0.05)
            assert c.fenced, "renewal loss did not fence the plane"
            # the executor is down: durable submissions are refused
            with pytest.raises(Exception):
                c.executor.submit("post-fence", "noop", {})
        finally:
            c.shutdown()
